"""Cross-process serve federation: the control plane (ISSUE 15).

The serve pool (PR 8) is N replica threads in ONE process — a single
process death takes the whole plane down.  :class:`FederationPlane`
closes that gap: it supervises N worker PROCESSES (each running a full
:class:`rca_tpu.serve.loop.ServeLoop` or :class:`rca_tpu.serve.pool.
ServePool` over its own devices, bootstrapped through the
:mod:`rca_tpu.parallel.distributed` seam so a cross-host mesh is a
rules change, not a rewrite), connected over the length-prefixed wire
protocol in :mod:`rca_tpu.serve.fedwire`.

**Liveness is a lease, not a socket.**  Each worker's hello is answered
with a lease (``ttl = heartbeat_s × lease_misses``); every heartbeat
renews it.  One late heartbeat never kills a worker; ``lease_misses``
consecutive misses expire the lease and the worker is marked dead even
if its socket is still open — which is exactly the ``worker_hang``
failure (a wedged process holds its fds).  A worker whose process dies
outright (``process_kill``) is detected faster, at socket EOF.  A
worker presenting a STALE lease (it hung, was declared dead, then woke
up) is rejected and must re-hello for a fresh lease — the rejoin path.

**Exactly-once across process death.**  Every routed request lives in
the coordinator's pending table, keyed by request id and OWNED by one
worker.  On worker death the entries it owned are reclaimed and
re-placed on survivors (drain-and-reroute); a late answer from the
dead worker no longer matches the owner and is dropped as a STALE
response — counted in ``stale_responses``, never delivered.  Delivery
itself goes through the pool's :class:`rca_tpu.serve.replica.
CompletionSink`, so ``double_completions`` stays 0 by construction and
is asserted 0 under concurrent kill chaos (tests, selftest, bench).

**Routing is consistent hashing on the graph digest** (rendezvous /
highest-random-weight): a graph key maps to the same worker wherever it
is submitted, so hot graphs keep their resident delta-scatter path
across processes; when one of N workers dies, ONLY the keys it owned
move (bounded handoff — property-tested).  Stickiness is best-effort:
past the per-worker outstanding window (``RCA_FED_WINDOW``) a request
spills to the next worker on its ring so one hot bucket cannot wedge
the plane behind one process.

**The fleet is elastic** (ISSUE 16, elasticmesh).  An
:class:`rca_tpu.serve.autoscale.AutoscaleController` attached to the
plane spawns workers through the procs seam and retires them through
:meth:`FederationPlane.drain_worker` — the worker leaves the ring
first, finishes its in-flight work, answers ``drained``, and only then
is its process terminated, so a scale-down is invisible to the
exactly-once contract (and never misclassified as a fault).  Placement
is shape-aware on top of rendezvous: hello frames carry each worker's
kernel-registry and device-memory summaries, and for graph buckets the
``PLACEMENT_RULES`` table marks as informed-routable the router
prefers the worker with the winning per-shape timing (headroom as the
tie-break), falling back to pure rendezvous order when nobody has
data.  ``advertise_host`` separates the bind address from the address
spawned/external workers dial — the multi-host deploy seam
(SERVING.md §Deploy).

Concurrency discipline (gravelock): all threads named via
:mod:`rca_tpu.util.threads`; ``FederationPlane._lock`` guards the
worker table, ring, and pending map and is never held across a socket
write that can block long (sends are to local buffers; the frame lock
inside :class:`FrameConn` is a leaf).  Lock order:
``FederationPlane._lock`` → ``FrameConn._wlock``;
``CompletionSink._lock`` / ``ServeMetrics._lock`` are leaves.  Timing
goes through the injectable ``clock`` seam (nondet-discipline — the
whole serve package is replay-covered).
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from rca_tpu.config import (
    ServeConfig,
    fed_heartbeat_s,
    fed_lease_misses,
    fed_window,
    fed_workers,
)
from rca_tpu.observability.spans import default_tracer
from rca_tpu.serve.autoscale import PLACEMENT_RULES, shape_tier_ms
from rca_tpu.serve.fedwire import (
    FrameConn,
    FrameError,
    PROTO,
    WireResult,
    encode_request,
)
from rca_tpu.serve.queue import RequestQueue
from rca_tpu.serve.replica import CompletionSink
from rca_tpu.serve.request import ServeRequest, ServeResponse
from rca_tpu.serve.metrics import ServeMetrics
from rca_tpu.util.net import bound_address, make_server_socket
from rca_tpu.util.threads import make_lock, spawn

#: the federation's fault classes — what the chaos gate must observe
FED_FAULT_CLASSES = ("process_kill", "worker_hang", "coordinator_partition")

#: the ingest-fleet fault class (ISSUE 17): an ingest worker's socket
#: EOF — its cluster mirrors move to rendezvous survivors with a fresh
#: ownership epoch, and the dead owner's in-flight tick stats are
#: dropped as epoch-stale (never double-applied)
INGEST_FAULT_CLASS = "ingest_death"

#: router idle park while nothing is queued / routable
_ROUTE_IDLE_S = 0.02

#: events kept for observability (oldest dropped)
_EVENT_CAP = 512


# ---------------------------------------------------------------------------
# Lease-based liveness
# ---------------------------------------------------------------------------


class Lease:
    """One worker's liveness lease: granted at hello, renewed by every
    heartbeat, expired after ``ttl_s`` without one."""

    __slots__ = ("lease_id", "worker_id", "granted_at", "renewed_at",
                 "ttl_s", "renewals")

    def __init__(self, worker_id: int, now: float, ttl_s: float,
                 lease_id: Optional[str] = None):
        self.lease_id = lease_id or uuid.uuid4().hex[:16]
        self.worker_id = int(worker_id)
        self.granted_at = now
        self.renewed_at = now
        self.ttl_s = float(ttl_s)
        self.renewals = 0

    def expires_at(self) -> float:
        return self.renewed_at + self.ttl_s

    def expired(self, now: float) -> bool:
        return now >= self.expires_at()


class LeaseTable:
    """The liveness source of truth, on an injectable clock.

    ``ttl_s = heartbeat_s × lease_misses``: missing ONE heartbeat keeps
    a worker alive (the miss-one-keep-alive property the tests pin);
    missing ``lease_misses`` in a row expires it.  A renewal carrying a
    lease id that is not the CURRENT lease for that worker — the worker
    was declared dead and a fresh lease was (or will be) minted — is
    refused: the holder must re-hello, which is what makes a recovered
    hung worker's rejoin explicit instead of a silent resurrection."""

    def __init__(self, heartbeat_s: float, lease_misses: int,
                 clock: Callable[[], float] = time.monotonic):
        if heartbeat_s <= 0:
            raise ValueError(f"heartbeat_s must be > 0, got {heartbeat_s}")
        if lease_misses < 2:
            raise ValueError(
                f"lease_misses must be >= 2 (one late heartbeat must "
                f"never kill a worker), got {lease_misses}"
            )
        self.heartbeat_s = float(heartbeat_s)
        self.ttl_s = float(heartbeat_s) * int(lease_misses)
        self.clock = clock
        self._lock = make_lock("LeaseTable._lock")
        self._leases: Dict[int, Lease] = {}

    def grant(self, worker_id: int, now: Optional[float] = None) -> Lease:
        """A FRESH lease (any previous lease for this worker becomes
        stale the moment this one exists)."""
        if now is None:
            now = self.clock()
        lease = Lease(worker_id, now, self.ttl_s)
        with self._lock:
            self._leases[int(worker_id)] = lease
        return lease

    def renew(self, worker_id: int, lease_id: str,
              now: Optional[float] = None) -> bool:
        """Heartbeat renewal; False when the lease is stale (not the
        current one), unknown, or already expired — the worker must
        re-hello."""
        if now is None:
            now = self.clock()
        with self._lock:
            lease = self._leases.get(int(worker_id))
            if (lease is None or lease.lease_id != lease_id
                    or lease.expired(now)):
                return False
            lease.renewed_at = now
            lease.renewals += 1
            return True

    def alive(self, worker_id: int, now: Optional[float] = None) -> bool:
        if now is None:
            now = self.clock()
        with self._lock:
            lease = self._leases.get(int(worker_id))
            return lease is not None and not lease.expired(now)

    def expired_workers(
        self, now: Optional[float] = None
    ) -> List[Tuple[int, float]]:
        """``(worker_id, overdue_s)`` for every held lease past its TTL
        — ``overdue_s`` is the detection lag the bench reports."""
        if now is None:
            now = self.clock()
        with self._lock:
            return [
                (wid, now - lease.expires_at())
                for wid, lease in self._leases.items()
                if lease.expired(now)
            ]

    def revoke(self, worker_id: int) -> None:
        with self._lock:
            self._leases.pop(int(worker_id), None)


# ---------------------------------------------------------------------------
# Consistent-hash routing (rendezvous)
# ---------------------------------------------------------------------------


class HashRing:
    """Rendezvous (highest-random-weight) hashing over worker ids.

    Chosen over a vnode ring for its EXACT remap property: when a node
    leaves, the only keys that move are the keys it owned — survivors'
    keys never reshuffle, which is the bounded-handoff contract the
    resident delta path depends on (a surviving worker's hot graphs
    stay hot through any topology change)."""

    def __init__(self) -> None:
        self._nodes: Tuple[int, ...] = ()

    def add(self, node: int) -> None:
        if int(node) not in self._nodes:
            self._nodes = tuple(sorted(self._nodes + (int(node),)))

    def remove(self, node: int) -> None:
        self._nodes = tuple(n for n in self._nodes if n != int(node))

    @property
    def nodes(self) -> Tuple[int, ...]:
        return self._nodes

    @staticmethod
    def _score(node: int, key: str) -> int:
        return int.from_bytes(
            hashlib.sha1(f"{node}|{key}".encode("utf-8")).digest()[:8],
            "big",
        )

    def ranked(self, key: str) -> List[int]:
        """All nodes, preference order for ``key`` (owner first; the
        tail is the deterministic spill order under saturation)."""
        return sorted(
            self._nodes, key=lambda n: self._score(n, key), reverse=True
        )

    def owner(self, key: str) -> Optional[int]:
        ranked = self.ranked(key)
        return ranked[0] if ranked else None


def graph_route_key(graph_key: Tuple) -> str:
    """The routing key for a request's shape bucket: the graph identity
    tuple, digest included — the same identity the dispatcher's
    prepared-graph cache is keyed by, so ring ownership and resident
    stickiness agree by construction."""
    return "/".join(str(p) for p in graph_key)


# ---------------------------------------------------------------------------
# Worker handles
# ---------------------------------------------------------------------------


def _parse_shape_summary(registry: Any) -> Dict[int, float]:
    """A hello frame's ``registry`` summary → ``{n_pad: winner_ms}``.
    Hellos from older workers (or fakes) omit it; malformed entries are
    dropped, never fatal — placement is an optimization, not a
    dependency."""
    out: Dict[int, float] = {}
    if not isinstance(registry, dict):
        return out
    for n_pad, t_ms in registry.items():
        try:
            key, val = int(n_pad), float(t_ms)
        except (TypeError, ValueError):
            continue
        if key > 0 and val >= 0.0:
            out[key] = val
    return out


def _parse_headroom(headroom: Any) -> Optional[int]:
    """A hello frame's ``headroom`` summary → device ``bytes_in_use``
    (LOWER = more headroom), or None when absent/malformed."""
    if not isinstance(headroom, dict):
        return None
    try:
        return int(headroom.get("bytes_in_use"))
    except (TypeError, ValueError):
        return None


class _WorkerHandle:
    """Coordinator-side state for one worker (connection + lease +
    outstanding accounting).  Mutated only under FederationPlane._lock
    except the FrameConn (its own write lock) and plain reads."""

    def __init__(self, worker_id: int):
        self.worker_id = int(worker_id)
        self.conn: Optional[FrameConn] = None
        self.lease: Optional[Lease] = None
        self.live = False
        self.proc = None                  # util.procs.WorkerProc | None
        self.hello: Dict[str, Any] = {}
        self.outstanding = 0
        self.partitioned_until = 0.0
        self.partition_dropped = 0
        self.served = 0
        self.state = "connecting"
        # elasticmesh: scale-down + placement state.  ``draining`` marks
        # an intentional retirement in progress (the worker is off the
        # ring, not routable, and its eventual EOF is NOT a fault);
        # ``shape_ms``/``mem_bytes`` are the hello frame's registry and
        # headroom summaries the placement rules read.
        self.draining = False
        self.shape_ms: Dict[int, float] = {}     # n_pad -> winner ms
        self.mem_bytes: Optional[int] = None
        # planetcap (ISSUE 17): worker class from the hello frame —
        # "serve" workers join the serve ring, "ingest" workers join the
        # ingest ring and own cluster capture mirrors instead
        self.role = "serve"

    def summary(self) -> Dict[str, Any]:
        return {
            "worker_id": self.worker_id,
            "role": self.role,
            "state": self.state,
            "live": self.live,
            "draining": self.draining,
            "outstanding": self.outstanding,
            "served": self.served,
            "pid": self.hello.get("pid"),
            "engine": self.hello.get("engine"),
            "lease_renewals": (
                self.lease.renewals if self.lease is not None else 0
            ),
            "shapes_known": len(self.shape_ms),
            "mem_bytes": self.mem_bytes,
        }


class _Pending:
    __slots__ = ("req", "worker_id", "sent_at", "moves")

    def __init__(self, req: ServeRequest, worker_id: int, sent_at: float):
        self.req = req
        self.worker_id = worker_id
        self.sent_at = sent_at
        self.moves = 0


# ---------------------------------------------------------------------------
# The control plane
# ---------------------------------------------------------------------------


class FederationPlane:
    """Coordinator for N worker processes behind one admission queue.

    Presents the same surface the gateway and ``ServeClient`` expect of
    a serving plane (``submit`` / ``clock`` / ``metrics`` / ``queue`` /
    ``start`` / ``stop``), so ``GatewayServer(plane)`` is the TLS+authn
    front door over a whole fleet.

    ``workers``: how many processes to spawn (via the
    :mod:`rca_tpu.util.procs` seam; each runs ``python -m
    rca_tpu.serve.worker`` connected back here).  ``spawn_workers=False``
    opens the control port without spawning — tests connect their own
    (fake or real) workers, and external workers on other hosts join the
    same way."""

    def __init__(
        self,
        workers: Optional[int] = None,
        config: Optional[ServeConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        host: str = "127.0.0.1",
        port: int = 0,
        heartbeat_s: Optional[float] = None,
        lease_misses: Optional[int] = None,
        window: Optional[int] = None,
        spawn_workers: bool = True,
        worker_env: Optional[Dict[str, str]] = None,
        store=None,
        tracer=None,
        steal: Optional[bool] = None,
        advertise_host: Optional[str] = None,
    ):
        self.config = config or ServeConfig.from_env()
        self.clock = clock
        self.n_workers = int(workers) if workers is not None else fed_workers()
        self.heartbeat_s = (
            float(heartbeat_s) if heartbeat_s is not None
            else fed_heartbeat_s()
        )
        self.lease_misses = (
            int(lease_misses) if lease_misses is not None
            else fed_lease_misses()
        )
        self.window = int(window) if window is not None else fed_window()
        self.steal = bool(self.config.steal if steal is None else steal)
        self.spawn_workers = bool(spawn_workers)
        self.worker_env = worker_env
        self.tracer = tracer if tracer is not None else default_tracer()
        self.metrics = ServeMetrics()
        self.queue = RequestQueue(self.config.queue_cap, clock=clock)
        self.sink = CompletionSink(
            self.metrics, clock, store=store, tracer=self.tracer,
        )
        self.leases = LeaseTable(
            self.heartbeat_s, self.lease_misses, clock=clock
        )
        self.ring = HashRing()
        # planetcap (ISSUE 17): the ingest worker class.  Cluster capture
        # mirrors are rendezvous-routed over THIS ring (``cid:digest``
        # keys), one owner per cluster; the cluster table is the
        # coordinator-side exactly-once arbiter for capture ticks
        # (epoch-stale and replayed tick stats are dropped, counted).
        self.ingest_ring = HashRing()
        self.clusters: Dict[str, Dict[str, Any]] = {}
        self.ingest_stale = 0
        self._lock = make_lock("FederationPlane._lock")
        self.workers: Dict[int, _WorkerHandle] = {}
        self._pending: Dict[str, _Pending] = {}
        self._overflow: "collections.deque[ServeRequest]" = (
            collections.deque()
        )
        self.events: List[Dict[str, Any]] = []
        self.stale_responses = 0
        self.reroutes = 0
        self._conn_counter = itertools.count()
        self._stop = threading.Event()
        self._ready = threading.Event()
        self._threads: List[threading.Thread] = []
        sock = make_server_socket("federation", host, port)
        self.host, self.port = bound_address(sock)
        self._server_sock = sock
        # multi-host (ISSUE 16): the address workers DIAL may differ
        # from the bind address (bind 0.0.0.0, advertise the host's
        # reachable IP); the attached autoscale controller registers
        # itself here so /healthz can report the elastic state
        self.advertise_host = advertise_host
        self.autoscaler = None

    # -- introspection --------------------------------------------------------
    @property
    def address(self) -> str:
        host = self.advertise_host if self.advertise_host else self.host
        return f"{host}:{self.port}"

    def live_workers(self) -> List[int]:
        with self._lock:
            return [w.worker_id for w in self.workers.values() if w.live]

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending) + len(self._overflow)

    def worker_table(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                self.workers[wid].summary()
                for wid in sorted(self.workers)
            ]

    def _event(self, kind: str, worker_id: Optional[int] = None,
               **extra: Any) -> None:
        with self._lock:
            self.events.append({
                "event": kind, "worker_id": worker_id,
                "t": self.clock(), **extra,
            })
            while len(self.events) > _EVENT_CAP:
                self.events.pop(0)

    def fault_classes_observed(self) -> List[str]:
        with self._lock:
            return sorted({
                e["class"] for e in self.events
                if e["event"] == "worker_down" and e.get("class")
            })

    # -- lifecycle ------------------------------------------------------------
    def start(self) -> "FederationPlane":
        self._stop.clear()
        self._threads = [
            spawn(self._accept_loop, name="rca-fed-accept", daemon=True),
            spawn(self._route_loop, name="rca-fed-route", daemon=True),
            spawn(self._monitor_loop, name="rca-fed-monitor", daemon=True),
        ]
        if self.spawn_workers:
            for i in range(self.n_workers):
                self.spawn_worker(i)
        return self

    def spawn_worker(self, worker_id: int, role: str = "serve"):
        """Spawn (or respawn) one worker process through the procs seam;
        it connects back to the control port and hellos.  ``role``
        selects the worker class (``"ingest"`` spawns a cluster-capture
        worker that joins the ingest ring instead of the serve ring)."""
        from rca_tpu.config import environ_copy
        from rca_tpu.util.procs import python_argv, spawn_worker

        env = environ_copy()
        if self.worker_env:
            env.update(self.worker_env)
        args = [
            "--connect", self.address,
            "--worker-id", str(worker_id),
        ]
        if role != "serve":
            args += ["--role", str(role)]
        proc = spawn_worker(
            f"fed-worker{worker_id}",
            python_argv("rca_tpu.serve.worker", *args),
            env=env,
        )
        with self._lock:
            handle = self.workers.setdefault(
                int(worker_id), _WorkerHandle(worker_id)
            )
            handle.proc = proc
        self._event("worker_spawned", worker_id, pid=proc.pid)
        return proc

    def wait_ready(self, n: Optional[int] = None,
                   timeout_s: float = 60.0) -> bool:
        """Block until ``n`` (default: all spawned) workers hold leases.
        False on timeout — callers decide whether a partial fleet is a
        failure (selftest) or a degraded start (demo)."""
        want = int(n) if n is not None else self.n_workers
        deadline = self.clock() + timeout_s
        while self.clock() < deadline:
            if len(self.live_workers()) >= want:
                return True
            if self._stop.wait(0.05):
                return False
        return len(self.live_workers()) >= want

    def stop(self, timeout: float = 15.0) -> None:
        deadline = self.clock() + timeout
        # drain: workers finish in flight, answer, and exit
        with self._lock:
            conns = [
                w.conn for w in self.workers.values()
                if w.live and w.conn is not None
            ]
        for conn in conns:
            conn.send({"t": "drain"})
        while self.pending_count() > 0 and self.clock() < deadline:
            if self._stop.wait(0.02):
                break
        self._stop.set()
        self.queue.kick()
        # complete everything still in the system — a stopped plane must
        # not leave submitters parked
        with self._lock:
            leftovers = [p.req for p in self._pending.values()]
            self._pending.clear()
            leftovers.extend(self._overflow)
            self._overflow.clear()
        while True:
            with self._lock:
                req = self.queue.pop()
            if req is None:
                break
            leftovers.append(req)
        for req in leftovers:
            self.sink.error(req, "federation stopped")
        with self._lock:
            handles = list(self.workers.values())
        for w in handles:
            if w.conn is not None:
                w.conn.close()
            if w.proc is not None:
                w.proc.terminate(grace_s=3.0)
        try:
            self._server_sock.close()
        except OSError:
            pass
        for t in self._threads:
            t.join(5.0)
        self._threads = []

    def __enter__(self) -> "FederationPlane":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- admission (ServeLoop/ServePool submit contract) ----------------------
    def submit(self, req: ServeRequest) -> bool:
        now = self.clock()
        if self.tracer.enabled and req.trace is None:
            req.trace = self.tracer.new_context(parent=req.trace_parent)
        if req.expired(now):
            self.sink.shed(req, detail="expired_at_admission")
            return False
        if not self.queue.submit(req):
            self.metrics.rejected(req.tenant)
            req.complete(ServeResponse(
                status="queue_full", request_id=req.request_id,
                tenant=req.tenant,
                detail=f"queue at capacity ({self.queue.cap})",
            ))
            return False
        self.metrics.submitted(req.tenant, len(self.queue))
        return True

    # -- chaos seams ----------------------------------------------------------
    def kill_worker(self, worker_id: int) -> bool:
        """SIGKILL one worker process (the ``process_kill`` fault; procs
        seam).  For fake/externally-connected workers the connection is
        severed instead — same failure shape at this layer."""
        with self._lock:
            w = self.workers.get(int(worker_id))
            proc = w.proc if w is not None else None
            conn = w.conn if w is not None else None
        if proc is not None:
            proc.kill()
            return True
        if conn is not None:
            conn.close()
            return True
        return False

    def hang_worker(self, worker_id: int, for_s: float) -> bool:
        """Tell one worker to stop heartbeating for ``for_s`` seconds
        while keeping its socket open — the ``worker_hang`` fault."""
        with self._lock:
            w = self.workers.get(int(worker_id))
            conn = w.conn if w is not None and w.live else None
        return conn is not None and conn.send(
            {"t": "hang", "for_s": float(for_s)}
        )

    def partition(self, worker_id: int, for_s: float) -> bool:
        """Drop every frame from (and ack to) one worker for ``for_s``
        seconds — the ``coordinator_partition`` fault: both sides are
        healthy, the control channel is not."""
        now = self.clock()
        with self._lock:
            w = self.workers.get(int(worker_id))
            if w is None:
                return False
            w.partitioned_until = now + float(for_s)
        self._event("partition_start", worker_id, for_s=float(for_s))
        return True

    # -- elastic scale-down (drain-and-retire, ISSUE 16) ----------------------
    def drain_worker(self, worker_id: int) -> bool:
        """Begin one worker's intentional retirement: off the ring first
        (no new routes), then a ``drain`` frame — the worker finishes
        its in-flight work, answers ``drained``, and
        :meth:`_scaledown_complete` retires it.  False when the worker
        is not live (or already draining)."""
        with self._lock:
            w = self.workers.get(int(worker_id))
            if w is None or not w.live or w.draining or w.conn is None:
                return False
            w.draining = True
            w.state = "draining"
            self.ring.remove(worker_id)
            conn = w.conn
        self._event("drain_started", worker_id)
        if not conn.send({"t": "drain"}):
            # died before the frame landed: the conn loop's EOF path
            # handles it as a fault; nothing to retire politely here
            return True
        return True

    def _scaledown_complete(self, w: _WorkerHandle) -> None:
        """Finish one intentional retirement (the ``drained`` ack).
        ``live`` drops FIRST, so the socket EOF (and the monitor's
        dead-proc sweep) that follow hit :meth:`_worker_down`'s
        not-live early-return — a scale-down must never be counted as a
        ``process_kill``.  Anything still pending on the worker (a race
        with the router) reroutes through overflow."""
        with self._lock:
            if not w.live:
                return
            w.live = False
            w.draining = False
            w.state = "drained"
            self.ring.remove(w.worker_id)
            reclaimed = [
                p for p in self._pending.values()
                if p.worker_id == w.worker_id
            ]
            for p in reclaimed:
                del self._pending[p.req.request_id]
            w.outstanding = 0
            proc = w.proc
            for p in reclaimed:
                p.moves += 1
                self.reroutes += 1
                self._overflow.append(p.req)
        self.leases.revoke(w.worker_id)
        self._event("worker_scaled_down", w.worker_id,
                    rerouted=len(reclaimed))
        if proc is not None:
            proc.terminate(grace_s=3.0)
        self.queue.kick()

    def scale_status(self) -> Dict[str, Any]:
        """The autoscale controller's view of the fleet in one lock
        acquisition: routable workers, retirements in progress, the
        per-worker outstanding map (the scale-down victim policy), and
        the next NEVER-REUSED worker id (reusing a retired id would
        alias its late, stale responses onto a fresh worker)."""
        with self._lock:
            live = sorted(
                w.worker_id for w in self.workers.values()
                if w.live and not w.draining
            )
            draining = sorted(
                w.worker_id for w in self.workers.values()
                if w.live and w.draining
            )
            outstanding = {
                w.worker_id: w.outstanding
                for w in self.workers.values() if w.live
            }
            next_id = max(self.workers) + 1 if self.workers else 0
        return {
            "live": live, "draining": draining,
            "outstanding": outstanding, "next_id": next_id,
        }

    # -- connection handling --------------------------------------------------
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                client, _addr = self._server_sock.accept()
            except OSError:
                return   # socket closed = shutdown
            conn = FrameConn(client, name="fed-coord")
            spawn(
                self._conn_loop,
                name=f"rca-fed-conn{next(self._conn_counter)}",
                daemon=True, args=(conn,),
            )

    def _register(self, conn: FrameConn,
                  hello: Dict[str, Any]) -> Optional[_WorkerHandle]:
        """Handle one hello: proto + lease staleness checks, then grant.
        Returns the registered handle, or None when rejected."""
        if int(hello.get("proto", -1)) != PROTO:
            conn.send({"t": "reject", "reason": "bad_proto"})
            return None
        worker_id = int(hello.get("worker_id", -1))
        if worker_id < 0:
            conn.send({"t": "reject", "reason": "bad_worker_id"})
            return None
        presented = hello.get("lease_id")
        if presented is not None and not self.leases.renew(
            worker_id, presented
        ):
            # rejoin with a stale lease: refused — the worker re-hellos
            # WITHOUT a lease and gets a fresh grant (tested)
            self._event("stale_lease_rejected", worker_id)
            conn.send({"t": "reject", "reason": "stale_lease"})
            return None
        lease = self.leases.grant(worker_id)
        with self._lock:
            w = self.workers.setdefault(worker_id, _WorkerHandle(worker_id))
            # a rejoin is any hello from a worker that held a lease
            # before — whether on a fresh connection (restart) or the
            # SAME one (a hung/partitioned worker whose stale lease was
            # just rejected)
            rejoin = w.lease is not None
            old_conn = (
                w.conn if (w.conn is not None and w.conn is not conn)
                else None
            )
            w.conn = conn
            w.lease = lease
            w.hello = dict(hello)
            w.live = True
            w.partitioned_until = 0.0
            # a hello on a NEW connection is a new process: any drain
            # sent to the old one died with its socket, so this worker
            # is volunteering back in.  On the SAME connection (a hung/
            # partitioned worker re-helloing past a stale lease) a
            # drain already sent is still in that process's inbox —
            # KEEP the draining intent, or a stale-heartbeat re-hello
            # racing a scale-down wipes it and the retirement never
            # completes (the scaling_storm rejoin-vs-drain race)
            if old_conn is not None:
                w.draining = False
            w.state = "draining" if w.draining else "live"
            w.shape_ms = _parse_shape_summary(hello.get("registry"))
            w.mem_bytes = _parse_headroom(hello.get("headroom"))
            w.role = str(hello.get("role") or "serve")
            if not w.draining:
                # worker class decides the ring: ingest workers own
                # cluster mirrors, never serve traffic
                (self.ingest_ring if w.role == "ingest"
                 else self.ring).add(worker_id)
        if old_conn is not None:
            old_conn.close()
        self._event("rejoin" if rejoin else "worker_joined", worker_id,
                    lease_id=lease.lease_id, role=w.role)
        conn.send({
            "t": "lease", "lease_id": lease.lease_id,
            "ttl_s": self.leases.ttl_s,
            "heartbeat_s": self.heartbeat_s,
        })
        self.queue.kick()    # routable capacity appeared
        if w.role == "ingest":
            # a (re)joined ingest worker may rendezvous-reclaim clusters
            self._ingest_rebalance()
        return w

    def _conn_loop(self, conn: FrameConn) -> None:
        """One connection's read loop: hello/handshake, then heartbeats,
        responses, and drain acks until EOF (EOF = process death)."""
        handle: Optional[_WorkerHandle] = None
        while not self._stop.is_set():
            try:
                msg = conn.recv()
            except FrameError:
                msg = None   # poisoned stream: treat as death
            if msg is None:
                break
            now = self.clock()
            if handle is not None and now < handle.partitioned_until:
                # coordinator_partition chaos: frames are dropped on the
                # floor — no renewals, no acks, no responses delivered
                with self._lock:
                    handle.partition_dropped += 1
                continue
            t = msg.get("t")
            if t == "hello":
                got = self._register(conn, msg)
                if got is not None:
                    handle = got
            elif t == "hb" and handle is not None:
                if self.leases.renew(
                    handle.worker_id, str(msg.get("lease_id"))
                ):
                    conn.send({"t": "hb_ack", "seq": msg.get("seq", 0)})
                else:
                    # stale/expired lease: the worker was declared dead;
                    # make it re-hello explicitly
                    conn.send({"t": "reject", "reason": "stale_lease"})
            elif t == "resp" and handle is not None:
                self._on_response(handle, msg)
            elif t == "ingest_stat" and handle is not None:
                self._on_ingest_stat(handle, msg)
            elif t == "drained" and handle is not None:
                self._event("worker_drained", handle.worker_id,
                            served=msg.get("served"))
                with self._lock:
                    draining = handle.draining
                if draining:
                    # intentional retirement (scale-down): complete it
                    # BEFORE the socket drops so the EOF below is a
                    # no-op, never a process_kill
                    self._scaledown_complete(handle)
        if handle is not None:
            self._worker_down(handle.worker_id, eof=True)

    # -- completion (exactly-once across the wire) ----------------------------
    def _on_response(self, w: _WorkerHandle, msg: Dict[str, Any]) -> None:
        rid = str(msg.get("request_id"))
        with self._lock:
            entry = self._pending.get(rid)
            if entry is None or entry.worker_id != w.worker_id:
                # reassigned or already completed: a late answer from a
                # declared-dead worker must not double-complete
                self.stale_responses += 1
                stale = True
            else:
                del self._pending[rid]
                w.outstanding = max(0, w.outstanding - 1)
                w.served += 1
                stale = False
        if stale:
            return
        req = entry.req
        status = str(msg.get("status", "error"))
        if status == "ok":
            ranked = [dict(r) for r in msg.get("ranked") or []]
            self.sink.remember(req.graph_key, ranked)
            queue_ms = max(0.0, (entry.sent_at - req.enqueued_at) * 1e3)
            self.metrics.answered(req.tenant, queue_ms)
            self.metrics.record_batch(int(msg.get("batch_size") or 1))
            self.sink._complete(req, ServeResponse(
                status="ok", request_id=req.request_id, tenant=req.tenant,
                ranked=ranked, queue_ms=round(queue_ms, 3),
                batch_size=int(msg.get("batch_size") or 1),
                deadline_missed=req.expired(self.clock()),
                result=WireResult(ranked, str(msg.get("engine") or "")),
            ))
        elif status == "shed":
            self.sink.shed(req, detail=str(msg.get("detail") or "shed"))
        elif status in ("degraded", "error", "queue_full"):
            # honest forwarding: the worker's ladder already ran; a
            # queue_full from a saturated worker degrades here (the
            # coordinator's ladder may still hold a last-known ranking)
            self.sink.degraded(
                req,
                detail=f"worker{w.worker_id}:{status}:"
                       f"{msg.get('detail') or ''}",
            )
        else:
            self.sink.error(req, f"worker{w.worker_id}:bad_status:{status}")
        self.queue.kick()    # window room appeared

    # -- death, drain-and-reroute ---------------------------------------------
    def _worker_down(self, worker_id: int, eof: bool = False) -> None:
        """Mark one worker dead and reclaim everything it owned.  The
        fault class is derived from HOW it died: socket EOF means the
        process is gone (``process_kill``); lease expiry with the socket
        open during a partition window is ``coordinator_partition``;
        lease expiry with an open socket otherwise is ``worker_hang``."""
        now = self.clock()
        with self._lock:
            w = self.workers.get(int(worker_id))
            if w is None or not w.live:
                return
            w.live = False
            w.state = "dead"
            self.ring.remove(worker_id)
            self.ingest_ring.remove(worker_id)
            was_ingest = w.role == "ingest"
            lease = w.lease
            overdue = (
                max(0.0, now - lease.expires_at())
                if lease is not None else 0.0
            )
            if was_ingest:
                # any ingest-owner loss is the same fault from the
                # capture plane's point of view: mirrors must move
                fault = INGEST_FAULT_CLASS
            elif eof:
                fault = "process_kill"
            elif w.partitioned_until > 0.0:
                fault = "coordinator_partition"
            else:
                fault = "worker_hang"
            reclaimed = [
                p for p in self._pending.values()
                if p.worker_id == w.worker_id
            ]
            for p in reclaimed:
                del self._pending[p.req.request_id]
            w.outstanding = 0
        self.leases.revoke(worker_id)
        self._event(
            "worker_down", worker_id, **{
                "class": fault, "reclaimed": len(reclaimed),
                "detect_lag_ms": round(overdue * 1e3, 3),
            },
        )
        for p in reclaimed:
            if not self.steal:
                self.sink.degraded(
                    p.req, detail=f"worker_unavailable:{fault}"
                )
                continue
            p.moves += 1
            with self._lock:
                self.reroutes += 1
                self._overflow.append(p.req)
        self.queue.kick()
        if was_ingest:
            # drain-and-reroute for the capture plane: every cluster the
            # dead worker owned moves to its rendezvous survivor
            self._ingest_rebalance()

    # -- ingest worker class: federated cluster capture (ISSUE 17) ------------
    def register_clusters(self, specs: Dict[str, Dict[str, Any]]) -> None:
        """Register captured clusters with the ingest fleet.

        ``specs`` maps cluster id -> a spec dict carrying at least
        ``digest`` (the :meth:`ClusterSet.cluster_digest` value; the
        rendezvous routing key is ``"<cid>:<digest>"``) plus whatever
        world parameters the worker-side runner needs to host the
        mirror.  Each cluster gets EXACTLY ONE live ingest owner; every
        ownership change bumps the cluster's epoch so stats from
        deposed owners are dropped, never double-applied."""
        with self._lock:
            for cid, spec in specs.items():
                ent = self.clusters.setdefault(str(cid), {
                    "digest": "", "spec": {}, "owner": None, "epoch": 0,
                    "last_seq": 0, "ticks": 0, "double_applied": 0,
                    "moves": 0, "sweep_ms": None, "coldiff_bytes": None,
                })
                ent["digest"] = str(spec.get("digest") or cid)
                ent["spec"] = dict(spec)
        self._ingest_rebalance()

    def _ingest_rebalance(self) -> None:
        """Recompute every cluster's owner over the live ingest ring and
        ship (un)assign frames for the moves.  Rendezvous keys are
        ``cid:digest`` — a digest change (topology change) is allowed to
        move a mirror; a rejoining worker reclaims exactly the clusters
        it owned before (HRW stickiness)."""
        sends: List[Any] = []
        moved: List[Dict[str, Any]] = []
        with self._lock:
            for cid in sorted(self.clusters):
                ent = self.clusters[cid]
                key = f"{cid}:{ent['digest']}"
                new_owner = None
                for wid in self.ingest_ring.ranked(key):
                    w = self.workers.get(wid)
                    if (w is not None and w.live and not w.draining
                            and w.conn is not None):
                        new_owner = wid
                        break
                if new_owner == ent["owner"]:
                    continue
                old_id = ent["owner"]
                old = (
                    self.workers.get(old_id)
                    if old_id is not None else None
                )
                ent["owner"] = new_owner
                ent["epoch"] += 1
                ent["moves"] += 1
                if (old is not None and old.live
                        and old.conn is not None):
                    sends.append((old.conn, {
                        "t": "ingest_unassign", "cluster": cid,
                        "epoch": ent["epoch"],
                    }))
                if new_owner is not None:
                    sends.append((self.workers[new_owner].conn, {
                        "t": "ingest_assign", "cluster": cid,
                        "epoch": ent["epoch"],
                        "resume_seq": ent["last_seq"],
                        "spec": ent["spec"],
                    }))
                moved.append({
                    "cluster": cid, "from": old_id, "to": new_owner,
                    "epoch": ent["epoch"],
                })
        for m in moved:
            self._event(
                "ingest_assigned" if m["to"] is not None
                else "ingest_orphaned",
                m["to"], cluster=m["cluster"], epoch=m["epoch"],
                prev_owner=m["from"],
            )
        for conn, msg in sends:
            conn.send(msg)

    def _on_ingest_stat(self, w: _WorkerHandle,
                        msg: Dict[str, Any]) -> None:
        """One capture-tick report from an ingest worker.  The cluster
        table arbitrates exactly-once: stats from a deposed owner
        (wrong worker or stale epoch) and replayed tick seqs are
        counted and dropped — a tick is applied at most once."""
        cid = str(msg.get("cluster"))
        epoch = int(msg.get("epoch") or -1)
        seq = int(msg.get("tick_seq") or 0)
        with self._lock:
            ent = self.clusters.get(cid)
            if (ent is None or ent["owner"] != w.worker_id
                    or ent["epoch"] != epoch):
                self.ingest_stale += 1
                return
            if seq <= ent["last_seq"]:
                ent["double_applied"] += 1
                return
            ent["last_seq"] = seq
            ent["ticks"] += 1
            if msg.get("sweep_ms") is not None:
                ent["sweep_ms"] = float(msg["sweep_ms"])
            if msg.get("coldiff_bytes") is not None:
                ent["coldiff_bytes"] = int(msg["coldiff_bytes"])

    def ingest_status(self) -> Dict[str, Dict[str, Any]]:
        """Snapshot of the cluster-ownership table (CLI + tests)."""
        with self._lock:
            return {
                cid: {k: v for k, v in ent.items() if k != "spec"}
                for cid, ent in self.clusters.items()
            }

    # -- routing --------------------------------------------------------------
    def _pick_worker(self, req: ServeRequest) -> Optional[_WorkerHandle]:
        """Ring owner first; spill down the preference order past the
        outstanding window.  None while nothing live has room (the
        router parks) — and None with NOTHING live at all (the ladder
        answers).  Called under the plane lock.

        Shape-aware placement (ISSUE 16): for graph buckets the
        ``PLACEMENT_RULES`` table marks as informed-routable, the pick
        prefers the candidate whose hello'd registry summary shows the
        winning timing at this request's shape tier (device
        ``bytes_in_use`` breaks ties toward headroom, ring order breaks
        the rest — the scoring is deterministic, so a hot bucket stays
        STICKY to its preferred worker).  No candidate with data, or a
        bucket the table leaves alone → pure rendezvous order.  The
        metrics lock is a documented leaf under the plane lock."""
        key = graph_route_key(req.graph_key)
        candidates = []
        for wid in self.ring.ranked(key):
            w = self.workers.get(wid)
            if (w is not None and w.live and not w.draining
                    and w.conn is not None
                    and w.outstanding < self.window):
                candidates.append(w)
        if not candidates:
            return None
        rule = PLACEMENT_RULES.rule_for(int(req.graph_key[0]))
        if "timings" in rule.prefer and len(candidates) > 1:
            scored = []
            for pos, w in enumerate(candidates):
                t_ms = shape_tier_ms(w.shape_ms, int(req.graph_key[0]))
                if t_ms is None:
                    continue
                mem = (
                    w.mem_bytes
                    if ("headroom" in rule.prefer
                        and w.mem_bytes is not None)
                    else float("inf")
                )
                scored.append((t_ms, mem, pos, w))
            if scored:
                self.metrics.placement("preferred")
                return min(scored)[3]
        self.metrics.placement("rendezvous")
        return candidates[0]

    def _route_one(self, req: ServeRequest, now: float) -> bool:
        """Place one popped request.  True when it reached a worker (or
        terminally completed); False = no capacity right now, the router
        holds it in overflow."""
        if req.expired(now):
            self.sink.shed(req, detail="expired_in_router")
            return True
        conn = None
        with self._lock:
            target = self._pick_worker(req)
            if target is not None:
                self._pending[req.request_id] = _Pending(
                    req, target.worker_id, now
                )
                target.outstanding += 1
                conn = target.conn
        if target is None:
            if not self.live_workers():
                # no fleet: ride the degradation ladder, never hang
                self.sink.degraded(req, detail="no_worker_available")
                return True
            return False
        if self.tracer.enabled and req.trace is not None:
            self.tracer.record(
                "serve.queue", req.enqueued_at, now, parent=req.trace,
                attrs={"tenant": req.tenant, "priority": req.priority,
                       "worker": target.worker_id},
            )
        if not conn.send(encode_request(req)):
            # died between pick and send: reclaim immediately and retry
            with self._lock:
                entry = self._pending.pop(req.request_id, None)
                if entry is not None:
                    target.outstanding = max(0, target.outstanding - 1)
            self._worker_down(target.worker_id, eof=True)
            if entry is not None:
                with self._lock:
                    self._overflow.append(req)
            return True
        return True

    def _route_loop(self) -> None:
        while not self._stop.is_set():
            now = self.clock()
            worked = False
            for req in self.queue.shed_expired(now):
                self.sink.shed(req, detail="expired_in_queue")
                worked = True
            with self._lock:
                held = self._overflow.popleft() if self._overflow else None
            if held is not None:
                if self._route_one(held, now):
                    worked = True
                else:
                    with self._lock:
                        self._overflow.appendleft(held)
                    self._stop.wait(_ROUTE_IDLE_S)
                    continue
            with self._lock:
                req = self.queue.pop()
            if req is not None:
                if self._route_one(req, now):
                    worked = True
                else:
                    with self._lock:
                        self._overflow.appendleft(req)
                    self._stop.wait(_ROUTE_IDLE_S)
                    continue
            if not worked and req is None:
                self.queue.wait_for_work(_ROUTE_IDLE_S)

    # -- liveness monitor ------------------------------------------------------
    def check_leases(self, now: Optional[float] = None) -> List[int]:
        """One liveness sweep (the monitor thread's body; also driven
        directly by fake-clock tests): expire overdue leases → mark
        workers down → drain-and-reroute.  Returns the worker ids
        expired this sweep."""
        if now is None:
            now = self.clock()
        downed = []
        for worker_id, _overdue in self.leases.expired_workers(now):
            with self._lock:
                w = self.workers.get(worker_id)
                live = w is not None and w.live
            if live:
                self._worker_down(worker_id)
                downed.append(worker_id)
            else:
                self.leases.revoke(worker_id)
        return downed

    def _monitor_loop(self) -> None:
        interval = max(0.01, self.heartbeat_s / 2.0)
        while not self._stop.wait(interval):
            self.check_leases()
            # belt and braces: a worker whose PROCESS is gone but whose
            # socket teardown is lagging gets downed here too
            with self._lock:
                gone = [
                    w.worker_id for w in self.workers.values()
                    if w.live and w.proc is not None and not w.proc.alive()
                ]
            for wid in gone:
                self._worker_down(wid, eof=True)

    # -- health (gateway /healthz, `rca fleet`) -------------------------------
    def health(self) -> Dict[str, Any]:
        with self._lock:
            states = {
                str(w.worker_id): w.state for w in self.workers.values()
            }
            ok = any(w.live for w in self.workers.values())
            fleet = [
                self.workers[wid].summary() for wid in sorted(self.workers)
            ]
        out = {
            "ok": bool(ok), "workers": states,
            "queue_depth": len(self.queue),
            "pending": self.pending_count(),
            "fleet": fleet,
        }
        auto = self.autoscaler
        if auto is not None:
            out["autoscale"] = auto.status()
        ingest = self.ingest_status()
        if ingest:
            out["ingest"] = ingest
        return out


# ---------------------------------------------------------------------------
# Selftest (CLI `rca serve --federation N [--kill-worker]`)
# ---------------------------------------------------------------------------


def federation_selftest(
    workers: int = 3,
    n_requests: int = 36,
    seed: int = 0,
    kill_worker: bool = False,
    submitters: int = 6,
    config: Optional[ServeConfig] = None,
    services: Tuple[int, ...] = (24, 60, 120),
    heartbeat_s: float = 0.15,
    timeout_s: float = 180.0,
    ready_timeout_s: float = 90.0,
    bind_external: bool = False,
) -> Dict[str, Any]:
    """End-to-end federation contract check, the cross-process twin of
    :func:`rca_tpu.serve.client.serve_selftest`:

    - ``workers`` real worker PROCESSES under one control plane, wire
      load from ``submitters`` concurrent threads over several shape
      buckets and tenants;
    - ``kill_worker``: SIGKILL one worker mid-wave (the procs seam's
      ``process_kill``) — every request must still end terminal
      (ok/shed/degraded, none hung), with ZERO double completions;
    - POOL-vs-FEDERATION bit parity: every ok ranking must equal a solo
      single-process analysis of the same request, bit for bit — the
      wire codec's float32→JSON→float32 identity plus the serve
      coalesced-vs-solo contract, now across process boundaries;
    - ``bind_external``: the multi-host deploy leg (ISSUE 16) — the
      coordinator binds ``0.0.0.0`` and advertises the host's primary
      interface IP, so every worker joins via a REAL non-loopback
      ``--connect host:port`` exactly as an external host would
      (SERVING.md §Deploy).
    """
    import threading as _threading   # Event only (signal, not a lock)

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.util.threads import make_thread

    cases = [
        synthetic_cascade_arrays(n, n_roots=1, seed=seed + i)
        for i, n in enumerate(services)
    ]
    tenants = [f"tenant-{c}" for c in "abcd"]
    rng = np.random.default_rng(seed)
    specs = []
    for i in range(n_requests):
        case = cases[i % len(cases)]
        feats = np.clip(
            case.features + rng.uniform(
                0, 0.05, case.features.shape
            ).astype(np.float32),
            0, 1,
        )
        specs.append({
            "case": case, "features": feats,
            "tenant": tenants[i % len(tenants)],
            # a few requests arrive already expired: the shed contract
            # must hold across the wire too
            "deadline_expired": i % 11 == 10,
        })

    plane_kwargs: Dict[str, Any] = {}
    if bind_external:
        from rca_tpu.util.net import primary_host_ip

        plane_kwargs.update(
            host="0.0.0.0", advertise_host=primary_host_ip(),
        )
    plane = FederationPlane(
        workers=workers, config=config, heartbeat_s=heartbeat_s,
        **plane_kwargs,
    )
    requests: List[Optional[ServeRequest]] = [None] * n_requests
    kill_at: Dict[str, Any] = {"t": None, "worker": None}
    kill_lock = make_lock("federation_selftest.kill_lock")
    killed = _threading.Event()
    t0 = plane.clock()
    with plane:
        if not plane.wait_ready(workers, timeout_s=ready_timeout_s):
            table = plane.worker_table()
            diag = [
                {**w.summary(), "stderr_tail": (
                    w.proc.output()[1][-2000:] if w.proc else ""
                )}
                for w in plane.workers.values()
            ]
            raise RuntimeError(
                f"federation selftest: only {len(plane.live_workers())}"
                f"/{workers} workers joined within {ready_timeout_s}s: "
                f"{table}; {diag}"
            )
        startup_s = plane.clock() - t0

        def submitter(worker: int) -> None:
            for i in range(worker, n_requests, submitters):
                s = specs[i]
                if (kill_worker and not killed.is_set()
                        and i >= n_requests // 2):
                    with kill_lock:
                        fire = not killed.is_set()
                        if fire:
                            killed.set()
                    if fire:
                        victims = plane.live_workers()
                        victim = victims[0] if victims else 0
                        kill_at["t"] = plane.clock()
                        kill_at["worker"] = victim
                        plane.kill_worker(victim)
                req = ServeRequest(
                    tenant=s["tenant"], features=s["features"],
                    dep_src=s["case"].dep_src, dep_dst=s["case"].dep_dst,
                    names=s["case"].names, k=3,
                    deadline_s=(plane.clock() - 1.0
                                if s["deadline_expired"] else None),
                )
                requests[i] = req
                plane.submit(req)

        threads = [
            make_thread(submitter, name=f"fed-selftest-submit-{w}",
                        daemon=True, args=(w,))
            for w in range(submitters)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        responses = [r.result(timeout_s) for r in requests]  # type: ignore
        all_terminal_at = plane.clock()
        events = list(plane.events)
        worker_table = plane.worker_table()
        double = plane.sink.double_completions
        stale = plane.stale_responses
        reroutes = plane.reroutes

    by_status: Dict[str, int] = {}
    for resp in responses:
        by_status[resp.status] = by_status.get(resp.status, 0) + 1
    # parity oracles: per ENGINE KIND, like the pool selftest — a
    # worker on a multi-device host auto-shards (RCA_SHARD default),
    # and dense-vs-sharded float differences must not masquerade as
    # federation-parity failures.  The wire response names its engine
    # tag; the solo rerun uses the SAME kind (and the same dp/sp
    # layout, parsed from the tag).
    import re as _re

    solo_cache: Dict[str, Any] = {}

    def _oracle(tag: str):
        if tag not in solo_cache:
            m = _re.search(r"sharded\(dp=(\d+),sp=(\d+)\)", tag or "")
            if m:
                from rca_tpu.engine.sharded_runner import (
                    ShardedGraphEngine,
                )

                solo_cache[tag] = ShardedGraphEngine(
                    spec=f"dp={m.group(1)},sp={m.group(2)}"
                )
            else:
                solo_cache[tag] = GraphEngine()
        return solo_cache[tag]

    parity_checked = 0
    parity_ok = True
    for spec, resp in zip(specs, responses):
        if not resp.ok:
            continue
        tag = getattr(resp.result, "engine", "") or ""
        ref = _oracle(tag).analyze_arrays(
            spec["features"], spec["case"].dep_src,
            spec["case"].dep_dst, spec["case"].names, k=3,
        )
        parity_checked += 1
        if [dict(r) for r in ref.ranked] != resp.ranked:
            parity_ok = False
    expected_shed = sum(1 for s in specs if s["deadline_expired"])
    all_resolved = all(r is not None and r.done() for r in requests)
    terminal_ok = all(
        r.status in ("ok", "shed", "degraded", "queue_full")
        for r in responses
    ) if not kill_worker else all(
        r.status in ("ok", "shed", "degraded", "error", "queue_full")
        for r in responses
    )
    fault_classes = sorted({
        e["class"] for e in events
        if e["event"] == "worker_down" and e.get("class")
    })
    ok = (
        all_resolved
        and parity_ok
        and double == 0
        and terminal_ok
        and by_status.get("shed", 0) >= expected_shed
        and (not kill_worker or "process_kill" in fault_classes)
        and (kill_worker or (
            by_status.get("error", 0) == 0
            and by_status.get("ok", 0)
            == n_requests - by_status.get("shed", 0)
        ))
    )
    out = {
        "ok": bool(ok),
        "workers": workers,
        "requests": n_requests,
        "kill_worker": bool(kill_worker),
        "startup_s": round(startup_s, 3),
        **({
            "bind_external": {
                "listen": "0.0.0.0",
                "advertised": plane.address,
            },
        } if bind_external else {}),
        "by_status": by_status,
        "expected_shed_min": expected_shed,
        "all_resolved": bool(all_resolved),
        "parity_checked": parity_checked,
        "parity_ok": bool(parity_ok),
        "double_completions": double,
        "stale_responses": stale,
        "reroutes": reroutes,
        "fault_classes_observed": fault_classes,
        "worker_table": worker_table,
    }
    if kill_worker and kill_at["t"] is not None:
        out["killed_worker"] = kill_at["worker"]
        out["recovery_ms"] = round(
            (all_terminal_at - kill_at["t"]) * 1e3, 1
        )
        down = [
            e for e in events
            if e["event"] == "worker_down"
            and e["worker_id"] == kill_at["worker"]
        ]
        if down:
            out["detect_ms"] = round(
                (down[0]["t"] - kill_at["t"]) * 1e3, 1
            )
    return out


# ---------------------------------------------------------------------------
# Chaos harness (CLI `rca chaos` federation leg)
# ---------------------------------------------------------------------------


def run_federation_chaos(
    seed: int = 7,
    workers: int = 3,
    heartbeat_s: float = 0.12,
    services: int = 32,
    timeout_s: float = 240.0,
    ready_timeout_s: float = 90.0,
) -> Dict[str, Any]:
    """Drive all three federation fault classes against one live fleet
    under continuous wire load, and score the contract:

    1. **worker_hang**: a seeded-chosen worker is told to stop
       heartbeating past the lease TTL (socket stays open) → lease
       expiry → drain-and-reroute; when the hang ends, its stale lease
       is REJECTED and it re-hellos — the rejoin path;
    2. **coordinator_partition**: the coordinator drops another
       worker's frames for a window → same expiry/reroute; on heal the
       worker rejoins the same way;
    3. **process_kill**: a third worker is SIGKILLed (procs seam) and
       stays dead — survivors absorb its keys.

    Exit contract: every submitted request terminal, ZERO double
    completions (stale late answers from hung/partitioned workers are
    dropped and counted), all three classes observed, and at least one
    rejoin."""
    import random as _random

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.util.threads import make_thread

    rng = _random.Random(seed)
    case = synthetic_cascade_arrays(services, n_roots=1, seed=seed)
    nprng = np.random.default_rng(seed)
    plane = FederationPlane(workers=workers, heartbeat_s=heartbeat_s)
    ttl = plane.leases.ttl_s
    submitted: List[ServeRequest] = []
    stop_load = threading.Event()

    def load() -> None:
        i = 0
        while not stop_load.is_set():
            feats = np.clip(
                case.features + nprng.uniform(
                    0, 0.05, case.features.shape
                ).astype(np.float32),
                0, 1,
            )
            req = ServeRequest(
                tenant=f"chaos-{i % 3}", features=feats,
                dep_src=case.dep_src, dep_dst=case.dep_dst,
                names=case.names, k=3,
            )
            submitted.append(req)
            plane.submit(req)
            i += 1
            stop_load.wait(0.03)

    def wait_event(pred, deadline: float) -> bool:
        while plane.clock() < deadline:
            if any(pred(e) for e in list(plane.events)):
                return True
            stop_load.wait(0.05)
        return False

    phases: List[Dict[str, Any]] = []
    with plane:
        if not plane.wait_ready(workers, timeout_s=ready_timeout_s):
            raise RuntimeError(
                "federation chaos: workers failed to join: "
                f"{plane.worker_table()}"
            )
        loader = make_thread(load, name="fed-chaos-load", daemon=True)
        loader.start()

        def downed(wid, klass):
            return lambda e: (
                e["event"] == "worker_down"
                and e["worker_id"] == wid and e.get("class") == klass
            )

        def rejoined(wid, after):
            return lambda e: (
                e["event"] == "rejoin" and e["worker_id"] == wid
                and e["t"] >= after
            )

        # 1. worker_hang → expiry → rejoin
        victims = plane.live_workers()
        hang_w = victims[rng.randrange(len(victims))]
        t_h = plane.clock()
        plane.hang_worker(hang_w, for_s=ttl * 2.5)
        hang_seen = wait_event(
            downed(hang_w, "worker_hang"), plane.clock() + timeout_s / 4
        )
        hang_rejoin = wait_event(
            rejoined(hang_w, t_h), plane.clock() + timeout_s / 4
        )
        phases.append({"fault": "worker_hang", "worker": hang_w,
                       "observed": hang_seen, "rejoined": hang_rejoin})

        # 2. coordinator_partition → expiry → heal → rejoin
        candidates = [
            w for w in plane.live_workers() if w != hang_w
        ] or plane.live_workers()
        part_w = candidates[rng.randrange(len(candidates))]
        t_p = plane.clock()
        plane.partition(part_w, for_s=ttl * 2.5)
        part_seen = wait_event(
            downed(part_w, "coordinator_partition"),
            plane.clock() + timeout_s / 4,
        )
        part_rejoin = wait_event(
            rejoined(part_w, t_p), plane.clock() + timeout_s / 4
        )
        phases.append({"fault": "coordinator_partition", "worker": part_w,
                       "observed": part_seen, "rejoined": part_rejoin})

        # 3. process_kill — permanent; survivors absorb the keys
        live = plane.live_workers()
        kill_w = live[rng.randrange(len(live))]
        plane.kill_worker(kill_w)
        kill_seen = wait_event(
            downed(kill_w, "process_kill"), plane.clock() + timeout_s / 4
        )
        phases.append({"fault": "process_kill", "worker": kill_w,
                       "observed": kill_seen})

        # let the plane settle under load, then stop
        stop_load.wait(ttl)
        stop_load.set()
        loader.join(10.0)
        responses = [r.result(timeout_s / 2) for r in submitted]
        double = plane.sink.double_completions
        stale = plane.stale_responses
        reroutes = plane.reroutes
        classes = plane.fault_classes_observed()
        events = list(plane.events)

    by_status: Dict[str, int] = {}
    for r in responses:
        by_status[r.status] = by_status.get(r.status, 0) + 1
    detect = [
        e["detect_lag_ms"] for e in events
        if e["event"] == "worker_down" and "detect_lag_ms" in e
    ]
    all_terminal = all(r.done() for r in submitted)
    ok = (
        all_terminal
        and double == 0
        and all(p["observed"] for p in phases)
        and all(p.get("rejoined", True) for p in phases)
        and set(classes) >= set(FED_FAULT_CLASSES)
    )
    return {
        "ok": bool(ok),
        "workers": workers,
        "requests": len(submitted),
        "by_status": by_status,
        "all_terminal": bool(all_terminal),
        "double_completions": double,
        "stale_responses": stale,
        "reroutes": reroutes,
        "fault_classes_observed": classes,
        "phases": phases,
        "lease_ttl_s": ttl,
        "detect_lag_ms_max": round(max(detect), 3) if detect else None,
        "rejoins": sum(1 for e in events if e["event"] == "rejoin"),
    }


def run_ingest_chaos(
    seed: int = 17,
    workers: int = 3,
    clusters: int = 4,
    heartbeat_s: float = 0.12,
    timeout_s: float = 180.0,
    ready_timeout_s: float = 90.0,
) -> Dict[str, Any]:
    """Drive the ``ingest_death`` fault class against a live ingest
    fleet mid-sweep, and score the capture-ownership contract:

    1. spawn an ingest-worker fleet and register ``clusters`` synthetic
       clusters — each rendezvous-routed to exactly one owner, ticking
       its columnar mirror continuously;
    2. SIGKILL the owner of a seeded-chosen cluster while its ticks are
       flowing (mid-sweep by construction: the runner never pauses);
    3. assert every orphaned cluster moves to EXACTLY ONE live
       survivor with a fresh epoch and resumes ticking, with ZERO
       double-applied ticks (late stats from the dead owner are
       epoch-stale and dropped);
    4. respawn the dead worker id: HRW stickiness must hand it back
       exactly the clusters it owned before (rejoin reclaims)."""
    import random as _random

    rng = _random.Random(seed)
    plane = FederationPlane(
        workers=0, heartbeat_s=heartbeat_s, spawn_workers=False,
    )

    def wait_for(pred, deadline: float) -> bool:
        while plane.clock() < deadline:
            if pred():
                return True
            if plane._stop.wait(0.05):
                return False
        return bool(pred())

    with plane:
        for i in range(workers):
            plane.spawn_worker(i, role="ingest")
        if not plane.wait_ready(workers, timeout_s=ready_timeout_s):
            raise RuntimeError(
                "ingest chaos: workers failed to join: "
                f"{plane.worker_table()}"
            )
        specs = {
            f"ing{j}": {
                "digest": f"digest-{seed}-{j}",
                "services": 6, "pods_per_service": 1,
                "seed": seed + j, "namespace": "synthetic",
            }
            for j in range(clusters)
        }
        plane.register_clusters(specs)

        def ticking(min_ticks: int, table=None) -> bool:
            status = plane.ingest_status()
            return all(
                c["owner"] is not None and c["ticks"] >= (
                    (table or {}).get(cid, 0) + min_ticks
                )
                for cid, c in status.items()
            )

        deadline = plane.clock() + timeout_s
        if not wait_for(lambda: ticking(3), deadline):
            raise RuntimeError(
                f"ingest chaos: fleet never ticked: {plane.ingest_status()}"
            )

        pre = plane.ingest_status()
        owners = sorted({c["owner"] for c in pre.values()})
        victim = owners[rng.randrange(len(owners))]
        victim_clusters = sorted(
            cid for cid, c in pre.items() if c["owner"] == victim
        )
        pre_ticks = {cid: pre[cid]["ticks"] for cid in pre}
        # mid-sweep: ticks are flowing when the SIGKILL lands
        plane.kill_worker(victim)

        death_seen = wait_for(
            lambda: any(
                e["event"] == "worker_down"
                and e["worker_id"] == victim
                and e.get("class") == INGEST_FAULT_CLASS
                for e in list(plane.events)
            ),
            deadline,
        )

        def moved() -> bool:
            status = plane.ingest_status()
            live = set(plane.live_workers())
            return all(
                status[cid]["owner"] in live
                and status[cid]["owner"] != victim
                and status[cid]["epoch"] > pre[cid]["epoch"]
                and status[cid]["ticks"] >= pre_ticks[cid] + 2
                for cid in victim_clusters
            )

        moved_ok = wait_for(moved, deadline)
        mid = plane.ingest_status()

        # rejoin: the respawned worker id must reclaim ITS clusters
        plane.spawn_worker(victim, role="ingest")

        def reclaimed() -> bool:
            status = plane.ingest_status()
            return all(
                status[cid]["owner"] == victim
                and status[cid]["ticks"] >= mid[cid]["ticks"] + 2
                for cid in victim_clusters
            )

        reclaimed_ok = wait_for(reclaimed, deadline)
        status = plane.ingest_status()
        double = sum(c["double_applied"] for c in status.values())
        stale = plane.ingest_stale
        classes = plane.fault_classes_observed()
        single_owner = all(
            c["owner"] is not None for c in status.values()
        )

    ok = (
        death_seen
        and moved_ok
        and reclaimed_ok
        and single_owner
        and double == 0
        and INGEST_FAULT_CLASS in classes
        and bool(victim_clusters)
    )
    return {
        "ok": bool(ok),
        "workers": workers,
        "clusters": clusters,
        "victim": victim,
        "victim_clusters": victim_clusters,
        "death_seen": bool(death_seen),
        "moved_to_survivor": bool(moved_ok),
        "rejoin_reclaimed": bool(reclaimed_ok),
        "double_applied": double,
        "stale_stats_dropped": stale,
        "fault_classes_observed": classes,
        "table": status,
    }
