"""Ingest worker runtime: hosting cluster capture mirrors in the fleet.

An ingest worker (``python -m rca_tpu.serve.worker --role ingest``) is a
fleetmesh member of a different class: it joins the coordinator with
``role: "ingest"`` in its hello, lives on the ingest ring instead of the
serve ring, and owns COLUMNAR CAPTURE MIRRORS for the clusters the
coordinator assigns it (``ingest_assign`` frames, rendezvous-routed on
``cluster_id:digest``).  For every assigned cluster the
:class:`IngestRunner` sweeps the cluster's ``get_columnar`` feed on the
``RCA_INGEST_TICK_S`` cadence and reports one ``ingest_stat`` frame per
tick — cluster id, ownership epoch, monotone tick seq, sweep latency,
and coldiff payload bytes.  The COORDINATOR's cluster table is the
exactly-once arbiter: this process just ticks and reports; a deposed
owner's late stats are epoch-stale there, never double-applied.

Assignment specs carry the synthetic world parameters (services, seed,
namespace) — the hermetic fleet drives generator-built clusters through
the very same mock client + columnar master the parity gates test.  A
live deployment would hand the runner a connected
:class:`~rca_tpu.cluster.k8s_client.K8sApiClient` instead; the sweep
loop is client-agnostic because ``get_columnar`` is.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Dict, Optional

from rca_tpu.util.threads import make_lock, spawn


class NullServePlane:
    """The 'serving plane' of an ingest worker: none.  Ingest workers
    are off the serve ring — nothing routes requests here — but the
    WorkerAgent surface expects a loop with start/stop/submit."""

    def start(self) -> "NullServePlane":
        return self

    def stop(self, *args: Any, **kwargs: Any) -> None:
        return None

    def submit(self, req: Any) -> bool:
        return False


def _payload_bytes(payload: Dict[str, Any]) -> int:
    """Wire-size accounting for one coldiff payload (ndarray-tolerant,
    never fatal — the stat is observability, not correctness)."""
    try:
        return len(json.dumps(
            payload, default=lambda o: (
                o.tolist() if hasattr(o, "tolist") else str(o)
            ),
        ))
    except Exception:  # noqa: BLE001 - stat only
        return 0


class IngestRunner:
    """The per-process capture loop behind one ingest WorkerAgent.

    One background thread sweeps every assigned cluster in sorted order
    each cycle; assignment state is swapped under a lock by the frame
    handler (:meth:`handle`), so a reassignment mid-cycle simply makes
    the next sweep skip the cluster.  Tick seqs resume from the
    coordinator's ``resume_seq`` — the rejoin/reclaim path continues the
    dead owner's count instead of restarting at zero (restarting would
    make every replayed seq look double-applied)."""

    def __init__(self, agent: Any, tick_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        from rca_tpu.config import ingest_tick_s

        self.agent = agent
        self.clock = clock
        self.tick_s = float(
            ingest_tick_s() if tick_s is None else tick_s
        )
        self._lock = make_lock("IngestRunner._lock")
        #: cluster id -> {"epoch", "seq", "spec", "state"}
        self.assigned: Dict[str, Dict[str, Any]] = {}
        self.ticks_sent = 0
        self._stop = threading.Event()
        self._thread = spawn(
            self._loop,
            name=f"rca-ingest{getattr(agent, 'worker_id', '?')}",
            daemon=True,
        )

    # -- frame handling (called from the agent's read loop) -----------------
    def handle(self, msg: Dict[str, Any]) -> None:
        if msg.get("t") == "ingest_assign":
            self.assign(msg)
        else:
            self.unassign(msg)

    def assign(self, msg: Dict[str, Any]) -> None:
        cid = str(msg.get("cluster"))
        with self._lock:
            prev = self.assigned.get(cid)
            self.assigned[cid] = {
                "epoch": int(msg.get("epoch") or 0),
                "seq": int(msg.get("resume_seq") or 0),
                "spec": dict(msg.get("spec") or {}),
                # keep a rebuilt-once world across same-process
                # reassignments (epoch bumps reuse the mirror)
                "state": prev.get("state") if prev else None,
            }

    def unassign(self, msg: Dict[str, Any]) -> None:
        with self._lock:
            self.assigned.pop(str(msg.get("cluster")), None)

    def stop(self) -> None:
        self._stop.set()

    # -- the sweep loop ------------------------------------------------------
    def _build(self, spec: Dict[str, Any]) -> Dict[str, Any]:
        from rca_tpu.cluster.generator import synthetic_cascade_world
        from rca_tpu.cluster.mock_client import MockClusterClient

        ns = str(spec.get("namespace") or "synthetic")
        world = synthetic_cascade_world(
            int(spec.get("services") or 6),
            seed=int(spec.get("seed") or 0),
            namespace=ns,
            pods_per_service=int(spec.get("pods_per_service") or 1),
        )
        return {
            "world": world, "client": MockClusterClient(world),
            "ns": ns, "cursor": None, "churn": 0,
        }

    def _tick(self, cid: str, st: Dict[str, Any]) -> None:
        if st["state"] is None:
            st["state"] = self._build(st["spec"])
        s = st["state"]
        world, ns = s["world"], s["ns"]
        pods = world.pods.get(ns) or []
        if pods:
            # deterministic churn: one metrics touch per sweep keeps
            # the coldiff stream non-trivial (quiet ticks still happen
            # between sweeps when nothing else changed)
            victim = pods[s["churn"] % len(pods)]
            world.touch(
                "pod_metrics", ns, victim["metadata"]["name"]
            )
            s["churn"] += 1
        t0 = self.clock()
        payload = s["client"].get_columnar(ns, s["cursor"])
        sweep_ms = (self.clock() - t0) * 1e3
        if payload.get("supported"):
            s["cursor"] = payload.get("cursor")
        st["seq"] += 1
        self.ticks_sent += 1
        self.agent.conn.send({
            "t": "ingest_stat",
            "cluster": cid,
            "epoch": st["epoch"],
            "tick_seq": st["seq"],
            "sweep_ms": round(sweep_ms, 3),
            "coldiff_bytes": _payload_bytes(payload),
            "full": bool(payload.get("full")),
        })

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                cids = sorted(self.assigned)
            for cid in cids:
                if self._stop.is_set():
                    return
                with self._lock:
                    st = self.assigned.get(cid)
                if st is not None:
                    try:
                        self._tick(cid, st)
                    except Exception:  # noqa: BLE001 - keep sweeping
                        # a torn-down conn mid-stop; the agent's read
                        # loop owns lifecycle, the sweep must not die
                        if self._stop.is_set():
                            return
            self._stop.wait(self.tick_s)
