"""elasticmesh: the autoscaling worker-fleet controller (ISSUE 16).

PR 15's federation survives process death but the fleet is a fixed N —
a surge burns SLO until an operator adds workers, an idle fleet wastes
hosts.  :class:`AutoscaleController` closes that loop on the
coordinator: it watches the telemetry the plane already exports
(windowed queue-time p99, SLO-burn, queue depth, fleet occupancy) and
spawns or drains workers through the existing seams —
``FederationPlane.spawn_worker`` (util/procs) up,
``FederationPlane.drain_worker`` (drain-and-reroute) down.

**Policy is a table, not code paths.**  Like ``GRAPH_RULES`` and the
partition rules (SNIPPETS.md idiom), every scale decision comes from
the declarative ``SCALE_RULES`` table: each rule names one signal, a
threshold, a SUSTAIN window (the signal must breach continuously for
``for_s`` before the rule fires — hysteresis), and an action.  One
global COOLDOWN after any action, plus min/max clamps, makes a
flapping load signal unable to thrash the ring: between the sustain
requirement and the cooldown there is provably at most one transition
per ``cooldown_s``.  Rule order is priority; tables are validated
loudly at construction (a typo'd rule must fail at import, not
mid-surge).

**Placement is a table too.**  ``PLACEMENT_RULES`` buckets requests by
graph size and says which evidence may reorder the rendezvous ring:
``timings`` (the hello frame's kernel-registry summary — per-shape
winner milliseconds) and ``headroom`` (the kernelscope device-memory
accountant's ``bytes_in_use``).  Small graphs stay pure rendezvous —
any worker serves them well and stickiness is worth more than
microseconds; big graphs route to the worker with the winning timing
at their shape tier, headroom breaking ties.  The scoring is
deterministic, so a preferred bucket is still STICKY.

**Every transition is chaos-gated.**  :func:`run_scaling_storm` is the
seeded ``scaling_storm`` fault class — scale-up racing worker SIGKILL,
rejoin racing drain, partition during scale-down — gated on zero
double completions and bounded stale drops; :func:`run_scale_ramp_soak`
ramps a live fleet 2→8→2 under continuous traffic and asserts
all-terminal + exactly-once + bounded windowed p99 through both
transitions.  Both run in THREAD worker mode by default
(:class:`ThreadWorker`: a real ``WorkerAgent`` + ``ServeLoop`` over a
real loopback socket per fleet member — the full wire protocol with
none of the process spawn cost; ``worker_mode="process"`` exercises
the procs seam itself).

Concurrency: ``AutoscaleController._lock`` guards the breach timers,
cooldown stamp, and decision log, and is a LEAF — never held across a
plane call.  Timing goes through the plane's injectable clock
(nondet-discipline; the fake-clock unit tests drive ``run_once(now=)``
directly).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from rca_tpu.config import (
    fed_scale_cooldown_s,
    fed_scale_interval_s,
    fed_scale_max,
    fed_scale_min,
)
from rca_tpu.util.threads import make_lock, spawn

#: the elastic fleet's fault class — what `rca chaos` must observe.
#: Deliberately NOT in federation.FED_FAULT_CLASSES: that vocabulary is
#: the plane's per-worker death taxonomy (pinned by tests); a scaling
#: storm is a HARNESS-level composite (decisions racing those faults).
SCALING_FAULT_CLASSES = ("scaling_storm",)

SCALE_SIGNALS = ("queue_p99_ms", "queue_depth", "occupancy", "slo_burn")
SCALE_OPS = (">", "<")
SCALE_ACTIONS = ("up", "down")
PLACEMENT_EVIDENCE = ("timings", "headroom")

#: controller decisions retained for `rca fleet` / the soak report
_DECISION_CAP = 256


# ---------------------------------------------------------------------------
# SCALE_RULES — the declarative scaling policy
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ScaleRule:
    """One scaling trigger: ``signal op threshold`` sustained for
    ``for_s`` seconds fires ``action`` by ``step`` workers."""

    name: str
    signal: str
    op: str
    threshold: float
    for_s: float
    action: str
    step: int = 1


@dataclass(frozen=True)
class ScaleRuleSet:
    """An ordered, validated scale-rule table.  Order is priority: the
    FIRST sustained-breaching rule wins a sweep.  Validation is loud and
    total at construction — the same contract as the partition rules."""

    rules: Tuple[ScaleRule, ...]

    def __post_init__(self) -> None:
        if not self.rules:
            raise ValueError("ScaleRuleSet: at least one rule required")
        seen: set = set()
        for r in self.rules:
            ctx = f"scale rule {r.name!r}"
            if not r.name or r.name in seen:
                raise ValueError(
                    f"{ctx}: names must be non-empty and unique"
                )
            seen.add(r.name)
            if r.signal not in SCALE_SIGNALS:
                raise ValueError(
                    f"{ctx}: unknown signal {r.signal!r} "
                    f"(known: {SCALE_SIGNALS})"
                )
            if r.op not in SCALE_OPS:
                raise ValueError(f"{ctx}: op must be one of {SCALE_OPS}")
            if r.action not in SCALE_ACTIONS:
                raise ValueError(
                    f"{ctx}: action must be one of {SCALE_ACTIONS}"
                )
            if r.threshold < 0:
                raise ValueError(f"{ctx}: threshold must be >= 0")
            if r.for_s < 0:
                raise ValueError(f"{ctx}: for_s must be >= 0")
            if r.step < 1:
                raise ValueError(f"{ctx}: step must be >= 1")
        if not any(r.action == "up" for r in self.rules):
            raise ValueError("ScaleRuleSet: no scale-up rule")
        if not any(r.action == "down" for r in self.rules):
            raise ValueError("ScaleRuleSet: no scale-down rule")
        # hysteresis band: a signal driving BOTH directions must leave a
        # dead zone between its down and up thresholds, or one steady
        # value could fire up and down alternately (the flap this table
        # exists to make impossible)
        for sig in SCALE_SIGNALS:
            ups = [r.threshold for r in self.rules
                   if r.signal == sig and r.action == "up" and r.op == ">"]
            downs = [r.threshold for r in self.rules
                     if r.signal == sig and r.action == "down"
                     and r.op == "<"]
            if ups and downs and max(downs) >= min(ups):
                raise ValueError(
                    f"ScaleRuleSet: signal {sig!r} has no hysteresis "
                    f"band (down threshold {max(downs)} >= up threshold "
                    f"{min(ups)})"
                )


#: the default policy: scale up on sustained queue growth or SLO burn,
#: down only on a long-idle fleet.  Sustain windows are in units of the
#: default sweep interval (RCA_FED_SCALE_INTERVAL_S=1.0); the soak and
#: the tests pass their own faster tables.
SCALE_RULES = ScaleRuleSet(rules=(
    ScaleRule("surge-queue-p99", "queue_p99_ms", ">", 500.0, 5.0, "up", 2),
    ScaleRule("surge-depth", "queue_depth", ">", 32.0, 5.0, "up", 2),
    ScaleRule("surge-slo-burn", "slo_burn", ">", 0.0, 10.0, "up", 1),
    ScaleRule("hot-occupancy", "occupancy", ">", 0.85, 5.0, "up", 1),
    ScaleRule("idle-occupancy", "occupancy", "<", 0.10, 30.0, "down", 1),
))


# ---------------------------------------------------------------------------
# PLACEMENT_RULES — shape-aware routing policy
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class PlacementRule:
    """One graph-size bucket: requests with ``n_services >=
    min_services`` may use the named evidence to reorder the ring."""

    name: str
    min_services: int
    prefer: Tuple[str, ...] = field(default_factory=tuple)


@dataclass(frozen=True)
class PlacementRuleSet:
    """Validated, first-match placement table over descending
    ``min_services`` bounds; the last rule must cover 0 so every
    request matches (an unroutable bucket is a bug, not a policy)."""

    rules: Tuple[PlacementRule, ...]

    def __post_init__(self) -> None:
        if not self.rules:
            raise ValueError("PlacementRuleSet: at least one rule required")
        seen: set = set()
        prev: Optional[int] = None
        for r in self.rules:
            ctx = f"placement rule {r.name!r}"
            if not r.name or r.name in seen:
                raise ValueError(
                    f"{ctx}: names must be non-empty and unique"
                )
            seen.add(r.name)
            if r.min_services < 0:
                raise ValueError(f"{ctx}: min_services must be >= 0")
            if prev is not None and r.min_services >= prev:
                raise ValueError(
                    f"{ctx}: min_services must strictly descend "
                    f"({r.min_services} after {prev})"
                )
            prev = r.min_services
            for ev in r.prefer:
                if ev not in PLACEMENT_EVIDENCE:
                    raise ValueError(
                        f"{ctx}: unknown evidence {ev!r} "
                        f"(known: {PLACEMENT_EVIDENCE})"
                    )
        if self.rules[-1].min_services != 0:
            raise ValueError(
                "PlacementRuleSet: last rule must cover min_services=0"
            )

    def rule_for(self, n_services: int) -> PlacementRule:
        for r in self.rules:
            if int(n_services) >= r.min_services:
                return r
        raise AssertionError("unreachable: last rule covers 0")


#: big graphs chase the winning per-shape kernel timing with headroom
#: tie-breaks; mid graphs use timings alone; small graphs stay pure
#: rendezvous — stickiness is worth more than microseconds there
PLACEMENT_RULES = PlacementRuleSet(rules=(
    PlacementRule("big-graphs", 192, ("timings", "headroom")),
    PlacementRule("mid-graphs", 48, ("timings",)),
    PlacementRule("small-graphs", 0, ()),
))


def shape_tier_ms(shape_ms: Dict[int, float],
                  n_services: int) -> Optional[float]:
    """A worker's advertised winner timing at the tier serving
    ``n_services``: the smallest known ``n_pad >= n_services``, else
    the largest known (an undersized tier still says how fast the
    worker's kernels are).  None with no data — the caller falls back
    to rendezvous."""
    if not shape_ms:
        return None
    covering = [p for p in shape_ms if p >= int(n_services)]
    tier = min(covering) if covering else max(shape_ms)
    return shape_ms[tier]


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------


class AutoscaleController:
    """Coordinator-side elastic-fleet controller over one
    :class:`rca_tpu.serve.federation.FederationPlane`.

    Reads the plane through narrow, lock-consistent surfaces
    (``scale_status`` / ``pending_count`` / ``metrics
    .autoscale_signals``), decides via ``SCALE_RULES``, and acts via
    ``spawner`` (default: the plane's procs-seam ``spawn_worker``) and
    ``plane.drain_worker``.  ``run_once(now=)`` is the whole policy —
    fake-clock drivable; ``start()`` runs it on a named monitor thread
    every ``interval_s``."""

    def __init__(
        self,
        plane,
        min_workers: Optional[int] = None,
        max_workers: Optional[int] = None,
        rules: Optional[ScaleRuleSet] = None,
        cooldown_s: Optional[float] = None,
        interval_s: Optional[float] = None,
        clock: Optional[Callable[[], float]] = None,
        spawner: Optional[Callable[[int], Any]] = None,
    ):
        self.plane = plane
        self.min_workers = (
            int(min_workers) if min_workers is not None else fed_scale_min()
        )
        self.max_workers = (
            int(max_workers) if max_workers is not None else fed_scale_max()
        )
        if not 1 <= self.min_workers <= self.max_workers:
            raise ValueError(
                f"autoscale bounds: need 1 <= min <= max, got "
                f"min={self.min_workers} max={self.max_workers}"
            )
        self.rules = rules if rules is not None else SCALE_RULES
        self.cooldown_s = (
            float(cooldown_s) if cooldown_s is not None
            else fed_scale_cooldown_s()
        )
        self.interval_s = (
            float(interval_s) if interval_s is not None
            else fed_scale_interval_s()
        )
        self.clock = clock if clock is not None else plane.clock
        self.spawner = spawner if spawner is not None else plane.spawn_worker
        self._lock = make_lock("AutoscaleController._lock")
        self._breach_since: Dict[str, float] = {}
        self._last_action_at: Optional[float] = None
        self._last_burn_total = 0
        self.decisions: "collections.deque[Dict[str, Any]]" = (
            collections.deque(maxlen=_DECISION_CAP)
        )
        self.decision_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        plane.autoscaler = self

    # -- signals --------------------------------------------------------------
    def _observe(self) -> Tuple[Dict[str, float], Dict[str, Any]]:
        """One consistent reading of every rule signal + the fleet
        status it was computed against."""
        status = self.plane.scale_status()
        live = len(status["live"])
        depth = float(len(self.plane.queue))
        pending = float(self.plane.pending_count())
        sig = self.plane.metrics.autoscale_signals()
        burn_total = int(sig["slo_breach_total"])
        with self._lock:
            burn = max(0, burn_total - self._last_burn_total)
            self._last_burn_total = burn_total
        return {
            "queue_p99_ms": float(sig["queue_ms_p99_recent"] or 0.0),
            "queue_depth": depth,
            "occupancy": (
                pending / (max(1, live) * float(self.plane.window))
            ),
            "slo_burn": float(burn),
        }, status

    def signals(self) -> Dict[str, float]:
        return self._observe()[0]

    # -- policy ---------------------------------------------------------------
    def run_once(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One sweep: evaluate the table against live signals, apply at
        most one action.  ``now`` overrides the clock (fake-clock
        tests); the decision record is always returned."""
        t_in = self.clock()
        if now is None:
            now = t_in
        sig, status = self._observe()
        with self._lock:
            fired: Optional[ScaleRule] = None
            for rule in self.rules.rules:
                value = sig[rule.signal]
                breached = (
                    value > rule.threshold if rule.op == ">"
                    else value < rule.threshold
                )
                if not breached:
                    self._breach_since.pop(rule.name, None)
                    continue
                since = self._breach_since.setdefault(rule.name, now)
                if fired is None and now - since >= rule.for_s:
                    fired = rule
            cooling = (
                fired is not None
                and self._last_action_at is not None
                and now - self._last_action_at < self.cooldown_s
            )
        if fired is None:
            self.plane.metrics.scale_event("holds")
            return {"t": now, "action": "hold", "rule": None,
                    "live": len(status["live"]), "signals": sig}
        if cooling:
            self.plane.metrics.scale_event("cooldown_skips")
            decision = {"t": now, "action": "cooldown", "rule": fired.name,
                        "live": len(status["live"]), "signals": sig}
            self._record(decision)
            return decision
        return self._apply(
            fired.name, fired.action, fired.step, now, t_in, sig, status,
        )

    def force(self, action: str, step: int = 1, rule: str = "forced",
              victims: Optional[List[int]] = None) -> Dict[str, Any]:
        """Chaos seam: apply one transition NOW, bypassing sustain and
        cooldown — the min/max clamps still hold (the storm harness
        must not be able to scale past the operator's bounds).
        ``victims`` pins the scale-down choice (racing a drain against
        a SPECIFIC rejoined worker needs to name it)."""
        if action not in SCALE_ACTIONS:
            raise ValueError(f"force: action must be one of {SCALE_ACTIONS}")
        t_in = self.clock()
        sig, status = self._observe()
        self.plane.metrics.scale_event("forced")
        return self._apply(rule, action, int(step), t_in, t_in, sig,
                           status, victims=victims, forced=True)

    def _apply(self, rule_name: str, action: str, step: int, now: float,
               t_in: float, sig: Dict[str, float], status: Dict[str, Any],
               victims: Optional[List[int]] = None,
               forced: bool = False) -> Dict[str, Any]:
        live = list(status["live"])
        n_live = len(live)
        if action == "up":
            target = min(self.max_workers, n_live + step)
        else:
            target = max(self.min_workers, n_live - step)
        decision: Dict[str, Any] = {
            "t": now, "rule": rule_name, "action": action,
            "from": n_live, "to": target, "forced": bool(forced),
            "workers": [],
            "signals": {k: round(float(v), 4) for k, v in sig.items()},
        }
        if target == n_live:
            decision["action"] = "clamped"
            self.plane.metrics.scale_event("clamps")
            self._record(decision)
            return decision
        with self._lock:
            self._last_action_at = now
            # hysteresis re-arm: every sustain window restarts after an
            # action — the fleet just changed, old breach history is
            # evidence about a topology that no longer exists
            self._breach_since.clear()
        if target > n_live:
            next_id = int(status["next_id"])
            for i in range(target - n_live):
                wid = next_id + i
                self.spawner(wid)
                decision["workers"].append(wid)
            self.plane.metrics.scale_event("scale_ups")
            self.plane._event(
                "scale_up", None, rule=rule_name,
                added=list(decision["workers"]), target=target,
            )
        else:
            if victims is None:
                outstanding = status["outstanding"]
                # least-loaded first (cheapest drain); newest id breaks
                # ties so long-lived workers keep their hot residency
                victims = sorted(
                    live,
                    key=lambda w: (outstanding.get(w, 0), -w),
                )[: n_live - target]
            for wid in victims:
                if self.plane.drain_worker(wid):
                    decision["workers"].append(wid)
            self.plane.metrics.scale_event("scale_downs")
            self.plane._event(
                "scale_down", None, rule=rule_name,
                drained=list(decision["workers"]), target=target,
            )
        decision["decision_ms"] = round((self.clock() - t_in) * 1e3, 3)
        self._record(decision)
        return decision

    def _record(self, decision: Dict[str, Any]) -> None:
        with self._lock:
            self.decisions.append(decision)
            self.decision_total += 1

    def ensure_min(self) -> List[int]:
        """Bring a smaller-than-floor fleet up to ``min_workers`` (the
        attach-time bootstrap; also what `rca serve --autoscale` leans
        on before traffic arrives)."""
        status = self.plane.scale_status()
        have = len(status["live"]) + len(status["draining"])
        spawned: List[int] = []
        next_id = int(status["next_id"])
        while have + len(spawned) < self.min_workers:
            wid = next_id + len(spawned)
            self.spawner(wid)
            spawned.append(wid)
        return spawned

    # -- lifecycle ------------------------------------------------------------
    def start(self, spawn_min: bool = True) -> "AutoscaleController":
        if self._thread is not None and self._thread.is_alive():
            return self
        if spawn_min:
            self.ensure_min()
        self._stop.clear()
        self._thread = spawn(
            self._run_loop, name="rca-fed-autoscale", daemon=True,
        )
        return self

    def _run_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception as exc:  # noqa: BLE001 - outlive one bad sweep
                self.plane._event(
                    "autoscale_error", None,
                    error=f"{type(exc).__name__}: {exc}",
                )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None

    def status(self) -> Dict[str, Any]:
        """The `rca fleet` / healthz block."""
        with self._lock:
            last = self.decisions[-1] if self.decisions else None
            return {
                "min": self.min_workers,
                "max": self.max_workers,
                "cooldown_s": self.cooldown_s,
                "interval_s": self.interval_s,
                "running": (
                    self._thread is not None and self._thread.is_alive()
                ),
                "decisions": self.decision_total,
                "last_decision": dict(last) if last is not None else None,
            }


# ---------------------------------------------------------------------------
# Thread-mode fleet members (ramp soak / storm / bench)
# ---------------------------------------------------------------------------


def _thread_fleet_engine():
    """The engine thread-mode fleet members SHARE.  Always the
    single-device :class:`GraphEngine`: the auto-sharded engine's
    cross-device collectives rendezvous by run, and concurrent
    invocations from several ServeLoop threads interleave participants
    and deadlock.  Process-mode workers (one engine per process) keep
    the full ``make_engine`` device posture."""
    from rca_tpu.engine.runner import GraphEngine

    return GraphEngine()


class ThreadWorker:
    """One in-process fleet member: a real :class:`WorkerAgent` over a
    real loopback socket, serving through its own started
    :class:`ServeLoop`.  The full wire protocol — hello/lease/
    heartbeats/drain — with none of the process spawn cost, so a ramp
    soak can cycle 2→8→2 in seconds.  A shared ``engine`` skips
    per-member compilation (thread members measure CONTROL-plane
    elasticity; ``worker_mode="process"`` measures the procs seam)."""

    def __init__(self, worker_id: int, host: str, port: int,
                 engine=None, config=None):
        from rca_tpu.serve.loop import ServeLoop
        from rca_tpu.serve.worker import WorkerAgent

        self.worker_id = int(worker_id)
        eng = engine if engine is not None else _thread_fleet_engine()
        self.loop = ServeLoop(engine=eng, config=config)
        self.loop.start()
        self.agent = WorkerAgent(
            self.worker_id, host, port, self.loop,
            engine_tag=getattr(eng, "engine_tag", type(eng).__name__),
            rejoin_seed=self.worker_id,
        )
        self.exit_code: Optional[int] = None
        self.thread = spawn(
            self._run, name=f"rca-fedw{worker_id}-agent", daemon=True,
        )

    def _run(self) -> None:
        try:
            self.exit_code = self.agent.run()
        finally:
            self.agent.close()
            self.loop.stop()

    def alive(self) -> bool:
        return self.thread.is_alive()

    def close(self, timeout_s: float = 10.0) -> None:
        self.agent.close()
        self.thread.join(timeout_s)


def thread_fleet_spawner(plane, fleet: Dict[int, ThreadWorker],
                         engine=None, config=None) -> Callable[[int], Any]:
    """A controller ``spawner`` that grows a THREAD fleet against
    ``plane``'s control port, recording members in ``fleet`` so the
    harness can kill/join them."""
    def _spawn(worker_id: int) -> ThreadWorker:
        tw = ThreadWorker(
            worker_id, plane.host, plane.port, engine=engine, config=config,
        )
        fleet[int(worker_id)] = tw
        return tw
    return _spawn


# ---------------------------------------------------------------------------
# Load-ramp soak (2→8→2 under continuous traffic)
# ---------------------------------------------------------------------------


def run_scale_ramp_soak(
    seed: int = 0,
    min_workers: int = 2,
    max_workers: int = 8,
    services: Tuple[int, ...] = (24, 48),
    heavy_threads: int = 24,
    heavy_requests_each: int = 8,
    window: int = 4,
    p99_bound_ms: float = 30000.0,
    config=None,
    ramp_timeout_s: float = 90.0,
    cooldown_s: float = 0.35,
    interval_s: float = 0.05,
) -> Dict[str, Any]:
    """The elastic fleet's endurance contract: scale ``min→max→min``
    under CONTINUOUS traffic and hold every invariant through both
    transitions.

    Heavy phase: ``heavy_threads`` closed-loop submitters over a small
    per-worker window saturate the fleet → the surge rules walk it up
    to ``max_workers``.  Then the load drops to a trickle (traffic
    never stops) → the idle rule drains it back to ``min_workers``.
    Gates computed IN-RUN: every request terminal, ZERO double
    completions, and the windowed queue p99 bounded right after the
    up-ramp and again at the end.  Returns the bench ``serve_autoscale``
    section's raw material (latency percentiles, scale-decision
    latency, placement hit rate)."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.serve.federation import FederationPlane
    from rca_tpu.serve.request import ServeRequest
    from rca_tpu.util.threads import make_thread

    # aggressive table scaled to the sweep interval: the soak must
    # cross min→max→min in seconds, not the production default's
    # minutes.  occupancy drives both directions (band 0.2 .. 0.85)
    rules = ScaleRuleSet(rules=(
        ScaleRule("soak-depth", "queue_depth", ">", 4.0, 0.10, "up", 2),
        ScaleRule("soak-occupancy", "occupancy", ">", 0.85, 0.15, "up", 2),
        ScaleRule("soak-idle", "occupancy", "<", 0.20, 0.70, "down", 2),
    ))
    cases = [
        synthetic_cascade_arrays(n, n_roots=1, seed=seed + i)
        for i, n in enumerate(services)
    ]
    nprng = np.random.default_rng(seed)
    engine = _thread_fleet_engine()
    plane = FederationPlane(
        workers=0, config=config, heartbeat_s=0.15, window=window,
        spawn_workers=False,
    )
    fleet: Dict[int, ThreadWorker] = {}
    controller = AutoscaleController(
        plane, min_workers=min_workers, max_workers=max_workers,
        rules=rules, cooldown_s=cooldown_s, interval_s=interval_s,
        spawner=thread_fleet_spawner(plane, fleet, engine=engine,
                                     config=config),
    )
    latencies_ms: List[float] = []
    requests: List[ServeRequest] = []
    hung = 0
    req_lock = make_lock("ramp_soak.req_lock")

    def one_request(i: int) -> "ServeRequest":
        case = cases[i % len(cases)]
        feats = np.clip(
            case.features + nprng.uniform(
                0, 0.05, case.features.shape
            ).astype(np.float32),
            0, 1,
        )
        return ServeRequest(
            tenant=f"soak-{i % 3}", features=feats,
            dep_src=case.dep_src, dep_dst=case.dep_dst,
            names=case.names, k=3,
        )

    def closed_loop(idx: int, n: int) -> None:
        nonlocal hung
        for j in range(n):
            req = one_request(idx * 1000 + j)
            with req_lock:
                requests.append(req)
            t0 = plane.clock()
            plane.submit(req)
            try:
                req.result(60.0)
            except TimeoutError:
                with req_lock:
                    hung += 1
                continue
            with req_lock:
                latencies_ms.append((plane.clock() - t0) * 1e3)

    def live_count() -> int:
        return len(plane.scale_status()["live"])

    def wait_fleet(pred, timeout_s: float) -> bool:
        deadline = plane.clock() + timeout_s
        while plane.clock() < deadline:
            if pred():
                return True
            stop_trickle.wait(0.05)
        return pred()

    stop_trickle = threading.Event()
    p99_after_up: Optional[float] = None
    with plane:
        controller.start(spawn_min=True)
        try:
            if not plane.wait_ready(min_workers, timeout_s=30.0):
                raise RuntimeError(
                    f"ramp soak: initial fleet of {min_workers} failed "
                    f"to join: {plane.worker_table()}"
                )
            t_ramp0 = plane.clock()
            heavy = [
                make_thread(closed_loop, name=f"soak-heavy-{i}",
                            daemon=True, args=(i, heavy_requests_each))
                for i in range(heavy_threads)
            ]
            for t in heavy:
                t.start()
            peaked = wait_fleet(
                lambda: live_count() >= max_workers, ramp_timeout_s,
            )
            ramp_up_s = plane.clock() - t_ramp0
            p99_after_up = plane.metrics.autoscale_signals()[
                "queue_ms_p99_recent"
            ]
            for t in heavy:
                t.join(120.0)
            # trickle: traffic CONTINUES through the down-ramp
            def trickle() -> None:
                i = 0
                while not stop_trickle.is_set():
                    req = one_request(900000 + i)
                    with req_lock:
                        requests.append(req)
                    t0 = plane.clock()
                    plane.submit(req)
                    try:
                        req.result(30.0)
                        with req_lock:
                            latencies_ms.append(
                                (plane.clock() - t0) * 1e3
                            )
                    except TimeoutError:
                        pass
                    i += 1
                    stop_trickle.wait(0.05)

            trickler = make_thread(trickle, name="soak-trickle",
                                   daemon=True)
            t_down0 = plane.clock()
            trickler.start()
            shrunk = wait_fleet(
                lambda: live_count() <= min_workers, ramp_timeout_s,
            )
            ramp_down_s = plane.clock() - t_down0
            stop_trickle.set()
            trickler.join(60.0)
            with req_lock:
                all_reqs = list(requests)
            for req in all_reqs:
                if not req.done():
                    try:
                        req.result(60.0)
                    except TimeoutError:
                        hung += 1
            sig_end = plane.metrics.autoscale_signals()
            snap = plane.metrics.snapshot()
            double = plane.sink.double_completions
            stale = plane.stale_responses
            reroutes = plane.reroutes
            events = list(plane.events)
            decisions = list(controller.decisions)
        finally:
            stop_trickle.set()
            controller.stop()
    for tw in fleet.values():
        tw.close(5.0)

    by_status: Dict[str, int] = {}
    for req in all_reqs:
        status = req.response.status if req.done() else "hung"
        by_status[status] = by_status.get(status, 0) + 1
    all_terminal = hung == 0 and all(r.done() for r in all_reqs)
    lat = sorted(latencies_ms)

    def pct(q: float) -> Optional[float]:
        return (
            round(lat[min(len(lat) - 1, int(len(lat) * q))], 3)
            if lat else None
        )

    decision_ms = sorted(
        d["decision_ms"] for d in decisions if "decision_ms" in d
    )
    p99_final = sig_end["queue_ms_p99_recent"]
    p99_ok = all(
        p is None or p <= p99_bound_ms
        for p in (p99_after_up, p99_final)
    )
    placement = snap["placement"]
    picks = sum(placement.values())
    scale_ups = sum(1 for e in events if e["event"] == "scale_up")
    scale_downs = sum(1 for e in events if e["event"] == "scale_down")
    ok = (
        all_terminal
        and double == 0
        and peaked
        and shrunk
        and p99_ok
        and scale_ups >= 1
        and scale_downs >= 1
    )
    return {
        "ok": bool(ok),
        "worker_mode": "thread",
        "min_workers": min_workers,
        "max_workers": max_workers,
        "requests": len(all_reqs),
        "by_status": by_status,
        "all_terminal": bool(all_terminal),
        "double_completions": double,
        "stale_responses": stale,
        "reroutes": reroutes,
        "peaked": bool(peaked),
        "shrunk": bool(shrunk),
        "ramp_up_s": round(ramp_up_s, 3),
        "ramp_down_s": round(ramp_down_s, 3),
        "scale_ups": scale_ups,
        "scale_downs": scale_downs,
        "request_ms_p50": pct(0.50),
        "request_ms_p99": pct(0.99),
        "queue_ms_p99_after_up": p99_after_up,
        "queue_ms_p99_final": p99_final,
        "p99_bound_ms": p99_bound_ms,
        "p99_ok": bool(p99_ok),
        "scale_decision_ms_p50": (
            round(decision_ms[len(decision_ms) // 2], 3)
            if decision_ms else None
        ),
        "placement": dict(placement),
        "placement_hit_rate": (
            round(placement["preferred"] / picks, 4) if picks else None
        ),
        "decisions": decisions[-12:],
    }


# ---------------------------------------------------------------------------
# scaling_storm — the chaos-gate fault class
# ---------------------------------------------------------------------------


def run_scaling_storm(
    seed: int = 7,
    workers: int = 3,
    max_workers: int = 6,
    services: int = 24,
    heartbeat_s: float = 0.12,
    timeout_s: float = 120.0,
    worker_mode: str = "thread",
    config=None,
) -> Dict[str, Any]:
    """The seeded ``scaling_storm`` fault class: scale decisions racing
    the federation's fault seams, under continuous wire load.

    1. **scale-up racing SIGKILL**: a forced controller scale-up spawns
       a worker; the moment it joins it is killed — the half-born
       member must die as an ordinary ``process_kill``, never wedge the
       ring;
    2. **rejoin racing drain**: a worker is hung past its lease, ages
       out, wakes, rejoins — and a forced scale-down drains EXACTLY
       that worker while its rejoin is still warm (this also exercises
       the backoff'd re-hello path);
    3. **partition during scale-down**: one worker is partitioned while
       a forced scale-down drains ANOTHER — the fleet transitions with
       its capacity ambiguous, then the partitioned worker rejoins.

    Exit contract: every request terminal, ZERO double completions,
    stale drops bounded by reroutes (+ slack), every phase observed —
    only then does ``scaling_storm`` count as observed."""
    import random as _random

    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.serve.federation import FederationPlane
    from rca_tpu.serve.request import ServeRequest
    from rca_tpu.util.threads import make_thread

    if worker_mode not in ("thread", "process"):
        raise ValueError(
            f"worker_mode must be 'thread' or 'process', got {worker_mode!r}"
        )
    rng = _random.Random(seed)
    case = synthetic_cascade_arrays(services, n_roots=1, seed=seed)
    nprng = np.random.default_rng(seed)
    process_mode = worker_mode == "process"
    plane = FederationPlane(
        workers=workers, config=config, heartbeat_s=heartbeat_s,
        spawn_workers=process_mode,
    )
    fleet: Dict[int, ThreadWorker] = {}
    spawner = None
    engine = None
    if not process_mode:
        engine = _thread_fleet_engine()
        spawner = thread_fleet_spawner(plane, fleet, engine=engine,
                                       config=config)
    # wide-open rules: nothing fires organically — every transition in
    # the storm is a FORCED controller decision, deliberately timed
    # against the fault seams
    storm_rules = ScaleRuleSet(rules=(
        ScaleRule("storm-up", "queue_depth", ">", 1e9, 3600.0, "up", 1),
        ScaleRule("storm-down", "queue_p99_ms", "<", 0.0, 3600.0,
                  "down", 1),
    ))
    controller = AutoscaleController(
        plane, min_workers=1, max_workers=max_workers, rules=storm_rules,
        cooldown_s=0.05, interval_s=0.5, spawner=spawner,
    )
    ttl = plane.leases.ttl_s
    submitted: List[ServeRequest] = []
    stop_load = threading.Event()

    def load() -> None:
        i = 0
        while not stop_load.is_set():
            feats = np.clip(
                case.features + nprng.uniform(
                    0, 0.05, case.features.shape
                ).astype(np.float32),
                0, 1,
            )
            req = ServeRequest(
                tenant=f"storm-{i % 3}", features=feats,
                dep_src=case.dep_src, dep_dst=case.dep_dst,
                names=case.names, k=3,
            )
            submitted.append(req)
            plane.submit(req)
            i += 1
            stop_load.wait(0.03)

    def wait_event(pred, deadline: float) -> bool:
        while plane.clock() < deadline:
            if any(pred(e) for e in list(plane.events)):
                return True
            stop_load.wait(0.05)
        return False

    def downed(wid: int, klass: str):
        return lambda e: (
            e["event"] == "worker_down"
            and e["worker_id"] == wid and e.get("class") == klass
        )

    def rejoined(wid: int, after: float):
        return lambda e: (
            e["event"] == "rejoin" and e["worker_id"] == wid
            and e["t"] >= after
        )

    def scaled_down(wid: int):
        return lambda e: (
            e["event"] == "worker_scaled_down" and e["worker_id"] == wid
        )

    phases: List[Dict[str, Any]] = []
    with plane:
        if not process_mode:
            for i in range(workers):
                spawner(i)
        if not plane.wait_ready(workers, timeout_s=timeout_s / 2):
            raise RuntimeError(
                "scaling storm: initial fleet failed to join: "
                f"{plane.worker_table()}"
            )
        controller.start(spawn_min=False)
        loader = make_thread(load, name="storm-load", daemon=True)
        loader.start()
        try:
            # 1. scale-up racing SIGKILL
            d1 = controller.force("up", rule="storm-spawn")
            new_wid = d1["workers"][0] if d1["workers"] else -1
            joined = wait_event(
                lambda e: (e["event"] == "worker_joined"
                           and e["worker_id"] == new_wid),
                plane.clock() + timeout_s / 4,
            )
            plane.kill_worker(new_wid)
            kill_seen = wait_event(
                downed(new_wid, "process_kill"),
                plane.clock() + timeout_s / 4,
            )
            phases.append({
                "race": "scaleup_vs_kill", "worker": new_wid,
                "observed": bool(joined and kill_seen),
            })

            # 2. rejoin racing drain
            victims = [
                w for w in plane.live_workers() if w != new_wid
            ]
            hang_w = victims[rng.randrange(len(victims))]
            t_h = plane.clock()
            plane.hang_worker(hang_w, for_s=ttl * 2.5)
            hang_seen = wait_event(
                downed(hang_w, "worker_hang"),
                plane.clock() + timeout_s / 4,
            )
            rejoin_seen = wait_event(
                rejoined(hang_w, t_h), plane.clock() + timeout_s / 4,
            )
            controller.force("down", rule="storm-drain-rejoined",
                             victims=[hang_w])
            drain_seen = wait_event(
                scaled_down(hang_w), plane.clock() + timeout_s / 4,
            )
            phases.append({
                "race": "rejoin_vs_drain", "worker": hang_w,
                "observed": bool(hang_seen and rejoin_seen and drain_seen),
            })

            # 3. partition during scale-down (of a DIFFERENT worker)
            live = plane.live_workers()
            part_w = live[rng.randrange(len(live))]
            others = [w for w in live if w != part_w]
            drain_w = others[rng.randrange(len(others))]
            t_p = plane.clock()
            plane.partition(part_w, for_s=ttl * 2.5)
            controller.force("down", rule="storm-drain-partitioned",
                             victims=[drain_w])
            down_seen = wait_event(
                scaled_down(drain_w), plane.clock() + timeout_s / 4,
            )
            part_seen = wait_event(
                downed(part_w, "coordinator_partition"),
                plane.clock() + timeout_s / 4,
            )
            part_rejoin = wait_event(
                rejoined(part_w, t_p), plane.clock() + timeout_s / 4,
            )
            phases.append({
                "race": "partition_vs_scaledown",
                "partitioned": part_w, "drained": drain_w,
                "observed": bool(down_seen and part_seen and part_rejoin),
            })

            stop_load.wait(ttl)
        finally:
            stop_load.set()
            loader.join(10.0)
            controller.stop()
        responses = [r.result(timeout_s / 2) for r in submitted]
        double = plane.sink.double_completions
        stale = plane.stale_responses
        reroutes = plane.reroutes
        events = list(plane.events)
        plane_classes = plane.fault_classes_observed()
    for tw in fleet.values():
        tw.close(5.0)

    by_status: Dict[str, int] = {}
    for r in responses:
        by_status[r.status] = by_status.get(r.status, 0) + 1
    all_terminal = all(r.done() for r in submitted)
    stale_bound = reroutes + 8
    stale_bounded = stale <= stale_bound
    scale_ups = sum(1 for e in events if e["event"] == "scale_up")
    scale_downs = sum(1 for e in events if e["event"] == "scale_down")
    storm_observed = all(p["observed"] for p in phases)
    ok = (
        all_terminal
        and double == 0
        and stale_bounded
        and storm_observed
        and scale_ups >= 1
        and scale_downs >= 2
    )
    classes = sorted(
        set(plane_classes)
        | (set(SCALING_FAULT_CLASSES) if storm_observed else set())
    )
    return {
        "ok": bool(ok),
        "worker_mode": worker_mode,
        "workers": workers,
        "requests": len(submitted),
        "by_status": by_status,
        "all_terminal": bool(all_terminal),
        "double_completions": double,
        "stale_responses": stale,
        "stale_bound": stale_bound,
        "stale_bounded": bool(stale_bounded),
        "reroutes": reroutes,
        "scale_ups": scale_ups,
        "scale_downs": scale_downs,
        "fault_classes_observed": classes,
        "phases": phases,
        "lease_ttl_s": ttl,
        "rejoins": sum(1 for e in events if e["event"] == "rejoin"),
    }
