"""Multi-tenant serving scheduler: continuous shape-bucketed batching.

Every pre-serve entry point (``rca analyze``, ``rca hypotheses``,
``rca stream``) owns the device exclusively — two concurrent
investigations serialize with zero batching, even though the engine's
``analyze_batch`` scores many hypotheses in one dispatch at near-zero
marginal cost per extra lane.  This package is the missing serving
layer (SERVING.md):

- :mod:`rca_tpu.serve.request` — the request/response contract;
- :mod:`rca_tpu.serve.queue` — bounded admission + per-tenant weighted
  fair queuing + priorities + deadline shedding;
- :mod:`rca_tpu.serve.batcher` — shape-bucket grouping with the
  max-batch / max-wait flush policy;
- :mod:`rca_tpu.serve.dispatcher` — the coalesced device dispatch
  (dispatch/fetch split; fetch is THE sync point, lint-enforced);
- :mod:`rca_tpu.serve.loop` — the continuous-batching worker with
  breaker-gated degradation;
- :mod:`rca_tpu.serve.replica` / :mod:`rca_tpu.serve.pool` — the
  multi-replica, multi-device serving plane (ISSUE 8): N engine
  replicas (dense/sharded mix over carved device groups) behind the
  shared queue, shape-bucket-sticky routing, per-replica breakers, and
  work-stealing failover with exactly-once completion;
- :mod:`rca_tpu.serve.federation` / :mod:`rca_tpu.serve.worker` /
  :mod:`rca_tpu.serve.fedwire` — the CROSS-PROCESS plane (ISSUE 15):
  worker processes with lease-based liveness, consistent-hash routing
  on graph digest, and drain-and-reroute on process death holding the
  same exactly-once contract across the wire (SERVING.md §Federation);
- :mod:`rca_tpu.serve.client` — in-process client, the coordinator's
  EngineAPI facade, and the ``rca serve --selftest`` harness;
- :mod:`rca_tpu.serve.metrics` — per-tenant queue/occupancy metrics.

The loop optionally writes through a flight recorder
(:class:`rca_tpu.replay.Recorder`, ``ServeLoop(recorder=...)`` /
``rca serve --record``): every OK response logs its full request inputs
and ranking as a self-contained frame, replayable solo via
``rca replay`` under the coalesced-vs-solo parity contract (REPLAY.md).
"""

from rca_tpu.serve.batcher import ShapeBucketBatcher
from rca_tpu.serve.client import ServeClient, ServeEngineAdapter, serve_selftest
from rca_tpu.serve.dispatcher import BatchDispatcher, BatchHandle
from rca_tpu.serve.federation import (
    FED_FAULT_CLASSES,
    FederationPlane,
    HashRing,
    LeaseTable,
)
from rca_tpu.serve.loop import ServeLoop
from rca_tpu.serve.metrics import ServeMetrics
from rca_tpu.serve.pool import ServePool
from rca_tpu.serve.queue import RequestQueue
from rca_tpu.serve.replica import (
    CompletionSink,
    ReplicaWorker,
    build_replica_engines,
)
from rca_tpu.serve.request import (
    PRIORITY_BATCH,
    PRIORITY_HIGH,
    PRIORITY_NORMAL,
    ServeRequest,
    ServeResponse,
    graph_key,
)

__all__ = [
    "ShapeBucketBatcher",
    "ServePool",
    "FederationPlane",
    "FED_FAULT_CLASSES",
    "HashRing",
    "LeaseTable",
    "ReplicaWorker",
    "CompletionSink",
    "build_replica_engines",
    "ServeClient",
    "ServeEngineAdapter",
    "serve_selftest",
    "BatchDispatcher",
    "BatchHandle",
    "ServeLoop",
    "ServeMetrics",
    "RequestQueue",
    "ServeRequest",
    "ServeResponse",
    "graph_key",
    "PRIORITY_HIGH",
    "PRIORITY_NORMAL",
    "PRIORITY_BATCH",
]
