"""Federation worker: one serve process in the fleet (ISSUE 15).

``python -m rca_tpu.serve.worker --connect HOST:PORT --worker-id N``
runs ONE slice of the federated serving plane: a full
:class:`rca_tpu.serve.loop.ServeLoop` (or, when ``RCA_SERVE_REPLICAS``
/ ``RCA_SERVE_REPLICA_MIX`` names more than one replica, a whole
:class:`rca_tpu.serve.pool.ServePool`) over this process's own JAX
devices, fronted by a control-channel connection back to the
:class:`rca_tpu.serve.federation.FederationPlane`.

Bootstrap goes through the :mod:`rca_tpu.parallel.distributed` seam
first — on a TPU pod every worker host runs this same program and the
mesh axes come from ``GRAPH_RULES`` exactly as in-process replicas do,
so a cross-host deployment is an environment change, not new code.  The
hello frame carries the bootstrap topology so the coordinator can see
what it federates.

Protocol behavior (see :mod:`rca_tpu.serve.fedwire`):

- hello → lease; heartbeats on the granted cadence renew it;
- a ``reject`` (stale lease — this worker was declared dead while it
  was hung or partitioned) triggers an explicit RE-HELLO for a fresh
  lease: rejoin is loud, never a silent resurrection;
- ``req`` frames become local :class:`ServeRequest` submissions; each
  completion is answered with a ``resp`` frame.  A request that was
  rerouted while this worker was presumed dead may still complete here
  — the coordinator drops that answer as stale (ITS pending table is
  the exactly-once arbiter, not this process);
- ``hang`` (chaos seam) suspends heartbeats for a window while leaving
  the socket — and local serving — untouched: the ``worker_hang``
  fault class from the inside;
- ``drain`` stops intake, finishes in flight, answers ``drained``, and
  exits 0.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from typing import Callable, Dict, List, Optional

from rca_tpu.serve.fedwire import (
    FrameConn,
    FrameError,
    PROTO,
    decode_request_kwargs,
    encode_response,
)
from rca_tpu.serve.request import ServeRequest
from rca_tpu.util.net import make_client_socket, parse_hostport
from rca_tpu.util.threads import make_lock, spawn

#: bound on one request's local serve time before the worker answers
#: ``error`` for it (the coordinator's deadline machinery is the real
#: latency policy; this only prevents a wedged local plane from
#: accumulating parked waiter threads forever)
REQUEST_TIMEOUT_S = 120.0

#: seeded jittered backoff for the re-hello loop (ISSUE 16 small fix):
#: a healing partition ages out MANY leases at once — without backoff
#: every survivor re-hellos in the same instant, a rejoin stampede on
#: the coordinator it just stopped being able to reach
REJOIN_BACKOFF_BASE_S = 0.05
REJOIN_BACKOFF_CAP_S = 2.0


def _registry_summary() -> Dict[str, float]:
    """The hello frame's kernel-registry digest: winning per-shape
    timing (ms) per ``n_pad`` tier, from this process's own
    :class:`KernelRegistry`.  Empty when nothing is compiled yet or the
    registry is unavailable — the field is OPTIONAL on the wire and the
    coordinator treats absence as 'no placement evidence'."""
    try:
        from rca_tpu.engine.registry import kernel_table

        out: Dict[str, float] = {}
        for row in kernel_table():
            n_pad = int(row.get("n_pad") or 0)
            timings = row.get("timings_ms") or {}
            winner = row.get("winner")
            ms = timings.get(winner) if winner else None
            if n_pad <= 0 or ms is None:
                continue
            key = str(n_pad)
            if key not in out or float(ms) < out[key]:
                out[key] = float(ms)
        return out
    except Exception:  # noqa: BLE001 - evidence is optional, never fatal
        return {}


def _headroom_summary() -> Optional[Dict[str, int]]:
    """The hello frame's device-memory digest from the kernelscope
    accountant — ``bytes_in_use`` lets the coordinator's headroom
    placement prefer the emptier device.  None when sampling fails
    (platforms without memory_stats): optional, like the registry."""
    try:
        from rca_tpu.observability.kernelscope import sample_device_memory

        mem = sample_device_memory()
        return {"bytes_in_use": int(mem["bytes_in_use"])}
    except Exception:  # noqa: BLE001 - evidence is optional, never fatal
        return None


class WorkerAgent:
    """The control-channel client around one local serving plane.

    ``loop`` is a STARTED ServeLoop/ServePool; the agent owns only the
    wire conversation.  The clock is injectable (nondet-discipline);
    heartbeat cadence comes from the coordinator's lease grant."""

    def __init__(
        self,
        worker_id: int,
        host: str,
        port: int,
        loop,
        clock: Callable[[], float] = time.monotonic,
        connect_timeout_s: float = 30.0,
        engine_tag: str = "",
        rejoin_seed: Optional[int] = None,
        sleeper: Callable[[float], None] = time.sleep,
        role: str = "serve",
    ):
        self.worker_id = int(worker_id)
        self.loop = loop
        self.clock = clock
        self.engine_tag = engine_tag
        # worker class (ISSUE 17): "serve" joins the serve ring;
        # "ingest" joins the ingest ring and hosts capture mirrors via
        # the runner attached at `self.ingest`
        self.role = str(role)
        self.ingest = None
        # seeded per-worker: every fleet member jitters DIFFERENTLY, so
        # a mass lease expiry heals as a spread, not a stampede
        self._rejoin_rng = random.Random(
            rejoin_seed if rejoin_seed is not None else worker_id
        )
        self._rejoin_attempts = 0
        self.rejoin_delays: List[float] = []
        self.sleeper = sleeper
        sock = make_client_socket(
            f"fed-worker{worker_id}", host, port,
            timeout_s=connect_timeout_s,
        )
        self.conn = FrameConn(sock, name=f"fed-worker{worker_id}")
        self._lock = make_lock("WorkerAgent._lock")
        self.lease_id: Optional[str] = None
        self.heartbeat_s = 0.5
        self.hang_until = 0.0
        self.draining = False
        self.inflight = 0
        self.served = 0
        self.acks = 0
        self._hb_seq = 0
        self._hb_thread = None

    # -- handshake ------------------------------------------------------------
    def _hello(self, with_lease: bool = True) -> bool:
        from rca_tpu.parallel.distributed import initialize_distributed

        boot = initialize_distributed()
        msg = {
            "t": "hello", "proto": PROTO, "worker_id": self.worker_id,
            "pid": os.getpid(),
            "role": self.role,
            "engine": self.engine_tag,
            "process_count": boot.get("process_count"),
            "process_index": boot.get("process_index"),
            "local_devices": boot.get("local_device_count"),
        }
        # placement evidence (ISSUE 16): OPTIONAL fields — a bare hello
        # (old workers, fresh processes) still joins, it just gets pure
        # rendezvous placement
        registry = _registry_summary()
        if registry:
            msg["registry"] = registry
        headroom = _headroom_summary()
        if headroom is not None:
            msg["headroom"] = headroom
        with self._lock:
            if with_lease and self.lease_id is not None:
                msg["lease_id"] = self.lease_id
        return self.conn.send(msg)

    def _next_rejoin_delay(self) -> float:
        """Exponential backoff with full-range jitter for the re-hello
        loop: ``min(cap, base * 2^attempts) * uniform(0.5, 1.5)``.
        Every call is a DISTINCT delay (the regression test asserts it),
        and the sequence is seeded — replayable stampede spreading."""
        raw = min(
            REJOIN_BACKOFF_CAP_S,
            REJOIN_BACKOFF_BASE_S * (2.0 ** self._rejoin_attempts),
        )
        self._rejoin_attempts += 1
        delay = raw * (0.5 + self._rejoin_rng.random())
        self.rejoin_delays.append(delay)
        return delay

    # -- heartbeats -----------------------------------------------------------
    def _hb_loop(self) -> None:
        """Fine-grained scheduler: wake at a fraction of the cadence and
        send when due, so the FIRST heartbeat lands well inside the
        lease TTL even when the granted cadence is much faster than the
        default (the coordinator, not this process, owns the cadence)."""
        last_sent = 0.0
        while True:
            with self._lock:
                lease, hung = self.lease_id, self.hang_until
                cadence = self.heartbeat_s
                # draining is NOT an exit: a worker finishing in-flight
                # work is alive and must keep its lease, or a drain
                # longer than the TTL reads as worker_hang death and
                # the retirement never completes (scaling_storm's
                # rejoin-vs-drain race found this)
                if self.conn.closed:
                    return
            now = self.clock()
            if (lease is not None and now >= hung
                    and now - last_sent >= cadence):
                # between leases or hung (chaos): stay quiet instead
                self._hb_seq += 1
                if not self.conn.send({
                    "t": "hb", "worker_id": self.worker_id,
                    "lease_id": lease, "seq": self._hb_seq,
                }):
                    return   # coordinator gone; read loop sees EOF too
                last_sent = now
            time.sleep(max(0.005, cadence / 4.0))

    # -- request handling -----------------------------------------------------
    def _serve_one(self, request_id: str, req: ServeRequest) -> None:
        """Waiter-thread body: park on the local plane's completion and
        answer over the wire.  Send failures are ignored — a vanished
        coordinator re-places the request elsewhere; its pending table
        arbitrates exactly-once, not this send."""
        try:
            resp = req.result(REQUEST_TIMEOUT_S)
        except TimeoutError:
            from rca_tpu.serve.request import ServeResponse

            resp = ServeResponse(
                status="error", request_id=req.request_id,
                tenant=req.tenant,
                detail=f"worker timeout after {REQUEST_TIMEOUT_S}s",
            )
        self.conn.send(encode_response(request_id, resp, self.engine_tag))
        with self._lock:
            self.inflight -= 1
            self.served += 1

    def _on_request(self, msg) -> None:
        request_id = str(msg.get("request_id"))
        try:
            kwargs = decode_request_kwargs(msg)
            req = ServeRequest(**kwargs)
        except Exception as exc:  # noqa: BLE001 - answer, never wedge
            self.conn.send({
                "t": "resp", "request_id": request_id, "status": "error",
                "ranked": [], "batch_size": 0, "engine": self.engine_tag,
                "detail": f"bad request frame: {type(exc).__name__}: {exc}",
            })
            return
        with self._lock:
            if self.draining:
                self.conn.send({
                    "t": "resp", "request_id": request_id,
                    "status": "shed", "ranked": [], "batch_size": 0,
                    "engine": self.engine_tag, "detail": "worker draining",
                })
                return
            self.inflight += 1
        self.loop.submit(req)
        spawn(
            self._serve_one,
            name=f"rca-fedw{self.worker_id}-wait{request_id[:8]}",
            daemon=True, args=(request_id, req),
        )

    # -- main loop ------------------------------------------------------------
    def run(self) -> int:
        """Connect → hello → serve until drain or coordinator loss.
        Returns the process exit code."""
        if not self._hello(with_lease=False):
            return 2
        self._hb_thread = spawn(
            self._hb_loop, name=f"rca-fedw{self.worker_id}-hb",
            daemon=True,
        )
        while True:
            try:
                msg = self.conn.recv()
            except FrameError:
                return 2
            if msg is None:
                # coordinator gone: nothing to answer to — exit; the
                # supervisor (or operator) restarts the fleet member
                return 0 if self.draining else 3
            t = msg.get("t")
            if t == "lease":
                with self._lock:
                    self.lease_id = str(msg.get("lease_id"))
                    self.heartbeat_s = float(
                        msg.get("heartbeat_s") or self.heartbeat_s
                    )
                self._rejoin_attempts = 0   # granted: backoff re-arms
            elif t == "reject":
                if str(msg.get("reason")) == "stale_lease":
                    # declared dead while hung/partitioned: rejoin with
                    # an explicit fresh hello (stale lease dropped) —
                    # after a jittered backoff, so a healing partition's
                    # worth of workers doesn't stampede the coordinator
                    with self._lock:
                        self.lease_id = None
                    self.sleeper(self._next_rejoin_delay())
                    if not self._hello(with_lease=False):
                        return 3
                else:
                    return 2
            elif t == "hb_ack":
                self.acks += 1
            elif t == "req":
                self._on_request(msg)
            elif t in ("ingest_assign", "ingest_unassign"):
                if self.ingest is not None:
                    self.ingest.handle(msg)
            elif t == "hang":
                with self._lock:
                    self.hang_until = self.clock() + float(
                        msg.get("for_s") or 0.0
                    )
            elif t == "drain":
                with self._lock:
                    self.draining = True
                if self.ingest is not None:
                    self.ingest.stop()
                deadline = self.clock() + REQUEST_TIMEOUT_S
                while self.clock() < deadline:
                    with self._lock:
                        if self.inflight == 0:
                            break
                    time.sleep(0.01)
                self.conn.send({"t": "drained", "served": self.served})
                return 0

    def close(self) -> None:
        self.conn.close()


def build_local_plane(config=None):
    """The worker's serving plane from its OWN environment: one dense
    engine by default; a replica mix (``RCA_SERVE_REPLICAS`` /
    ``RCA_SERVE_REPLICA_MIX``) builds a full in-process pool over this
    worker's devices — federation of pools, not just loops."""
    from rca_tpu.config import ServeConfig
    from rca_tpu.engine import make_engine
    from rca_tpu.serve.loop import ServeLoop
    from rca_tpu.serve.pool import ServePool

    cfg = config or ServeConfig.from_env()
    if len(cfg.replica_specs()) > 1:
        return ServePool(config=cfg), "serve+pool"
    engine = make_engine()
    return ServeLoop(engine=engine, config=cfg), getattr(
        engine, "engine_tag", type(engine).__name__
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="rca_tpu.serve.worker",
        description="federation serve worker (SERVING.md §Federation)",
    )
    parser.add_argument("--connect", required=True, metavar="HOST:PORT",
                        help="coordinator control address")
    parser.add_argument("--worker-id", type=int, required=True,
                        dest="worker_id")
    parser.add_argument("--role", choices=("serve", "ingest"),
                        default="serve",
                        help="worker class: serve (default) joins the "
                             "serve ring; ingest hosts cluster capture "
                             "mirrors (SERVING.md §Ingest workers)")
    args = parser.parse_args(argv)
    host, port = parse_hostport(args.connect, 0)
    if args.role == "ingest":
        # no engine, no serve plane: ingest workers never see requests
        from rca_tpu.serve.ingest import NullServePlane

        loop, tag = NullServePlane(), "ingest"
    else:
        loop, tag = build_local_plane()
    loop.start()
    agent = WorkerAgent(args.worker_id, host, port, loop, engine_tag=tag,
                        role=args.role)
    if args.role == "ingest":
        from rca_tpu.serve.ingest import IngestRunner

        agent.ingest = IngestRunner(agent)
    # the one stdout line: machine-parseable liveness for the procs
    # seam's capture (everything else goes to stderr)
    print(json.dumps({
        "worker": args.worker_id,
        "pid": os.getpid(),
        "coordinator": args.connect,
        "engine": tag,
    }), flush=True)
    try:
        code = agent.run()
    finally:
        agent.close()
        loop.stop()
    print(json.dumps({
        "worker": args.worker_id, "exit": code,
        "served": agent.served,
    }), file=sys.stderr, flush=True)
    return code


if __name__ == "__main__":   # pragma: no cover - subprocess entry
    sys.exit(main())
