"""Bounded multi-tenant request queue: weighted-fair + priority + deadlines.

Scheduling model (SERVING.md):

- **admission**: the queue is hard-capped (``RCA_SERVE_QUEUE_CAP``); a
  submit against a full queue is rejected immediately (``queue_full``)
  instead of growing an unbounded backlog — the caller gets backpressure
  it can act on, and queue time stays bounded for everyone already in;
- **weighted fair queuing**: each tenant holds a FIFO lane; every request
  is stamped a virtual finish tag ``max(vclock, tenant_vtime) + cost/weight``
  at admission (start-time fair queuing).  Pops take the head-of-line
  request with the smallest tag, so a tenant flooding the queue cannot
  starve the others — its requests just stack up LATER virtual time while
  light tenants' heads stay early;
- **priority classes**: strict across tenants (``PRIORITY_HIGH`` pops
  before any normal request); the fair tags order requests WITHIN a
  class.  Lanes stay FIFO per tenant — a tenant's own requests never
  reorder;
- **deadline shedding**: :meth:`shed_expired` removes requests whose
  deadline passed while queued, so an expired request never reaches the
  batcher, let alone a device slot.

All methods are thread-safe; the scheduler's clock is injectable so the
policy tests drive it with fake time.
"""

from __future__ import annotations

import collections
import time
from typing import Callable, Deque, Dict, List, Optional

from rca_tpu.serve.request import ServeRequest
from rca_tpu.util.threads import make_condition


class RequestQueue:
    def __init__(
        self,
        cap: int = 256,
        clock: Callable[[], float] = time.monotonic,
    ):
        if cap < 1:
            raise ValueError(f"queue cap must be >= 1, got {cap}")
        self.cap = int(cap)
        self.clock = clock
        self._cond = make_condition("RequestQueue._cond")
        self._lanes: Dict[str, Deque[ServeRequest]] = {}
        self._vtime: Dict[str, float] = {}    # per-tenant last finish tag
        self._weights: Dict[str, float] = {}
        self._vclock = 0.0                    # virtual time of last pop
        self._size = 0
        self._seq = 0                         # admission counter (tie-break)

    # -- tenant weights ------------------------------------------------------
    def set_weight(self, tenant: str, weight: float) -> None:
        """A tenant's fair share (default 1.0): weight 2 drains twice as
        fast as weight 1 under contention."""
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        with self._cond:
            self._weights[tenant] = float(weight)

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, 1.0)

    # -- introspection -------------------------------------------------------
    def __len__(self) -> int:
        with self._cond:
            return self._size

    def depth_by_tenant(self) -> Dict[str, int]:
        with self._cond:
            return {t: len(dq) for t, dq in self._lanes.items() if dq}

    # -- admission -----------------------------------------------------------
    def submit(self, req: ServeRequest) -> bool:
        """Admit a request; False when the queue is at capacity (the
        caller responds ``queue_full`` — the request is NOT queued)."""
        with self._cond:
            if self._size >= self.cap:
                return False
            now = self.clock()
            req.enqueued_at = now
            self._seq += 1
            req.seq = self._seq
            start = max(self._vclock, self._vtime.get(req.tenant, 0.0))
            req.vtag = start + max(req.cost, 1e-9) / self.weight(req.tenant)
            self._vtime[req.tenant] = req.vtag
            self._lanes.setdefault(
                req.tenant, collections.deque()
            ).append(req)
            self._size += 1
            self._cond.notify_all()
            return True

    # -- service order -------------------------------------------------------
    def pop(self) -> Optional[ServeRequest]:
        """The next request in service order: strict priority class first,
        then smallest virtual finish tag, then admission order."""
        with self._cond:
            best_tenant = None
            best_key = None
            for tenant, lane in self._lanes.items():
                if not lane:
                    continue
                head = lane[0]
                key = (head.priority, head.vtag, head.seq)
                if best_key is None or key < best_key:
                    best_key = key
                    best_tenant = tenant
            if best_tenant is None:
                return None
            req = self._lanes[best_tenant].popleft()
            self._size -= 1
            self._vclock = max(self._vclock, req.vtag)
            return req

    # -- deadline shedding ---------------------------------------------------
    def shed_expired(self, now: Optional[float] = None) -> List[ServeRequest]:
        """Remove (and return) every queued request whose deadline has
        passed — the caller responds ``shed``; none of them will ever
        reach a device slot."""
        with self._cond:
            if now is None:
                now = self.clock()
            shed: List[ServeRequest] = []
            for tenant, lane in self._lanes.items():
                if not lane:
                    continue
                keep = collections.deque()
                for req in lane:
                    (shed if req.expired(now) else keep).append(req)
                self._lanes[tenant] = keep
            self._size -= len(shed)
            return shed

    # -- worker parking ------------------------------------------------------
    def wait_for_work(self, timeout: Optional[float] = None) -> bool:
        """Park until something is queued (or the timeout lapses);
        returns whether the queue is non-empty."""
        with self._cond:
            if self._size:
                return True
            self._cond.wait(timeout)
            return self._size > 0

    def kick(self) -> None:
        """Wake a parked worker (shutdown path)."""
        with self._cond:
            self._cond.notify_all()
