"""Per-tenant serving metrics, flowing through the obslog accumulators.

One :class:`ServeMetrics` per :class:`rca_tpu.serve.loop.ServeLoop`:
counters per tenant (submitted / answered / shed / rejected / degraded /
errors), time-in-queue samples per tenant (p50/p99 via
:class:`rca_tpu.obslog.profiling.PhaseStats` — the same accumulator the
streaming tick phases use), instantaneous queue depth at each admission,
and batch occupancy per device dispatch.  Everything is thread-safe: the
submit path and the serve worker record concurrently.
"""

from __future__ import annotations

import collections
from typing import Dict, List, Optional

from rca_tpu.config import slo_ms
from rca_tpu.observability.export import LatencyHistogram
from rca_tpu.obslog.profiling import PhaseStats
from rca_tpu.util.threads import make_lock

_COUNTER_KEYS = (
    "submitted", "answered", "shed", "rejected", "degraded", "errors",
)

#: recent time-in-queue samples kept for the autoscaler's WINDOWED p99
#: (ISSUE 16).  PhaseStats quantiles are all-time — after one surge the
#: all-time p99 never falls again, so a scale-DOWN signal fed by it
#: could never fire; the controller reads this bounded window instead.
_RECENT_QUEUE_CAP = 512

_SCALE_EVENTS = (
    "scale_ups", "scale_downs", "holds", "cooldown_skips", "clamps",
    "forced",
)


class ServeMetrics:
    def __init__(self, slo_ms_target: Optional[float] = None) -> None:
        self._lock = make_lock("ServeMetrics._lock")
        # per-tenant SLO telemetry (ISSUE 11): submit→completion duration
        # histograms in proper le-bucket exposition form (burn rate is a
        # PromQL division, so it needs buckets, not quantile gauges) plus
        # the burn counter itself — completions slower than RCA_SLO_MS,
        # or terminal failures, burn budget
        self.slo_ms_target = (
            float(slo_ms_target) if slo_ms_target is not None else slo_ms()
        )
        self._duration: Dict[str, LatencyHistogram] = {}
        self._slo_breaches: Dict[str, int] = {}
        self._counts: Dict[str, Dict[str, int]] = {}
        self._queue_ms = PhaseStats()      # one phase per tenant
        self._occupancy: List[int] = []
        self._depth_peak = 0
        self.dispatched_requests = 0
        # dispatcher cache observability (ISSUE 6): prepared-graph cache
        # hits/misses/evictions, plus per-tenant resident-buffer reuse —
        # how many requests rode the delta-scatter path and how many rows
        # the resident base saved them from uploading
        self._graph_cache = {"hit": 0, "miss": 0, "eviction": 0}
        self._resident: Dict[str, Dict[str, int]] = {}
        # causelens (ISSUE 14): per-tenant explain-request counts — the
        # requests that asked for (and were charged) an attribution pass
        self._explained: Dict[str, int] = {}
        # serve-pool observability (ISSUE 8): per-replica dispatch
        # counters + occupancy samples, work-steal accounting, and the
        # last reported breaker/liveness state — `rca serve --selftest`
        # prints these and bench's serve_pool section reads them
        self._replicas: Dict[int, Dict[str, object]] = {}
        self._replica_occ = PhaseStats()   # one phase per replica id
        # elasticmesh (ISSUE 16): the autoscaler's windowed queue-time
        # signal, its action counters, and shape-aware placement
        # outcomes (preferred = a registry/headroom-informed pick,
        # rendezvous = the hash-ring fallback)
        self._recent_queue_ms: "collections.deque[float]" = (
            collections.deque(maxlen=_RECENT_QUEUE_CAP)
        )
        self._scale_events: Dict[str, int] = {k: 0 for k in _SCALE_EVENTS}
        self._placement: Dict[str, int] = {"preferred": 0, "rendezvous": 0}

    def _tenant(self, tenant: str) -> Dict[str, int]:
        return self._counts.setdefault(
            tenant, {k: 0 for k in _COUNTER_KEYS}
        )

    # -- recording -----------------------------------------------------------
    def submitted(self, tenant: str, queue_depth: int) -> None:
        with self._lock:
            self._tenant(tenant)["submitted"] += 1
            self._depth_peak = max(self._depth_peak, queue_depth)

    def answered(self, tenant: str, queue_ms: float) -> None:
        with self._lock:
            self._tenant(tenant)["answered"] += 1
            self._queue_ms.record(tenant, queue_ms)
            self._recent_queue_ms.append(float(queue_ms))

    def shed(self, tenant: str) -> None:
        with self._lock:
            self._tenant(tenant)["shed"] += 1

    def rejected(self, tenant: str) -> None:
        with self._lock:
            self._tenant(tenant)["rejected"] += 1

    def degraded(self, tenant: str) -> None:
        with self._lock:
            self._tenant(tenant)["degraded"] += 1

    def errors(self, tenant: str) -> None:
        with self._lock:
            self._tenant(tenant)["errors"] += 1

    def explained(self, tenant: str) -> None:
        """One request served with its causelens attribution (the
        ``ServeRequest.explain`` flag — ISSUE 14)."""
        with self._lock:
            self._explained[tenant] = self._explained.get(tenant, 0) + 1

    def request_duration(
        self, tenant: str, seconds: float, ok: bool,
    ) -> None:
        """One terminal completion's submit→completion wall time.  A
        completion burns SLO budget when it was slower than the target
        (``RCA_SLO_MS``) or was not served (``shed``/``error`` — a
        failure is never within SLO, however fast)."""
        with self._lock:
            hist = self._duration.get(tenant)
            if hist is None:
                hist = self._duration[tenant] = LatencyHistogram()
            hist.record(seconds)
            if not ok or seconds * 1e3 > self.slo_ms_target:
                self._slo_breaches[tenant] = (
                    self._slo_breaches.get(tenant, 0) + 1
                )

    def record_batch(self, size: int) -> None:
        with self._lock:
            self._occupancy.append(int(size))
            self.dispatched_requests += int(size)

    def graph_cache(self, event: str) -> None:
        """One prepared-graph cache event: ``hit``/``miss``/``eviction``
        (the dispatcher calls this from its staging lookup)."""
        with self._lock:
            self._graph_cache[event] += 1

    # -- serve pool (ISSUE 8) ------------------------------------------------
    def _replica(self, replica_id: int) -> Dict[str, object]:
        return self._replicas.setdefault(int(replica_id), {
            "batches": 0, "requests": 0,
            "stolen_from": 0, "stolen_to": 0,
            "state": "closed",
        })

    def replica_batch(self, replica_id: int, width: int) -> None:
        """One device batch fetched OK on a replica."""
        with self._lock:
            rec = self._replica(replica_id)
            rec["batches"] += 1
            rec["requests"] += int(width)

    def replica_occupancy(self, replica_id: int, occupancy: int) -> None:
        """One occupancy sample: staged + in-flight requests the replica
        held when sampled (taken per scheduling iteration that did
        work)."""
        with self._lock:
            self._replica(replica_id)
            self._replica_occ.record(f"r{int(replica_id)}", float(occupancy))

    def stolen(self, from_replica: int, to_replica: int, n: int) -> None:
        """``n`` staged requests moved off a dead/open replica onto a
        survivor by the work-stealing rebalance."""
        with self._lock:
            self._replica(from_replica)["stolen_from"] += int(n)
            self._replica(to_replica)["stolen_to"] += int(n)

    def replica_state(self, replica_id: int, state: str) -> None:
        """Latest breaker/liveness state (``closed``/``open``/
        ``half-open``/``dead``) the replica reported."""
        with self._lock:
            self._replica(replica_id)["state"] = state

    def resident_reuse(self, tenant: str, rows_saved: int) -> None:
        """One request served via the resident delta path: ``rows_saved``
        feature rows came from the device-pinned base instead of the
        host upload."""
        with self._lock:
            rec = self._resident.setdefault(
                tenant, {"delta_requests": 0, "rows_saved": 0}
            )
            rec["delta_requests"] += 1
            rec["rows_saved"] += int(rows_saved)

    # -- elasticmesh (ISSUE 16) ----------------------------------------------
    def scale_event(self, kind: str) -> None:
        """One autoscaler outcome: ``scale_ups``/``scale_downs``/
        ``holds``/``cooldown_skips``/``clamps``/``forced``."""
        with self._lock:
            self._scale_events[kind] += 1

    def placement(self, outcome: str) -> None:
        """One routing pick: ``preferred`` (registry/headroom-informed)
        or ``rendezvous`` (the hash-ring fallback)."""
        with self._lock:
            self._placement[outcome] += 1

    def autoscale_signals(self) -> Dict[str, object]:
        """The controller's metric-side inputs in one lock acquisition:
        the WINDOWED cross-tenant queue-time p99 (last
        ``_RECENT_QUEUE_CAP`` completions — all-time quantiles can never
        fall after a surge, see ``_RECENT_QUEUE_CAP``) and the running
        SLO-breach total (the controller differentiates it into a burn
        rate between sweeps)."""
        with self._lock:
            recent = sorted(self._recent_queue_ms)
            breaches = sum(self._slo_breaches.values())
        p99 = (
            recent[min(len(recent) - 1, int(len(recent) * 0.99))]
            if recent else None
        )
        return {
            "queue_ms_p99_recent": p99,
            "recent_samples": len(recent),
            "slo_breach_total": breaches,
        }

    # -- reporting -----------------------------------------------------------
    def snapshot(self) -> Dict[str, object]:
        """One CONSISTENT deep copy of all raw state, taken under the
        lock (ISSUE 9 small fix).  The previous ``summary()`` derived
        quantiles (per-tenant sorts, per-replica sorts) while HOLDING
        the lock, so every ``/metrics`` scrape or pool status read
        blocked the replicas' hot-path counter updates for the whole
        computation — and any caller reaching into the accumulators
        directly saw them mid-mutation.  Now the lock covers only the
        copy; derivation happens on the exporter's thread over data no
        replica can touch.  Audited with gravelock/rsan
        (tests/test_gateway.py::test_metrics_snapshot_consistent_under_rsan)."""
        with self._lock:
            return {
                "counts": {t: dict(c) for t, c in self._counts.items()},
                "queue_ms": self._queue_ms.snapshot(),
                "occupancy": list(self._occupancy),
                "depth_peak": self._depth_peak,
                "dispatched_requests": self.dispatched_requests,
                "graph_cache": dict(self._graph_cache),
                "resident": {
                    t: dict(r) for t, r in self._resident.items()
                },
                "explained": dict(self._explained),
                "replicas": {
                    rid: dict(rec)
                    for rid, rec in self._replicas.items()
                },
                "replica_occ": self._replica_occ.snapshot(),
                "duration": {
                    t: h.to_dict() for t, h in self._duration.items()
                },
                "slo_breaches": dict(self._slo_breaches),
                "slo_ms": self.slo_ms_target,
                "scale_events": dict(self._scale_events),
                "placement": dict(self._placement),
            }

    def summary(self) -> Dict[str, object]:
        snap = self.snapshot()
        counts: Dict[str, Dict[str, int]] = snap["counts"]
        resident: Dict[str, Dict[str, int]] = snap["resident"]
        queue_ms = snap["queue_ms"]
        per_tenant = {}
        # union: a tenant that only ever rode the delta path (direct
        # dispatcher callers) still shows its reuse counters
        for tenant in sorted(set(counts) | set(resident)):
            tcounts = counts.get(tenant, {k: 0 for k in _COUNTER_KEYS})
            treuse = resident.get(
                tenant, {"delta_requests": 0, "rows_saved": 0}
            )
            per_tenant[tenant] = {
                **tcounts,
                "queue_ms_p50": queue_ms.quantile(tenant, 0.50),
                "queue_ms_p99": queue_ms.quantile(tenant, 0.99),
                "resident_delta_requests": treuse["delta_requests"],
                "resident_rows_saved": treuse["rows_saved"],
                "explain_requests": snap["explained"].get(tenant, 0),
            }
        occ = snap["occupancy"]
        occ_sorted = sorted(occ)
        replica_occ = snap["replica_occ"]
        replicas = {
            str(rid): {
                **rec,
                "occupancy_p50": replica_occ.quantile(f"r{rid}", 0.50),
                "occupancy_max": replica_occ.quantile(f"r{rid}", 1.0),
            }
            for rid, rec in sorted(snap["replicas"].items())
        }
        return {
            **({
                "replicas": replicas,
                "steals_total": sum(
                    r["stolen_from"]
                    for r in snap["replicas"].values()
                ),
            } if replicas else {}),
            "tenants": per_tenant,
            "batches": len(occ),
            "dispatched_requests": snap["dispatched_requests"],
            "batch_occupancy_mean": (
                round(sum(occ) / len(occ), 2) if occ else None
            ),
            "batch_occupancy_p50": (
                occ_sorted[len(occ_sorted) // 2] if occ_sorted else None
            ),
            "batch_occupancy_max": max(occ) if occ else None,
            "queue_depth_peak": snap["depth_peak"],
            "graph_cache": snap["graph_cache"],
            "duration": snap["duration"],
            "slo_breaches": snap["slo_breaches"],
            "slo_ms": snap["slo_ms"],
            "shed_total": sum(c["shed"] for c in counts.values()),
            "rejected_total": sum(
                c["rejected"] for c in counts.values()
            ),
            **self._autoscale_summary(snap),
        }

    @staticmethod
    def _autoscale_summary(snap: Dict[str, object]) -> Dict[str, object]:
        """Autoscale + placement block, only when anything happened —
        a plain ServeLoop's summary stays byte-identical to PR 15."""
        events: Dict[str, int] = snap["scale_events"]   # type: ignore
        placement: Dict[str, int] = snap["placement"]   # type: ignore
        picks = sum(placement.values())
        if not any(events.values()) and picks == 0:
            return {}
        return {
            "autoscale": dict(events),
            "placement": {
                **placement,
                "hit_rate": (
                    round(placement["preferred"] / picks, 4)
                    if picks else None
                ),
            },
        }
