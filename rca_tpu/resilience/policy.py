"""Composable failure policies: Retry, Deadline, CircuitBreaker, suppressed.

Design rules these primitives share:

- **injectable time**: every policy takes ``clock`` (monotonic seconds) and,
  where it waits, ``sleep`` — hermetic tests drive them with fake clocks
  and zero-cost sleeps instead of wall time;
- **bounded state**: the fault log and every counter are capped; a policy
  object can live for the process lifetime without growing;
- **no silent swallows**: the one sanctioned way to drop an exception is
  :func:`suppressed`, which records the fault into the bounded module
  fault log (drained into per-tick health records by the streaming
  session).  ``tools/lint_swallowed_faults.py`` fails the build on any
  literal ``except Exception: pass`` outside ``rca_tpu/resilience/``.
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import time
from typing import Any, Callable, Dict, List, Optional, Tuple, Type

from rca_tpu.util.threads import make_lock

FAULT_LOG_CAP = 256


class PolicyError(RuntimeError):
    """Base class for policy-raised failures."""


class DeadlineExceeded(PolicyError):
    """The operation's time budget ran out (possibly mid-retry)."""


class CircuitOpen(PolicyError):
    """The breaker is open: the protected operation was not attempted."""


# ---------------------------------------------------------------------------
# Fault log — the sanctioned swallow channel
# ---------------------------------------------------------------------------


class _FaultLog:
    """Bounded, thread-safe record of deliberately-swallowed faults."""

    def __init__(self, cap: int = FAULT_LOG_CAP):
        self._lock = make_lock("_FaultLog._lock")
        self._cap = cap
        self._entries: List[Dict[str, str]] = []

    def record(self, op: str, error: BaseException | str) -> None:
        detail = (
            f"{type(error).__name__}: {error}"
            if isinstance(error, BaseException) else str(error)
        )
        with self._lock:
            if len(self._entries) < self._cap:
                self._entries.append({"op": op, "error": detail[:300]})

    def drain(self, clear: bool = True) -> List[Dict[str, str]]:
        with self._lock:
            out = list(self._entries)
            if clear:
                self._entries.clear()
            return out


FAULTS = _FaultLog()


def record_fault(op: str, error: BaseException | str) -> None:
    """Record a swallowed/handled fault into the module fault log."""
    FAULTS.record(op, error)


def drain_faults(clear: bool = True) -> List[Dict[str, str]]:
    """Swallowed faults since the last drain (health-record channel)."""
    return FAULTS.drain(clear)


@contextlib.contextmanager
def suppressed(op: str, reraise: Tuple[Type[BaseException], ...] = ()):
    """The ONE sanctioned way to swallow an exception outside a policy.

    Unlike a bare ``except Exception: pass``, the fault is recorded into
    the bounded module fault log, so a health record (or a debugging
    session) can still see it happened.  ``reraise`` exempts exception
    types that must propagate (e.g. ``KeyboardInterrupt`` is never caught
    — only ``Exception`` subclasses are)."""
    try:
        yield
    except reraise:
        raise
    except Exception as exc:
        FAULTS.record(op, exc)


# ---------------------------------------------------------------------------
# Counters — cheap aggregate stats the health records snapshot
# ---------------------------------------------------------------------------


class Counter:
    """Thread-safe monotonic counter with delta snapshots."""

    def __init__(self) -> None:
        self._lock = make_lock("Counter._lock")
        self._value = 0

    def add(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


#: process-wide count of retry sleeps spent by every Retry policy — the
#: streaming session snapshots per-tick deltas into its health record
RETRIES = Counter()


def retry_counter() -> int:
    """Process-wide retries spent so far (for health-record deltas)."""
    return RETRIES.value


# ---------------------------------------------------------------------------
# Deadline
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Deadline:
    """A monotonic time budget shared by the steps of one operation."""

    budget_s: float
    clock: Callable[[], float] = time.monotonic
    _started: Optional[float] = None

    def __post_init__(self) -> None:
        if self._started is None:
            self._started = self.clock()

    @classmethod
    def after(cls, seconds: float,
              clock: Callable[[], float] = time.monotonic) -> "Deadline":
        return cls(budget_s=seconds, clock=clock)

    def remaining(self) -> float:
        return self.budget_s - (self.clock() - self._started)

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, op: str = "operation") -> None:
        if self.expired:
            raise DeadlineExceeded(
                f"{op}: deadline of {self.budget_s:.3f}s exceeded"
            )


# ---------------------------------------------------------------------------
# Retry
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class Retry:
    """Exponential backoff + jitter.  ``attempts`` counts RE-tries: the
    first call is free, so ``attempts=2`` means at most 3 invocations.

    ``seed`` makes the jitter hermetic (policies constructed in tests and
    chaos runs are reproducible); ``sleep``/``clock`` are injectable so a
    test never waits wall time."""

    attempts: int = 2
    base_delay: float = 0.1
    max_delay: float = 5.0
    jitter: float = 0.25               # fraction of the delay randomized
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)
    sleep: Callable[[float], None] = time.sleep
    clock: Callable[[], float] = time.monotonic
    seed: Optional[int] = None

    def __post_init__(self) -> None:
        self._rng = random.Random(self.seed)
        # one Retry policy is routinely SHARED across threads (the watch
        # pump set hands one instance to both pump threads), so the
        # read-modify-write counter and the jitter RNG draw both sit
        # under a lock — gravelock's race-guard surfaced the unguarded
        # `retries_spent += 1` as a lost-update race (ANALYSIS.md)
        self._lock = make_lock("Retry._lock")
        self.retries_spent = 0  # instance-lifetime count

    def delay(self, attempt: int) -> float:
        """Backoff before re-try number ``attempt`` (1-based)."""
        d = min(self.base_delay * (2.0 ** (attempt - 1)), self.max_delay)
        if self.jitter:
            with self._lock:
                jitter_draw = self._rng.random()
            d *= 1.0 + self.jitter * (2.0 * jitter_draw - 1.0)
        return max(d, 0.0)

    def sleep_for(self, attempt: int) -> None:
        with self._lock:
            self.retries_spent += 1
        RETRIES.add(1)
        self.sleep(self.delay(attempt))

    def call(
        self,
        fn: Callable[..., Any],
        *args: Any,
        deadline: Optional[Deadline] = None,
        on_retry: Optional[Callable[[int, BaseException], None]] = None,
        **kwargs: Any,
    ) -> Any:
        """``fn(*args, **kwargs)`` with up to ``attempts`` re-tries.

        A ``deadline`` bounds the WHOLE call including backoff sleeps:
        when the budget cannot cover the next delay the original failure
        is re-raised chained under :class:`DeadlineExceeded`."""
        attempt = 0
        while True:
            if deadline is not None:
                deadline.check(getattr(fn, "__name__", "call"))
            try:
                return fn(*args, **kwargs)
            except self.retry_on as exc:
                if attempt >= self.attempts:
                    raise
                attempt += 1
                if deadline is not None and (
                    deadline.remaining() <= self.delay(attempt)
                ):
                    raise DeadlineExceeded(
                        f"{getattr(fn, '__name__', 'call')}: budget cannot "
                        f"cover retry {attempt}"
                    ) from exc
                if on_retry is not None:
                    on_retry(attempt, exc)
                self.sleep_for(attempt)


# ---------------------------------------------------------------------------
# CircuitBreaker
# ---------------------------------------------------------------------------


class CircuitBreaker:
    """Classic closed → open → half-open breaker.

    ``failure_threshold`` consecutive failures open the circuit; after
    ``reset_after`` seconds one probe call is allowed (half-open) — its
    success closes the circuit, its failure re-opens it for another full
    window.  ``allow()`` is the gate callers check before attempting the
    protected operation; it consumes the half-open probe slot."""

    def __init__(
        self,
        failure_threshold: int = 2,
        reset_after: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        name: str = "",
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_after = float(reset_after)
        self.clock = clock
        self.name = name
        self._lock = make_lock("CircuitBreaker._lock")
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._half_open = False

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._half_open:
                return "half-open"
            if self.clock() - self._opened_at >= self.reset_after:
                return "half-open"
            return "open"

    def allow(self) -> bool:
        """May the caller attempt the protected operation right now?"""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._half_open:
                # one probe is already in flight; hold further callers
                return False
            if self.clock() - self._opened_at >= self.reset_after:
                self._half_open = True  # this caller is the probe
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._half_open = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._half_open or self._failures >= self.failure_threshold:
                self._opened_at = self.clock()
                self._half_open = False

    def call(self, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
        """Gate + execute + record in one step."""
        if not self.allow():
            raise CircuitOpen(
                f"circuit {self.name or getattr(fn, '__name__', '?')} is open"
            )
        try:
            out = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return out
