"""Resilience layer: composable failure policies + fault injection.

Every failure path in the system used to be ad hoc in whatever layer a
reviewer happened to find it (watch-pump 410 expiry, LLM quota failover,
Pallas interpret fallback).  This package centralizes the vocabulary:

- :mod:`rca_tpu.resilience.policy` — ``Retry`` (exponential backoff +
  jitter, injectable clock/sleep), ``Deadline``, ``CircuitBreaker``, and
  the ``suppressed`` context manager that replaces every bare
  ``except Exception: pass`` outside this package (enforced by
  ``tools/lint_swallowed_faults.py``);
- :mod:`rca_tpu.resilience.chaos` — ``ChaosClusterClient``, a seeded
  fault-injection wrapper over any :class:`rca_tpu.cluster.protocol.
  ClusterClient`, plus the chaos-soak harness behind
  ``python -m rca_tpu chaos`` and ``bench.py --chaos``.

See RESILIENCE.md for the degradation ladder and the chaos-schedule
format.
"""

from rca_tpu.resilience.policy import (
    CircuitBreaker,
    CircuitOpen,
    Deadline,
    DeadlineExceeded,
    PolicyError,
    Retry,
    drain_faults,
    record_fault,
    retry_counter,
    suppressed,
)

__all__ = [
    "CircuitBreaker",
    "CircuitOpen",
    "Deadline",
    "DeadlineExceeded",
    "PolicyError",
    "Retry",
    "drain_faults",
    "record_fault",
    "retry_counter",
    "suppressed",
]
