"""Seeded fault injection over any ClusterClient + the chaos-soak harness.

:class:`ChaosClusterClient` wraps a :class:`rca_tpu.cluster.protocol.
ClusterClient` and injects, from a seeded schedule, the fault classes the
resilience layer must absorb:

- ``api_timeout``     list/fetch calls raise :class:`InjectedTimeout`;
- ``truncated_list``  ``get_pods`` silently returns a truncated copy
  (the "collector dropped spans" shape from LogGD/RIG degraded-telemetry
  scenarios — see ISSUE motivation);
- ``nan_metrics``     ``get_pod_metrics`` returns a deep-copied payload
  with NaN/Inf ``usage_percentage`` values (poisons feature channels,
  exercising the engine's on-device finite-mask sanitizer);
- ``gone_storm``      ``watch_changes`` reports ``expired`` for several
  consecutive polls (a 410 Gone storm — repeated resyncs);
- ``pump_death``      ``watch_changes`` silently discards the pending
  feed entries, then reports one ``expired`` (a watch pump died holding
  undelivered changes).

With ``config.enabled = False`` (or every rate 0) the wrapper is a pure
delegating proxy — bit-identical to the wrapped client (property-tested in
tests/test_resilience.py), so it can sit permanently in a test harness.

Injected faults are recorded in :meth:`ChaosClusterClient.drain_injected`;
:class:`rca_tpu.engine.live.LiveStreamingSession` drains that surface into
its per-tick health record, which is how :func:`run_chaos_soak` (behind
``python -m rca_tpu chaos`` and ``bench.py --chaos``) counts observed
fault classes and checks the fault-free-tick parity invariant.
"""

from __future__ import annotations

import copy
import dataclasses
import json
import random
from typing import Any, Callable, Dict, List, Optional

FAULT_CLASSES = (
    "api_timeout", "truncated_list", "nan_metrics", "gone_storm",
    "pump_death",
)

# calls eligible for api_timeout injection: the heavy capture-path getters
_TIMEOUT_OPS = ("get_pods", "get_events", "get_pod_metrics")


class InjectedTimeout(TimeoutError):
    """A chaos-injected API timeout (distinguishable from real ones)."""


@dataclasses.dataclass
class ChaosConfig:
    """Schedule parameters.  ``rates`` are per-opportunity probabilities
    drawn from one seeded stream, so a (seed, call-sequence) pair replays
    the exact same fault schedule."""

    seed: int = 0
    enabled: bool = True
    rates: Dict[str, float] = dataclasses.field(default_factory=lambda: {
        "api_timeout": 0.06,
        "truncated_list": 0.10,
        "nan_metrics": 0.12,
        "gone_storm": 0.04,
        "pump_death": 0.03,
    })
    storm_len: int = 3      # consecutive expired polls per gone_storm
    nan_pods: int = 2       # pods corrupted per nan_metrics injection

    def rate(self, fault: str) -> float:
        return float(self.rates.get(fault, 0.0))


class ChaosClusterClient:
    """Fault-injecting proxy over any ``ClusterClient``."""

    def __init__(self, inner: Any, config: Optional[ChaosConfig] = None):
        self.inner = inner
        self.config = config or ChaosConfig()
        self._rng = random.Random(self.config.seed)
        self._injected: List[Dict[str, str]] = []
        self._storm_left = 0
        self._nan_toggle = 0

    # -- bookkeeping --------------------------------------------------------
    def drain_injected(self, clear: bool = True) -> List[Dict[str, str]]:
        out = list(self._injected)
        if clear:
            self._injected.clear()
        return out

    def _fires(self, fault: str) -> bool:
        if not self.config.enabled:
            return False
        return self._rng.random() < self.config.rate(fault)

    def _record(self, fault: str, op: str) -> None:
        self._injected.append({"fault": fault, "op": op})

    def _maybe_timeout(self, op: str) -> None:
        if self._fires("api_timeout"):
            self._record("api_timeout", op)
            raise InjectedTimeout(f"chaos: injected timeout in {op}")

    # -- transparent delegation --------------------------------------------
    def __getattr__(self, name: str) -> Any:
        if name == "get_columnar":
            # the columnar capture path (ISSUE 10) would bypass exactly
            # the surfaces this wrapper injects on (get_pods truncation,
            # metric NaNs, capture-call timeouts), starving the seeded
            # schedule — so a chaos-wrapped client does not ADVERTISE
            # columnar support and chaos soaks exercise the dict capture
            # path end to end.  Columnar resilience (feed expiry, full
            # rebuild, capture faults) is tested directly in
            # tests/test_columnar.py.
            raise AttributeError(name)
        # anything not explicitly intercepted passes straight through —
        # the disabled wrapper is bit-identical to the wrapped client
        return getattr(self.inner, name)

    # -- intercepted surfaces ----------------------------------------------
    def get_pods(self, namespace: str) -> List[Dict[str, Any]]:
        self._maybe_timeout("get_pods")
        pods = self.inner.get_pods(namespace)
        if pods and len(pods) > 1 and self._fires("truncated_list"):
            keep = max(1, len(pods) - max(1, len(pods) // 4))
            self._record("truncated_list", "get_pods")
            return pods[:keep]
        return pods

    def get_events(self, namespace: str, field_selector=None):
        self._maybe_timeout("get_events")
        return self.inner.get_events(namespace, field_selector)

    def get_pod_metrics(self, namespace: str) -> Dict[str, Any]:
        self._maybe_timeout("get_pod_metrics")
        metrics = self.inner.get_pod_metrics(namespace)
        if not self._fires("nan_metrics"):
            return metrics
        pods = (metrics or {}).get("pods") or {}
        if not pods:
            return metrics
        corrupted = copy.deepcopy(metrics)
        names = sorted(pods)
        picks = [
            names[self._rng.randrange(len(names))]
            for _ in range(min(self.config.nan_pods, len(names)))
        ]
        # alternate NaN / +Inf so both non-finite shapes are exercised
        self._nan_toggle ^= 1
        poison = float("nan") if self._nan_toggle else float("inf")
        for name in picks:
            rec = corrupted["pods"][name]
            for ch in ("cpu", "memory"):
                if isinstance(rec.get(ch), dict):
                    rec[ch]["usage_percentage"] = poison
        self._record("nan_metrics", "get_pod_metrics")
        return corrupted

    def watch_changes(self, namespace: str, cursor):
        if cursor is not None and self.config.enabled:
            if self._storm_left > 0:
                self._storm_left -= 1
                self._record("gone_storm", "watch_changes")
                return {"supported": True, "cursor": cursor,
                        "expired": True, "changes": []}
            if self._fires("gone_storm"):
                # storm: this poll and the next storm_len-1 expire too
                self._storm_left = max(0, self.config.storm_len - 1)
                self._record("gone_storm", "watch_changes")
                return {"supported": True, "cursor": cursor,
                        "expired": True, "changes": []}
            if self._fires("pump_death"):
                # a dead pump loses whatever it was holding: consume the
                # real feed (dropping the entries) and report expiry
                self._record("pump_death", "watch_changes")
                self.inner.watch_changes(namespace, cursor)
                return {"supported": True, "cursor": cursor,
                        "expired": True, "changes": []}
        return self.inner.watch_changes(namespace, cursor)


def seeded_fault_hook(
    seed: int,
    rate: float = 0.1,
    ops: Optional[List[str]] = None,
) -> Callable[[str], None]:
    """Seeded fault injector for the serving dispatcher (rca_tpu/serve):
    called with the op name (``"dispatch"`` / ``"fetch"``) before the
    device work; raises :class:`InjectedTimeout` at ``rate`` per call
    from one seeded stream, so a (seed, call-sequence) pair replays the
    exact same fault schedule — the serve soak's analogue of
    :class:`ChaosClusterClient`.  ``ops`` restricts injection to those
    call sites (default: all)."""
    rng = random.Random(seed)

    def hook(op: str) -> None:
        if ops is not None and op not in ops:
            return
        if rng.random() < rate:
            raise InjectedTimeout(f"chaos: injected fault in serve {op}")

    return hook


# ---------------------------------------------------------------------------
# Chaos soak harness (CLI `rca chaos`, bench --chaos, tests)
# ---------------------------------------------------------------------------


def run_chaos_soak(
    make_world: Callable[[], Any],
    namespace: str,
    seed: int = 7,
    ticks: int = 200,
    k: int = 5,
    engine_factory: Optional[Callable[[], Any]] = None,
    config: Optional[ChaosConfig] = None,
    topology_check_every: int = 5,
    record_path: Optional[str] = None,
    pipeline_depth: Optional[int] = None,
    replay_check: bool = True,
    parity_mode: Optional[str] = None,
) -> Dict[str, Any]:
    """Run ``ticks`` polls of a :class:`LiveStreamingSession` over a
    chaos-wrapped mock world and score the resilience contract:

    - ``uncaught_exceptions`` MUST be 0 (``poll()`` never raises);
    - every injected fault class should appear in the health records;
    - fault-free ticks (no injection this tick, no residual contamination,
      no sanitized rows, not degraded) must be bit-identical to a
      fault-free baseline session over an identically-built world.

    ``make_world`` is called twice (baseline + chaos) so the two sessions
    never share mutable state.

    ``record_path`` attaches a flight recorder (ISSUE 5) to the CHAOS
    session: every client call (faults included) and every tick's ranking
    land in the log, and — with ``replay_check`` — the soak finishes by
    replaying its own recording through a fresh engine and asserting
    tick-for-tick bit-identity (``summary["replay"]``): a chaos run is
    thereby a durable regression artifact, not a one-shot.

    ``parity_mode`` picks the fault-free parity gate: ``exact`` (bitwise
    rankings, the default) or ``rank`` (hit@1/hit@3 + Kendall-tau,
    ISSUE 13's first-class gate mode).  ``None`` auto-selects: ``rank``
    when the registry forces the quantized kernel (whose scores move in
    the low decimals by design), ``exact`` otherwise — so
    ``RCA_KERNEL=quantized rca chaos`` gates out of the box.
    """
    from rca_tpu.cluster.mock_client import MockClusterClient
    from rca_tpu.engine.live import LiveStreamingSession

    if parity_mode is None:
        from rca_tpu.engine.registry import forced_kernel

        parity_mode = (
            "rank" if forced_kernel() == "quantized" else "exact"
        )
    if parity_mode not in ("exact", "rank"):
        raise ValueError(
            f"parity_mode={parity_mode!r}: expected 'exact' or 'rank'"
        )

    make_engine = engine_factory or (lambda: None)

    base = LiveStreamingSession(
        MockClusterClient(make_world()), namespace, k=k,
        engine=make_engine(), topology_check_every=topology_check_every,
        pipeline_depth=pipeline_depth,
    )
    baseline_list = base.poll()["ranked"]
    baseline_ranked = json.dumps(baseline_list, sort_keys=True)

    recorder = None
    if record_path is not None:
        from rca_tpu.replay import Recorder

        recorder = Recorder(
            record_path, mode="stream",
            seeds={"chaos_seed": seed},
            meta={"harness": "chaos_soak", "ticks": ticks},
        )

    cfg = config or ChaosConfig(seed=seed)
    was_enabled = cfg.enabled
    cfg.enabled = False  # session bootstrap capture runs fault-free
    chaos = ChaosClusterClient(MockClusterClient(make_world()), cfg)
    live = LiveStreamingSession(
        chaos, namespace, k=k, engine=make_engine(),
        topology_check_every=topology_check_every,
        pipeline_depth=pipeline_depth, recorder=recorder,
    )
    cfg.enabled = was_enabled

    counts: Dict[str, int] = {f: 0 for f in FAULT_CLASSES}
    uncaught = 0
    degraded_ticks = 0
    sanitized_total = 0
    parity_checked = 0
    parity_ok = True
    dirty = False
    # kernelscope soak gates (ISSUE 12): the session's own recompile
    # monitor covers the tick path; a dedicated accountant samples
    # device memory every few ticks so the monotonic-growth leak gate
    # has a series to judge
    from rca_tpu.observability.kernelscope import DeviceMemoryAccountant

    soak_memory = DeviceMemoryAccountant(sample_every=5)
    for _ in range(ticks):
        soak_memory.maybe_sample(live._polls)
        try:
            out = live.poll()
        except Exception as exc:  # contract violation — poll must not raise
            uncaught += 1
            from rca_tpu.resilience.policy import record_fault

            record_fault("chaos.soak.uncaught", exc)
            continue
        health = out.get("health", {})
        injected = health.get("injected", [])
        for f in injected:
            counts[f.get("fault", "?")] = counts.get(f.get("fault", "?"), 0) + 1
        sanitized = int(health.get("sanitized_rows", 0))
        sanitized_total += sanitized
        if out.get("degraded"):
            degraded_ticks += 1
        faulted = bool(injected) or sanitized > 0 or bool(health.get("faults"))
        if faulted:
            # contaminated state can outlive the faulting tick (stale rows
            # persist across quiet polls until the next clean capture)
            dirty = True
        elif not out.get("quiet", False):
            dirty = False  # a clean full capture restored ground truth
        if not faulted and not dirty and not out.get("degraded"):
            parity_checked += 1
            if parity_mode == "rank":
                from rca_tpu.engine.quantized import rank_parity

                if not rank_parity(baseline_list, out["ranked"])["ok"]:
                    parity_ok = False
            elif json.dumps(out["ranked"], sort_keys=True) != (
                    baseline_ranked):
                parity_ok = False
    replay_summary = None
    if recorder is not None:
        recorder.close()
        replay_summary = {
            "path": recorder.path,
            "ticks_recorded": recorder.ticks_recorded,
            "bytes": recorder.bytes_written,
        }
        if replay_check:
            # the record→replay parity leg: re-drive the REAL engine from
            # the log just written and demand bit-identical rankings
            from rca_tpu.replay import replay_stream

            report = replay_stream(record_path, engine=make_engine(),
                                   parity=parity_mode)
            replay_summary.update({
                "parity_ok": report["parity_ok"],
                "first_divergent_tick": report.get("first_divergent_tick"),
                "ticks_replayed": report["ticks_replayed"],
                "unconsumed_calls": report["unconsumed_calls"],
            })
            if report.get("attribution_ticks_compared") is not None:
                # causelens (ISSUE 14): an explained recording's digests
                # re-verified from the tape (folded into parity_ok too)
                replay_summary["attribution_ticks_compared"] = (
                    report["attribution_ticks_compared"]
                )
                replay_summary["attribution_parity_ok"] = (
                    report["attribution_parity_ok"]
                )
    soak_memory.sample()  # closing sample so short soaks still gate
    scope = live.recompile_monitor.snapshot()
    kernelscope_summary = {
        "enabled": scope["enabled"],
        "compiles": scope["compiles"],
        "recompiles_post_warm": scope["recompiles_post_warm"],
        **({"recompiled": scope["recompiled"]}
           if scope["recompiled"] else {}),
        "memory_samples": soak_memory.samples_taken,
        "memory_gate": soak_memory.gate(),
    }
    return {
        "ticks": ticks,
        "seed": seed,
        "kernelscope": kernelscope_summary,
        **({"replay": replay_summary} if replay_summary else {}),
        "uncaught_exceptions": uncaught,
        "faults_injected": counts,
        "fault_classes_observed": sorted(
            f for f, n in counts.items() if n > 0
        ),
        "all_classes_observed": all(
            counts.get(f, 0) > 0 for f in FAULT_CLASSES
        ),
        "degraded_ticks": degraded_ticks,
        "sanitized_rows_total": sanitized_total,
        "final_degradation": getattr(live, "degradation", 0),
        "resyncs_expired": getattr(live, "resyncs_expired", 0),
        "resyncs_topology": getattr(live, "resyncs_topology", 0),
        "parity_ticks_checked": parity_checked,
        "parity_mode": parity_mode,
        "parity_ok": parity_ok,
    }
