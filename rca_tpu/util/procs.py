"""The ONE place ``rca_tpu/`` spawns long-lived child processes.

The serve federation (rca_tpu/serve/federation.py, SERVING.md
§Federation) supervises N worker PROCESSES — the first place the
package owns a child's whole life cycle instead of a one-shot
``subprocess.run``.  Long-lived children are built here for the same
reasons threads live in :mod:`rca_tpu.util.threads` and sockets in
:mod:`rca_tpu.util.net`:

- **named, attributable processes**: ``spawn_worker("fed-worker0",
  argv)`` stamps an owner name into the handle, so a leaked child, a
  nonzero exit, or a SIGKILL in a chaos run names its owner instead of
  a bare pid;
- **captured output, never a deadlock**: stdout/stderr are drained by
  named reader threads into bounded buffers — a chatty child can never
  fill a pipe and wedge both processes, and a crashed worker's last
  stderr lines are available to the failure report;
- **one termination protocol**: ``terminate()`` is the polite
  SIGTERM→wait→SIGKILL ladder, ``kill()`` is the chaos seam's
  immediate SIGKILL (the ``process_kill`` fault class) — both
  idempotent, both safe on an already-dead child;
- **lint-enforceable**: the graftlint ``thread-discipline`` rule flags
  raw ``subprocess.Popen`` / ``os.fork`` / ``multiprocessing``
  construction anywhere else in ``rca_tpu/``, so the seam cannot
  silently erode (one-shot ``subprocess.run`` calls — kubectl, git —
  stay legal: they own no lifecycle).
"""

from __future__ import annotations

import subprocess
import sys
from typing import Dict, List, Optional, Tuple

from rca_tpu.util.threads import make_lock, spawn

#: bytes of child stdout/stderr kept per stream (oldest dropped) — the
#: buffers exist for failure reports, not log shipping
CAPTURE_CAP = 256 * 1024


class WorkerProc:
    """One supervised child process: named, output-captured, with the
    SIGTERM→SIGKILL termination ladder.  Built via :func:`spawn_worker`
    only (the procs seam)."""

    def __init__(self, name: str, proc: "subprocess.Popen",
                 argv: List[str]):
        self.name = name
        self.proc = proc
        self.argv = list(argv)
        self._lock = make_lock("WorkerProc._lock")
        self._out: List[bytes] = []
        self._err: List[bytes] = []
        self._out_bytes = 0
        self._err_bytes = 0
        self._readers = [
            spawn(self._drain, name=f"rca-proc-{name}-out", daemon=True,
                  args=(proc.stdout, self._out, "out")),
            spawn(self._drain, name=f"rca-proc-{name}-err", daemon=True,
                  args=(proc.stderr, self._err, "err")),
        ]

    @property
    def pid(self) -> int:
        return int(self.proc.pid)

    def _drain(self, stream, sink: List[bytes], which: str) -> None:
        """Reader-thread body: drain one pipe into its bounded buffer.
        Runs until EOF (child exit) — the child can never block on a
        full pipe."""
        while True:
            chunk = stream.readline()
            if not chunk:
                return
            with self._lock:
                sink.append(chunk)
                if which == "out":
                    self._out_bytes += len(chunk)
                    while self._out_bytes > CAPTURE_CAP and len(sink) > 1:
                        self._out_bytes -= len(sink.pop(0))
                else:
                    self._err_bytes += len(chunk)
                    while self._err_bytes > CAPTURE_CAP and len(sink) > 1:
                        self._err_bytes -= len(sink.pop(0))

    # -- state ---------------------------------------------------------------
    def alive(self) -> bool:
        return self.proc.poll() is None

    @property
    def returncode(self) -> Optional[int]:
        return self.proc.poll()

    def output(self) -> Tuple[str, str]:
        """Captured (stdout, stderr) so far, newest-complete — the
        failure report's evidence."""
        with self._lock:
            out = b"".join(self._out)
            err = b"".join(self._err)
        return (out.decode("utf-8", "replace"),
                err.decode("utf-8", "replace"))

    # -- termination ladder --------------------------------------------------
    def terminate(self, grace_s: float = 5.0) -> Optional[int]:
        """Polite stop: SIGTERM, wait ``grace_s``, then SIGKILL.
        Idempotent; returns the exit code (None only if the child
        somehow survives SIGKILL's wait)."""
        if self.alive():
            self.proc.terminate()
            try:
                return self.proc.wait(grace_s)
            except subprocess.TimeoutExpired:
                pass
        return self.kill()

    def kill(self, wait_s: float = 5.0) -> Optional[int]:
        """Immediate SIGKILL — the ``process_kill`` chaos seam.  A dead
        worker mid-request is exactly the failure the federation's
        drain-and-reroute must absorb."""
        if self.alive():
            self.proc.kill()
        try:
            return self.proc.wait(wait_s)
        except subprocess.TimeoutExpired:  # pragma: no cover - kernel lag
            return None

    def join(self, timeout: Optional[float] = None) -> Optional[int]:
        """Wait for natural exit; returns the code, None on timeout."""
        try:
            return self.proc.wait(timeout)
        except subprocess.TimeoutExpired:
            return None


def spawn_worker(
    name: str,
    argv: List[str],
    env: Optional[Dict[str, str]] = None,
) -> WorkerProc:
    """Spawn one named, output-captured child process (the seam).

    ``env`` REPLACES the inherited environment when given (callers merge
    ``os.environ`` themselves if they want inheritance — an implicit
    merge is how env-dependent test pollution is born)."""
    proc = subprocess.Popen(
        list(argv),
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        env=env,
    )
    return WorkerProc(name, proc, argv)


def python_argv(module: str, *args: str) -> List[str]:
    """``argv`` for a ``python -m <module>`` child under THIS
    interpreter — the federation worker's spawn shape."""
    return [sys.executable, "-m", module, *args]
