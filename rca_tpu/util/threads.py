"""The ONE place ``rca_tpu/`` constructs threads and locks.

Every thread and every lock in the package is built here, for three
reasons the gravelock analyzer (ANALYSIS.md) depends on:

- **named, attributable primitives**: ``make_lock("ServeMetrics._lock")``
  carries the same ``Class.attr`` identity the static concurrency model
  uses for its lock-order graph, so a runtime observation and a static
  edge talk about the same object;
- **reliable thread-root discovery**: ``spawn(...)``/``make_thread(...)``
  call sites (plus ``threading.Thread`` subclasses) are the complete set
  of thread entry points — the analyzer's reachability computation does
  not have to guess; every thread is named and its daemon flag is
  explicit, never defaulted;
- **the rsan seam**: when the runtime lock sanitizer is enabled
  (``RCA_RSAN=1`` or :func:`rca_tpu.analysis.concurrency.rsan.enable`),
  the constructors return :class:`SanitizedLock`-family shims that record
  actual acquisition orders for the static model's cross-check.  When it
  is off (the default), these functions return the bare ``threading``
  primitives — zero wrappers, zero per-acquire cost.

The graftlint rule ``thread-discipline`` (rules/threads.py) makes raw
``threading.Thread(...)`` / ``threading.Lock()`` construction outside
this module unlandable, so the seam cannot silently erode.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterable, Optional


def _rsan_on() -> bool:
    """Is the runtime lock sanitizer active?  Cheap when off: the rsan
    module is imported only after something enabled it (env or API)."""
    import sys

    mod = sys.modules.get("rca_tpu.analysis.concurrency.rsan")
    if mod is not None:
        return bool(mod.enabled())
    from rca_tpu.config import rsan_enabled

    if not rsan_enabled():
        return False
    from rca_tpu.analysis.concurrency import rsan

    return bool(rsan.enabled())


def make_lock(name: str) -> Any:
    """A mutex named for the attribute that owns it (``"Class._lock"``)."""
    if _rsan_on():
        from rca_tpu.analysis.concurrency import rsan

        return rsan.SanitizedLock(name)
    return threading.Lock()


def make_rlock(name: str) -> Any:
    if _rsan_on():
        from rca_tpu.analysis.concurrency import rsan

        return rsan.SanitizedLock(name, reentrant=True)
    return threading.RLock()


def make_condition(name: str, lock: Optional[Any] = None) -> Any:
    """A condition variable (its internal mutex counts as the lock the
    name identifies — ``with cond:`` is an acquire of it)."""
    if _rsan_on():
        from rca_tpu.analysis.concurrency import rsan

        return rsan.SanitizedCondition(name, lock=lock)
    return threading.Condition(lock)


def make_thread(
    target: Callable[..., None],
    *,
    name: str,
    daemon: bool,
    args: Iterable[Any] = (),
) -> threading.Thread:
    """A NOT-yet-started thread.  ``name`` and ``daemon`` are mandatory:
    an anonymous thread is invisible to the analyzer's root discovery and
    to every stack dump, and an implicit daemon flag is how shutdown
    hangs are born."""
    return threading.Thread(
        target=target, name=name, daemon=daemon, args=tuple(args)
    )


def spawn(
    target: Callable[..., None],
    *,
    name: str,
    daemon: bool = True,
    args: Iterable[Any] = (),
) -> threading.Thread:
    """``make_thread`` + ``start()`` — the common case."""
    t = make_thread(target, name=name, daemon=daemon, args=args)
    t.start()
    return t
