"""The ONE place ``rca_tpu/`` constructs sockets.

The gateway (rca_tpu/gateway, SERVING.md §Gateway) is the package's only
network surface, and its listening sockets are built here for the same
reasons threads and locks are built in :mod:`rca_tpu.util.threads`:

- **named, attributable resources**: ``make_server_socket("gateway",
  host, port)`` stamps the purpose into the construction site, so a
  leaked fd or an address-in-use failure names its owner instead of a
  bare ``socket.socket`` three frames deep;
- **one validated construction path**: reuse flags, backlog, and the
  bind/listen sequence are decided once — every listener behaves the
  same under restart (``SO_REUSEADDR``) and port-0 ephemeral binding
  (tests and ``rca serve --listen 127.0.0.1:0`` read the kernel-chosen
  port back from the returned socket);
- **lint-enforceable**: the graftlint ``thread-discipline`` rule flags
  raw ``socket.socket(...)`` construction anywhere else in ``rca_tpu/``,
  so the seam cannot silently erode (stdlib internals — the HTTP
  server's accepted connections, ``http.client`` outbound sockets — are
  library code and out of scope by construction).
"""

from __future__ import annotations

import socket
import ssl
from typing import Optional, Tuple


def make_server_socket(
    name: str,
    host: str,
    port: int,
    backlog: int = 64,
) -> socket.socket:
    """A bound, LISTENING TCP socket named for its owner.

    ``port`` 0 binds an ephemeral port — read the kernel's choice back
    via :func:`bound_address`.  Raises ``OSError`` (address in use,
    permission) with the owner name prefixed, so the failure is
    attributable."""
    if not 0 <= int(port) <= 65535:
        raise ValueError(f"{name}: port {port} out of range [0, 65535]")
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, int(port)))
        sock.listen(int(backlog))
    except OSError as exc:
        sock.close()
        raise OSError(f"{name}: cannot listen on {host}:{port}: {exc}") from exc
    return sock


def make_client_socket(
    name: str,
    host: str,
    port: int,
    timeout_s: Optional[float] = None,
) -> socket.socket:
    """A CONNECTED TCP socket named for its owner — the outbound twin of
    :func:`make_server_socket` (the federation worker's control-channel
    connection is built here; ``http.client`` internals stay stdlib
    territory).  ``timeout_s`` bounds the connect; the socket is
    returned in blocking mode (callers set their own read deadlines)."""
    try:
        sock = socket.create_connection(
            (host, int(port)), timeout=timeout_s
        )
    except OSError as exc:
        raise OSError(
            f"{name}: cannot connect to {host}:{port}: {exc}"
        ) from exc
    sock.settimeout(None)
    return sock


# -- TLS (ISSUE 15: the gateway front door) ----------------------------------

def make_tls_server_context(
    name: str, certfile: str, keyfile: str,
    client_ca: Optional[str] = None,
) -> ssl.SSLContext:
    """A server-side TLS context over the one seam, so cert loading
    failures are attributable and protocol floors are decided once
    (TLS 1.2+; everything older is disabled by the default context).

    ``client_ca`` turns on mutual TLS (ISSUE 16): the listener DEMANDS
    a client certificate at handshake and verifies it against that CA —
    a client without one is rejected before a byte of HTTP is read."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    try:
        ctx.load_cert_chain(certfile=certfile, keyfile=keyfile)
    except (OSError, ssl.SSLError) as exc:
        raise ValueError(
            f"{name}: cannot load TLS cert/key "
            f"({certfile!r}, {keyfile!r}): {exc}"
        ) from exc
    if client_ca:
        try:
            ctx.load_verify_locations(cafile=client_ca)
        except (OSError, ssl.SSLError) as exc:
            raise ValueError(
                f"{name}: cannot load client CA {client_ca!r}: {exc}"
            ) from exc
        ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    return ctx


def make_tls_client_context(
    name: str, ca_file: Optional[str] = None,
    cert_file: Optional[str] = None, key_file: Optional[str] = None,
) -> ssl.SSLContext:
    """Client-side twin: with ``ca_file`` the server cert is VERIFIED
    against it (self-signed deployments pin their own cert); without,
    verification is off — encryption without authentication, loopback
    test territory only, and the caller had to ask for it by name.
    ``cert_file``/``key_file`` present the CLIENT's certificate to an
    mTLS gateway (``key_file`` defaults to the cert file holding both)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if ca_file:
        try:
            ctx.load_verify_locations(cafile=ca_file)
        except (OSError, ssl.SSLError) as exc:
            raise ValueError(
                f"{name}: cannot load CA file {ca_file!r}: {exc}"
            ) from exc
    else:
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    if cert_file:
        try:
            ctx.load_cert_chain(certfile=cert_file, keyfile=key_file)
        except (OSError, ssl.SSLError) as exc:
            raise ValueError(
                f"{name}: cannot load client cert/key "
                f"({cert_file!r}, {key_file!r}): {exc}"
            ) from exc
    return ctx


def primary_host_ip(name: str = "external") -> str:
    """This host's primary outbound IPv4 address — what an EXTERNAL
    worker should ``--connect`` to when the coordinator binds 0.0.0.0
    (ISSUE 16 multi-host deploy).  Uses the classic connected-UDP trick:
    no packet is sent, the kernel just picks the route's source address.
    Falls back to loopback on isolated hosts (no route at all)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        sock.connect(("10.255.255.255", 1))
        return str(sock.getsockname()[0])
    except OSError:
        return "127.0.0.1"
    finally:
        sock.close()


def bound_address(sock: socket.socket) -> Tuple[str, int]:
    """The (host, port) a server socket actually bound — the kernel's
    choice when the requested port was 0."""
    host, port = sock.getsockname()[:2]
    return str(host), int(port)


def parse_hostport(spec: str, default_port: int) -> Tuple[str, int]:
    """``HOST[:PORT]`` → ``(host, port)``; a bare ``:PORT`` listens on
    all interfaces of localhost's default.  Malformed specs fail loudly."""
    spec = (spec or "").strip()
    if not spec:
        raise ValueError("empty listen address (want HOST:PORT)")
    if ":" in spec:
        host, _, port_s = spec.rpartition(":")
        host = host or "127.0.0.1"
        try:
            port = int(port_s)
        except ValueError:
            raise ValueError(
                f"listen address {spec!r}: port {port_s!r} is not an integer"
            )
    else:
        host, port = spec, default_port
    if not 0 <= port <= 65535:
        raise ValueError(
            f"listen address {spec!r}: port {port} out of range [0, 65535]"
        )
    return host, port
