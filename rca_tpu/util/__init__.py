"""Small shared utilities with no engine/JAX dependencies."""
