"""Typed configuration for the framework.

Replaces the reference's ad-hoc env-var reads scattered across modules
(reference: app.py:45, utils/llm_client_improved.py:41-53) with one frozen
dataclass resolved once.  The ``RCA_BACKEND`` flag selects the correlation
engine per the north star: ``jax`` (TPU graph inference, default here),
``deterministic`` (CPU rule-based oracle), or ``llm`` (provider fusion).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, Optional, Tuple

VALID_BACKENDS = ("jax", "deterministic", "llm")


# -- central env access (graftlint: env-discipline) --------------------------
# Every env read in rca_tpu/ goes through one of these three accessors (the
# env-discipline rule in rca_tpu/analysis flags raw ``os.environ`` anywhere
# else in the package), so each knob is validated in exactly one place and a
# typo'd value fails loudly instead of silently selecting a default.

def env_str(name: str, default: str = "", *, choices=None,
            lower: bool = False) -> str:
    """A string env knob; empty/unset means ``default`` (which is NOT
    checked against ``choices`` — an unset knob is always legal)."""
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return default
    if lower:
        raw = raw.lower()
    if choices is not None and raw not in choices:
        raise ValueError(
            f"{name}={raw!r}: expected one of {tuple(choices)}"
        )
    return raw


def env_int(name: str, default: int, lo: int, hi: int) -> int:
    """A range-checked integer env knob; empty/unset means ``default``."""
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer in [{lo}, {hi}]")
    if not lo <= value <= hi:
        raise ValueError(f"{name}={value}: out of range [{lo}, {hi}]")
    return value


def env_int_opt(name: str, lo: int, hi: int) -> Optional[int]:
    """Like :func:`env_int` but unset/empty means None (for knobs like
    ``JAX_PROCESS_ID`` where 0 is a meaningful value and absence is a
    signal of its own)."""
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return None
    return env_int(name, 0, lo, hi)


def env_float(name: str, default: float, lo: float, hi: float) -> float:
    """A range-checked float env knob; empty/unset means ``default``."""
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return default
    try:
        value = float(raw)
    except ValueError:
        raise ValueError(
            f"{name}={raw!r}: expected a number in [{lo}, {hi}]"
        )
    if not lo <= value <= hi:
        raise ValueError(f"{name}={value}: out of range [{lo}, {hi}]")
    return value


def env_raw(name: str, default: Optional[str] = None) -> Optional[str]:
    """A free-form env value (path, address, API key): pass-through with
    no validation beyond centralizing the read.  None when unset."""
    value = os.environ.get(name)
    return default if value is None else value


def environ_copy() -> "Dict[str, str]":
    """A snapshot of the FULL environment — for spawning child processes
    (the federation's workers inherit the parent's RCA_*/JAX_* knobs and
    overlay their own).  Reading it here keeps env-discipline honest:
    the one non-knob environ consumer is named, not scattered."""
    return dict(os.environ)


@dataclasses.dataclass(frozen=True)
class RCAConfig:
    # Correlation backend: jax | deterministic | llm
    backend: str = "jax"
    # LLM provider for the optional LLM paths: anthropic | openai | offline
    llm_provider: str = "offline"
    # Where investigations / evidence / prompt logs are persisted
    log_dir: str = "logs"
    # Kubeconfig path for the live-cluster client
    kubeconfig: Optional[str] = None
    # Default namespace when the caller does not pass one
    namespace: str = "default"
    # Engine knobs
    propagation_steps: int = 8
    top_k_root_causes: int = 5
    # Streaming tick pipeline depth (RCA_PIPELINE_DEPTH): 1 = serial
    # capture→dispatch→fetch per poll (the pre-round-6 behavior,
    # bit-identical); N >= 2 keeps N-1 ticks in flight — each poll
    # dispatches this tick's work and fetches the tick issued N-1 polls
    # ago, hiding the tunnel RTT behind the next poll's host capture at
    # the cost of N-1 polls of result latency (surfaced per tick in the
    # health record).  See engine/live.py and PERF.md round-6.
    pipeline_depth: int = 1
    # Shape-bucket tiers for jit recompilation control (padded node AND
    # edge counts).  Explicit power-of-two tiers up to 4096; above, sizes
    # round up to 8 sub-tiers per octave (bucket_for), because the
    # down-scan scatter serializes over the PADDED edge count (~33 ns/lane
    # on v5e, PERF.md): the round-1 4x tiers made a 10k-service graph pay
    # a 65536-lane scatter for ~20k real edges (3.3x waste), and a plain
    # pow2 ladder padded 50k's ~100k edges to 131072 (+31%, measured +20ms
    # per inference).  Relative tiers cap waste at 12.5% at any scale for
    # a bounded executable count (8 per octave).
    shape_buckets: tuple = (64, 128, 256, 512, 1024, 2048, 4096)

    def __post_init__(self):
        if self.backend not in VALID_BACKENDS:
            raise ValueError(
                f"backend must be one of {VALID_BACKENDS}, got {self.backend!r}"
            )

    @classmethod
    def from_env(cls, **overrides) -> "RCAConfig":
        env = {
            "backend": os.environ.get("RCA_BACKEND", "jax"),
            "llm_provider": os.environ.get("LLM_PROVIDER", "offline"),
            "log_dir": os.environ.get("RCA_LOG_DIR", "logs"),
            "kubeconfig": os.environ.get("KUBECONFIG"),
            "pipeline_depth": pipeline_depth_from_env(),
        }
        env.update(overrides)
        return cls(**env)


def pipeline_depth_from_env(default: int = 1) -> int:
    """``RCA_PIPELINE_DEPTH`` as a validated int (>= 1); empty/unset means
    the caller's default.  A malformed value fails loudly — a typo'd depth
    silently running serial would fake away the optimization it asked for.
    """
    raw = (os.environ.get("RCA_PIPELINE_DEPTH") or "").strip()
    if not raw:
        return default
    try:
        depth = int(raw)
    except ValueError:
        raise ValueError(
            f"RCA_PIPELINE_DEPTH={raw!r}: expected a positive integer"
        )
    if depth < 1:
        raise ValueError(
            f"RCA_PIPELINE_DEPTH={depth}: depth counts this tick too, so "
            "it must be >= 1 (1 = serial)"
        )
    return depth


# -- device-resident sessions (ISSUE 6) -------------------------------------
# env knobs for the resident analyze path (engine/resident.py), each
# validated here so a typo'd value fails loudly instead of silently
# disabling (or mis-sizing) the cache:
#
#   RCA_RESIDENT        1 (default) | 0 — keep per-graph analysis state
#                       device-resident across one-shot analyze calls, so
#                       a repeat request over a known graph uploads only
#                       its changed feature rows (bit-identical results;
#                       0 restores the restage-everything behavior)
#   RCA_RESIDENT_CACHE  [1, 1024]  resident sessions kept per engine
#                       (LRU beyond the cap; default 8 — each session
#                       pins one [n_pad, C] device buffer)
#   RCA_SERVE_GRAPH_CACHE [1, 4096]  prepared graphs (edges + layouts +
#                       resident base features) the serving dispatcher
#                       keeps hot (default 32)


def resident_enabled() -> bool:
    """``RCA_RESIDENT``: device-resident one-shot analyze sessions."""
    return env_str(
        "RCA_RESIDENT", "1", choices=("0", "1", "on", "off"), lower=True,
    ) in ("1", "on")


def resident_cache_cap() -> int:
    """``RCA_RESIDENT_CACHE``: resident sessions kept per engine (LRU)."""
    return env_int("RCA_RESIDENT_CACHE", 8, 1, 1024)


def serve_graph_cache_cap() -> int:
    """``RCA_SERVE_GRAPH_CACHE``: prepared graphs the dispatcher pins."""
    return env_int("RCA_SERVE_GRAPH_CACHE", 32, 1, 4096)


def columnar_enabled() -> bool:
    """``RCA_COLUMNAR``: columnar world-state capture (ISSUE 10).  When a
    cluster client exposes ``get_columnar`` (the mock world does), snapshot
    capture reads the incrementally-maintained columnar tables instead of
    re-sanitizing and re-scanning every object per sweep, and feature
    extraction becomes a vectorized assembly over the table's columns —
    bit-identical to the per-object dict path (property-tested), ~10x
    cheaper at 10k pods and the difference between seconds and tens of
    milliseconds at 100k-1M.  Default on; 0 restores the dict scans."""
    return env_str(
        "RCA_COLUMNAR", "1", choices=("0", "1", "on", "off"), lower=True,
    ) in ("1", "on")


def rsan_enabled() -> bool:
    """``RCA_RSAN``: route the :mod:`rca_tpu.util.threads` constructors
    through the gravelock runtime lock sanitizer (ANALYSIS.md) so lock
    acquisition orders and shared-state access pairs are recorded for the
    static model's cross-check.  Default off — bare primitives, zero
    per-acquire cost."""
    return env_str(
        "RCA_RSAN", "0", choices=("0", "1", "on", "off"), lower=True,
    ) in ("1", "on")


# -- serving scheduler (ISSUE 3) --------------------------------------------
# env knobs, each a validated int with the documented range:
#
#   RCA_SERVE_MAX_BATCH   [1, 4096]          requests coalesced per device
#                                            dispatch (a full batch never
#                                            waits; default 16)
#   RCA_SERVE_MAX_WAIT_US [0, 60_000_000]    longest a request is held
#                                            waiting for batchmates while
#                                            the device is busy (µs;
#                                            default 2000 — an idle engine
#                                            never waits, see SERVING.md)
#   RCA_SERVE_QUEUE_CAP   [1, 1_000_000]     admission cap: a submit
#                                            against a full queue is
#                                            rejected (`queue_full`), the
#                                            queue never grows unboundedly
#                                            (default 256)

#   RCA_SERVE_REPLICAS    [1, 64]            engine replicas behind the
#                                            shared queue (serve pool;
#                                            default 1 = the single
#                                            ServeLoop scheduler)
#   RCA_SERVE_STEAL       0|1|on|off         work-stealing rebalance on
#                                            replica death / open breaker
#                                            (default on; off = the
#                                            victim's staged work rides
#                                            the degradation ladder)
#   RCA_SERVE_REPLICA_MIX e.g. "dense:2,sharded@4:2"   replica kinds +
#                                            device-group sizes (see
#                                            parse_replica_mix; empty =
#                                            RCA_SERVE_REPLICAS dense
#                                            replicas)

_SERVE_ENV_RANGES = {
    "RCA_SERVE_MAX_BATCH": (1, 4096),
    "RCA_SERVE_MAX_WAIT_US": (0, 60_000_000),
    "RCA_SERVE_QUEUE_CAP": (1, 1_000_000),
    "RCA_SERVE_REPLICAS": (1, 64),
}

#: replica kinds a serve-pool mix may name
REPLICA_KINDS = ("dense", "sharded")

_MIX_ENTRY = re.compile(r"(dense|sharded)(?:@(\d+))?(?::(\d+))?")


def parse_replica_mix(
    spec: str, default_replicas: int = 1,
) -> Tuple[Tuple[str, Optional[int]], ...]:
    """``RCA_SERVE_REPLICA_MIX`` → ``((kind, group_size|None), ...)``.

    Syntax: comma-separated ``kind[@group_size][:count]`` entries, e.g.
    ``"dense:2,sharded@4:2"`` = two dense replicas (one device each) plus
    two sharded replicas spanning four devices each.  ``group_size``
    defaults per kind at pool-construction time (dense → 1, sharded →
    an equal share of the visible devices).  Empty/unset spec means
    ``default_replicas`` dense replicas.  Malformed specs fail loudly —
    a typo'd mix silently running one dense replica would fake away the
    scaling the operator asked for."""
    spec = (spec or "").strip().lower()
    if not spec:
        return tuple(("dense", None) for _ in range(default_replicas))
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        m = _MIX_ENTRY.fullmatch(part)
        if m is None:
            raise ValueError(
                f"RCA_SERVE_REPLICA_MIX entry {part!r}: expected "
                "'kind[@group_size][:count]' with kind in "
                f"{REPLICA_KINDS}"
            )
        kind, group, count = m.group(1), m.group(2), m.group(3)
        count = int(count) if count else 1
        group_size = int(group) if group else None
        if not 1 <= count <= 64:
            raise ValueError(
                f"RCA_SERVE_REPLICA_MIX entry {part!r}: count {count} "
                "out of range [1, 64]"
            )
        if group_size is not None and not 1 <= group_size <= 4096:
            raise ValueError(
                f"RCA_SERVE_REPLICA_MIX entry {part!r}: group size "
                f"{group_size} out of range [1, 4096]"
            )
        out.extend((kind, group_size) for _ in range(count))
    if not 1 <= len(out) <= 64:
        raise ValueError(
            f"RCA_SERVE_REPLICA_MIX={spec!r}: {len(out)} replicas out "
            "of range [1, 64]"
        )
    return tuple(out)


def serve_steal_enabled() -> bool:
    """``RCA_SERVE_STEAL``: work-stealing rebalance in the serve pool."""
    return env_str(
        "RCA_SERVE_STEAL", "1", choices=("0", "1", "on", "off"),
        lower=True,
    ) in ("1", "on")


def _serve_env_int(name: str, default: int) -> int:
    """One ``RCA_SERVE_*`` env var as a range-checked int; empty/unset
    means the default.  Malformed or out-of-range values fail loudly —
    a typo'd serving knob silently falling back would fake away the
    batching (or the backpressure) the operator asked for."""
    lo, hi = _SERVE_ENV_RANGES[name]
    raw = (os.environ.get(name) or "").strip()
    if not raw:
        return default
    try:
        value = int(raw)
    except ValueError:
        raise ValueError(f"{name}={raw!r}: expected an integer in [{lo}, {hi}]")
    if not lo <= value <= hi:
        raise ValueError(f"{name}={value}: out of range [{lo}, {hi}]")
    return value


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Typed serving-scheduler knobs (rca_tpu/serve, SERVING.md)."""

    max_batch: int = 16      # RCA_SERVE_MAX_BATCH
    max_wait_us: int = 2000  # RCA_SERVE_MAX_WAIT_US
    queue_cap: int = 256     # RCA_SERVE_QUEUE_CAP
    replicas: int = 1        # RCA_SERVE_REPLICAS (serve pool width)
    steal: bool = True       # RCA_SERVE_STEAL (rebalance on death/open)
    replica_mix: str = ""    # RCA_SERVE_REPLICA_MIX ("" = all dense)

    def __post_init__(self):
        # same ranges as the env parse, so a directly-constructed config
        # cannot smuggle in a value the env path would reject
        for name, value in (
            ("RCA_SERVE_MAX_BATCH", self.max_batch),
            ("RCA_SERVE_MAX_WAIT_US", self.max_wait_us),
            ("RCA_SERVE_QUEUE_CAP", self.queue_cap),
            ("RCA_SERVE_REPLICAS", self.replicas),
        ):
            lo, hi = _SERVE_ENV_RANGES[name]
            if not lo <= int(value) <= hi:
                raise ValueError(
                    f"{name.lower().removeprefix('rca_serve_')}={value}: "
                    f"out of range [{lo}, {hi}]"
                )
        # a malformed mix fails at construction, not at pool start
        parse_replica_mix(self.replica_mix, self.replicas)

    def replica_specs(self) -> Tuple[Tuple[str, Optional[int]], ...]:
        """The resolved replica set: the parsed mix when one is given
        (its length then DEFINES the replica count), else ``replicas``
        dense entries."""
        return parse_replica_mix(self.replica_mix, self.replicas)

    @classmethod
    def from_env(cls, **overrides) -> "ServeConfig":
        env = {
            "max_batch": _serve_env_int("RCA_SERVE_MAX_BATCH", 16),
            "max_wait_us": _serve_env_int("RCA_SERVE_MAX_WAIT_US", 2000),
            "queue_cap": _serve_env_int("RCA_SERVE_QUEUE_CAP", 256),
            "replicas": _serve_env_int("RCA_SERVE_REPLICAS", 1),
            "steal": serve_steal_enabled(),
            "replica_mix": env_str("RCA_SERVE_REPLICA_MIX", ""),
        }
        env.update(overrides)
        return cls(**env)


# -- gateway + canary (ISSUE 9) ---------------------------------------------
# env knobs for the wire front door (rca_tpu/gateway, SERVING.md §Gateway)
# and the replay-driven regression canary (REPLAY.md §Canary), each
# validated here so a typo'd value fails loudly:
#
#   RCA_GATEWAY_PORT      [0, 65535]  default listen port for
#                         `rca serve --listen` when the spec omits one
#                         (default 8321; 0 = kernel-chosen ephemeral —
#                         the CLI prints the bound port)
#   RCA_GATEWAY_MAX_BODY  [1024, 1_073_741_824]  largest request body the
#                         gateway accepts, bytes (default 8 MiB; larger
#                         bodies get 413 before any parse — backpressure
#                         must not require reading the flood first)
#   RCA_CANARY_SAMPLE_RATE [0.0, 1.0]  probability `rca canary` records a
#                         given sampling round into the regression corpus
#                         (default 1.0 — every round; production tuning
#                         trades corpus freshness for record overhead)


def gateway_port() -> int:
    """``RCA_GATEWAY_PORT``: the gateway's default listen port."""
    return env_int("RCA_GATEWAY_PORT", 8321, 0, 65535)


def gateway_max_body() -> int:
    """``RCA_GATEWAY_MAX_BODY``: request-body byte cap (413 beyond it)."""
    return env_int("RCA_GATEWAY_MAX_BODY", 8 * 1024 * 1024, 1024,
                   1 << 30)


def canary_sample_rate() -> float:
    """``RCA_CANARY_SAMPLE_RATE``: per-round recording probability."""
    return env_float("RCA_CANARY_SAMPLE_RATE", 1.0, 0.0, 1.0)


# -- gateway TLS + authn (ISSUE 15) ------------------------------------------
# env knobs for the hardened front door (SERVING.md §Gateway security):
#
#   RCA_GATEWAY_TLS_CERT  PEM certificate chain file; with
#   RCA_GATEWAY_TLS_KEY   the PEM private key, the gateway listener is
#                         wrapped in TLS (util/net.py seam, TLS 1.2+).
#                         Setting one without the other fails loudly —
#                         a half-configured TLS gateway silently serving
#                         plaintext is the worst outcome.
#   RCA_GATEWAY_TOKENS    bearer-token authn + the token→tenant map:
#                         comma-separated ``token:tenant[:expires_unix]``
#                         entries.  When set, every request (except
#                         /healthz) needs ``Authorization: Bearer <tok>``
#                         — checked constant-time BEFORE the body is
#                         read — and the token's tenant BINDS the
#                         request: an X-RCA-Tenant header naming a
#                         different tenant is a spoof attempt (403).
#   RCA_GATEWAY_TLS_CLIENT_CA
#                         PEM CA bundle for MUTUAL TLS (ISSUE 16): when
#                         set (requires the cert/key pair above), the
#                         listener demands and verifies a client
#                         certificate at handshake; a client without one
#                         is rejected before a single HTTP byte and the
#                         rejection counts in ``auth_rejections``.


def gateway_tls_files() -> Optional[Tuple[str, str]]:
    """``RCA_GATEWAY_TLS_CERT``/``RCA_GATEWAY_TLS_KEY`` as a validated
    pair: both set → ``(cert, key)``; neither → None (plaintext); one
    without the other raises."""
    cert = (env_raw("RCA_GATEWAY_TLS_CERT") or "").strip()
    key = (env_raw("RCA_GATEWAY_TLS_KEY") or "").strip()
    if not cert and not key:
        return None
    if not (cert and key):
        raise ValueError(
            "RCA_GATEWAY_TLS_CERT and RCA_GATEWAY_TLS_KEY must be set "
            "together (a half-configured TLS gateway would silently "
            "serve plaintext)"
        )
    return cert, key


def gateway_tls_client_ca() -> Optional[str]:
    """``RCA_GATEWAY_TLS_CLIENT_CA``: PEM CA bundle that turns the TLS
    gateway MUTUAL — set without the cert/key pair raises (an mTLS knob
    on a plaintext listener would silently verify nobody)."""
    ca = (env_raw("RCA_GATEWAY_TLS_CLIENT_CA") or "").strip()
    if not ca:
        return None
    if gateway_tls_files() is None:
        raise ValueError(
            "RCA_GATEWAY_TLS_CLIENT_CA requires RCA_GATEWAY_TLS_CERT/"
            "RCA_GATEWAY_TLS_KEY (client-cert verification needs a TLS "
            "listener to verify on)"
        )
    return ca


def parse_gateway_tokens(spec: str) -> "Dict[str, Tuple[str, Optional[float]]]":
    """``RCA_GATEWAY_TOKENS`` → ``{token: (tenant, expires_unix|None)}``.

    Syntax: comma-separated ``token:tenant[:expires_unix]``.  Tokens and
    tenants must be non-empty and tokens unique; a malformed spec fails
    loudly — a typo'd token list silently running the gateway OPEN would
    fake away the authn the operator asked for."""
    out: Dict[str, Tuple[str, Optional[float]]] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) not in (2, 3) or not fields[0] or not fields[1]:
            raise ValueError(
                f"RCA_GATEWAY_TOKENS entry {part!r}: expected "
                "'token:tenant[:expires_unix]'"
            )
        expires: Optional[float] = None
        if len(fields) == 3:
            try:
                expires = float(fields[2])
            except ValueError:
                raise ValueError(
                    f"RCA_GATEWAY_TOKENS entry {part!r}: expiry "
                    f"{fields[2]!r} is not a number"
                )
        if fields[0] in out:
            raise ValueError(
                f"RCA_GATEWAY_TOKENS: duplicate token {fields[0][:4]}…"
            )
        out[fields[0]] = (fields[1], expires)
    return out


def gateway_tokens() -> "Dict[str, Tuple[str, Optional[float]]]":
    """``RCA_GATEWAY_TOKENS`` parsed; empty dict = authn disabled."""
    return parse_gateway_tokens(env_raw("RCA_GATEWAY_TOKENS") or "")


def gateway_tenant_rps() -> float:
    """``RCA_GATEWAY_TENANT_RPS``: per-tenant token-bucket rate limit at
    the gateway, requests/second ([0, 1e6]; 0 = disabled, the default).
    Until ISSUE 10 the only admission control was the GLOBAL serve-queue
    cap, so one hot tenant could starve every other tenant's wire
    requests before weighted-fair queuing ever saw them; with a rate set,
    each tenant gets an independent bucket (burst = one second's worth)
    and excess requests are refused at the door with 429 + Retry-After
    before touching the serve queue."""
    return env_float("RCA_GATEWAY_TENANT_RPS", 0.0, 0.0, 1e6)


# -- serve federation (ISSUE 15) ---------------------------------------------
# env knobs for the cross-process serving plane (rca_tpu/serve/federation.py,
# SERVING.md §Federation), each validated here so a typo'd value fails loudly:
#
#   RCA_FED_WORKERS      [1, 64]  worker processes the federation control
#                        plane supervises (default 2); each worker runs a
#                        full ServeLoop/ServePool over its own devices
#   RCA_FED_HEARTBEAT_S  [0.01, 60.0]  worker heartbeat interval, seconds
#                        (default 0.5); the lease TTL is
#                        heartbeat_s * RCA_FED_LEASE_MISSES, so ONE late
#                        heartbeat never kills a worker
#   RCA_FED_LEASE_MISSES [2, 100]  consecutive missed heartbeats before a
#                        worker's lease expires and its work reroutes
#                        (default 3)
#   RCA_FED_WINDOW       [1, 4096]  per-worker outstanding-request window
#                        the router enforces (default 64): stickiness
#                        spills to the next ring worker past it, so one
#                        hot bucket cannot wedge the whole plane behind
#                        one process
#
# elasticmesh (ISSUE 16) — the autoscaling controller's fleet bounds and
# pacing (rca_tpu/serve/autoscale.py, SERVING.md §Autoscaling):
#
#   RCA_FED_SCALE_MIN        [1, 64]  fleet floor the controller never
#                            drains below (default 1)
#   RCA_FED_SCALE_MAX        [1, 64]  fleet ceiling it never spawns past
#                            (default 8); min > max fails loudly at
#                            controller construction
#   RCA_FED_SCALE_COOLDOWN_S [0.05, 600.0]  quiet period after ANY scale
#                            action before the next may fire (default
#                            10.0) — with the per-rule sustain windows in
#                            SCALE_RULES this is what makes a flapping
#                            load signal unable to thrash the ring
#   RCA_FED_SCALE_INTERVAL_S [0.01, 60.0]  controller sweep cadence,
#                            seconds (default 1.0)


def fed_workers() -> int:
    """``RCA_FED_WORKERS``: worker processes under the federation."""
    return env_int("RCA_FED_WORKERS", 2, 1, 64)


def fed_heartbeat_s() -> float:
    """``RCA_FED_HEARTBEAT_S``: worker heartbeat interval (seconds)."""
    return env_float("RCA_FED_HEARTBEAT_S", 0.5, 0.01, 60.0)


def fed_lease_misses() -> int:
    """``RCA_FED_LEASE_MISSES``: missed heartbeats before lease expiry."""
    return env_int("RCA_FED_LEASE_MISSES", 3, 2, 100)


def fed_window() -> int:
    """``RCA_FED_WINDOW``: per-worker outstanding-request window."""
    return env_int("RCA_FED_WINDOW", 64, 1, 4096)


def fed_scale_min() -> int:
    """``RCA_FED_SCALE_MIN``: autoscaler fleet floor."""
    return env_int("RCA_FED_SCALE_MIN", 1, 1, 64)


def fed_scale_max() -> int:
    """``RCA_FED_SCALE_MAX``: autoscaler fleet ceiling."""
    return env_int("RCA_FED_SCALE_MAX", 8, 1, 64)


def fed_scale_cooldown_s() -> float:
    """``RCA_FED_SCALE_COOLDOWN_S``: quiet period after a scale action."""
    return env_float("RCA_FED_SCALE_COOLDOWN_S", 10.0, 0.05, 600.0)


def fed_scale_interval_s() -> float:
    """``RCA_FED_SCALE_INTERVAL_S``: controller sweep cadence (seconds)."""
    return env_float("RCA_FED_SCALE_INTERVAL_S", 1.0, 0.01, 60.0)


# -- tracing + SLO telemetry (ISSUE 11) --------------------------------------
# env knobs for the span-based tracing subsystem (rca_tpu/observability,
# OBSERVABILITY.md), each validated here so a typo'd value fails loudly:
#
#   RCA_TRACE         0 (default) | 1 — wire-to-device distributed tracing.
#                     0 is the ZERO-COST path: every component holds the
#                     shared NULL tracer, span calls are constant no-ops,
#                     and results are bit-identical to pre-tracing builds
#                     (property-tested).  1 records spans into the bounded
#                     ring buffer, exports them on `GET /v1/traces`, and
#                     stamps them into tick health records + recordings.
#   RCA_TRACE_BUFFER  [64, 1_000_000]  spans kept in the ring buffer
#                     (default 8192; beyond it the OLDEST spans drop and
#                     the drop counter rises — saturation sheds history,
#                     never blocks the serve path)
#   RCA_SLO_MS        [1, 600_000]  per-request latency SLO target, ms
#                     (default 500) — the burn-rate counters in /metrics
#                     count completions slower than this (or failed)


def trace_enabled() -> bool:
    """``RCA_TRACE``: span-based request tracing (default off — the
    zero-cost null-tracer path)."""
    return env_str(
        "RCA_TRACE", "0", choices=("0", "1", "on", "off"), lower=True,
    ) in ("1", "on")


def trace_buffer_cap() -> int:
    """``RCA_TRACE_BUFFER``: ring-buffer span capacity."""
    return env_int("RCA_TRACE_BUFFER", 8192, 64, 1_000_000)


def slo_ms() -> float:
    """``RCA_SLO_MS``: the per-request latency SLO target (ms)."""
    return env_float("RCA_SLO_MS", 500.0, 1.0, 600_000.0)


# -- causelens: evidence attribution (ISSUE 14) ------------------------------
# env knobs for on-device blame attribution (rca_tpu/engine/attribution.py +
# rca_tpu/observability/causelens.py, OBSERVABILITY.md §causelens), each
# validated here so a typo'd value fails loudly:
#
#   RCA_EXPLAIN        0 (default) | 1 — compute a per-ranking provenance
#                      block (per-channel evidence contributions,
#                      counterfactual evidence rows, blame paths, gradient
#                      saliency) beside every streaming tick, and stamp
#                      its digest into recordings so `rca replay --explain`
#                      can parity-check attributions against the tape.
#                      Serve/gateway explain is per-request (the
#                      ServeRequest.explain flag / ?explain=1), not gated
#                      by this knob.
#   RCA_EXPLAIN_PATHS  [1, 16]  blame-path hop cap per candidate (the
#                      greedy up-term walk; default 4)
#   RCA_EXPLAIN_TOPM   [1, 64]  evidence rows the counterfactual sweep
#                      masks (top-M by anomaly; default 8 — each row is
#                      one extra vmapped propagation lane)


def explain_enabled() -> bool:
    """``RCA_EXPLAIN``: per-tick attribution + recording digests."""
    return env_str(
        "RCA_EXPLAIN", "0", choices=("0", "1", "on", "off"), lower=True,
    ) in ("1", "on")


def explain_paths() -> int:
    """``RCA_EXPLAIN_PATHS``: blame-path hop cap per candidate."""
    return env_int("RCA_EXPLAIN_PATHS", 4, 1, 16)


def explain_topm() -> int:
    """``RCA_EXPLAIN_TOPM``: counterfactual evidence rows per sweep."""
    return env_int("RCA_EXPLAIN_TOPM", 8, 1, 64)


# -- kernel registry + kernelscope (ISSUE 12) --------------------------------
# env knobs for the per-shape kernel registry (rca_tpu/engine/registry.py)
# and the kernelscope runtime watchdogs (rca_tpu/observability/kernelscope),
# each validated here so a typo'd value fails loudly:
#
#   RCA_KERNEL_CACHE   file the registry persists timed autotune winners +
#                      cost rows to (keyed by jax version + kernel-set
#                      hash, so upgrades re-time); default
#                      ~/.cache/rca_tpu/kernel_cache.<platform>.json
#                      (platform-keyed, ISSUE 17); 0|off|none disables
#                      persistence entirely.  A committed read-only seed
#                      (rca_tpu/engine/kernel_cache.<platform>.json)
#                      backstops a cold user cache.
#   RCA_KERNELSCOPE    1 (default) | 0 — the runtime recompile watchdog
#                      (a jax_log_compiles-fed monitor counting any
#                      compilation whose signature was already compiled —
#                      the dynamic complement of tracecheck, running
#                      continuously on tick/serve paths) and the
#                      device-memory accountant in health records and
#                      ServeMetrics
#   RCA_MEM_SAMPLE_EVERY [1, 100000]  ticks between device-memory samples
#                      in streaming health records (default 10 — the
#                      live-buffer walk is cheap but not free)


def kernel_platform() -> str:
    """The platform key the winner cache files are named by — the JAX
    default backend ("cpu", "tpu", "gpu"), falling back to "cpu" before
    jax is importable.  Filesystem-safe by construction."""
    try:
        import jax

        name = str(jax.default_backend()).strip().lower()
    except Exception:
        name = "cpu"
    return "".join(ch if ch.isalnum() or ch in "-_" else "-" for ch in name) \
        or "cpu"


def kernel_cache_path() -> Optional[str]:
    """``RCA_KERNEL_CACHE``: the registry's autotune/cost cache file.
    Unset/empty = the PLATFORM-KEYED default under ``~/.cache``
    (``kernel_cache.<platform>.json`` — ISSUE 17: a CPU host and a TPU
    host must never overwrite each other's timed winners); ``0``/``off``/
    ``none`` = disabled (returns None)."""
    raw = (env_raw("RCA_KERNEL_CACHE") or "").strip()
    if not raw:
        return os.path.join(
            os.path.expanduser("~"), ".cache", "rca_tpu",
            f"kernel_cache.{kernel_platform()}.json",
        )
    if raw.lower() in ("0", "off", "none"):
        return None
    return raw


def shipped_kernel_cache_path() -> str:
    """The committed-shippable winner cache for this platform
    (``rca_tpu/engine/kernel_cache.<platform>.json``): read-only seed
    rows so fleet workers skip the autotune cold-start.  Stale headers
    (different jax version / kernel-set hash) are rejected by the same
    header check as the user cache — stale platform keys re-time, they
    never poison."""
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "engine",
        f"kernel_cache.{kernel_platform()}.json",
    )


def kernelscope_enabled() -> bool:
    """``RCA_KERNELSCOPE``: recompile watchdog + memory accountant."""
    return env_str(
        "RCA_KERNELSCOPE", "1", choices=("0", "1", "on", "off"),
        lower=True,
    ) in ("1", "on")


# -- live columnar ingestion + multi-cluster capture (ISSUE 17) --------------
# env knobs for the live get_columnar adapter (cluster/live_columnar.py),
# the ClusterSet merged world (cluster/clusterset.py), and the fleetmesh
# cluster-ingest worker class (serve/federation.py):
#
#   RCA_INGEST_TOPO_EVERY [0, 100000]  re-list + rv-diff the topology kinds
#                      (services, deployments, ... — everything the watch
#                      pumps do not stream) every Nth sweep; 0 = never
#                      (watch entries only; real pumps then never refresh
#                      topology).  Default 1: every sweep, the rv-diff
#                      makes unchanged stores free downstream.
#   RCA_INGEST_LOGS    1 (default) | 0 — fetch tail-200 container logs
#                      into the shadow world when a pod changes.  Off
#                      keeps log-pattern columns at zero (clusters where
#                      the log API is the expensive hop) and trades away
#                      log-channel evidence + dict-path parity on pods
#                      with logs.
#   RCA_INGEST_TICK_S  [0.0, 60.0]  ingest-worker capture cadence inside
#                      fleetmesh cluster-ingest workers (default 0.05)


def ingest_topo_every() -> int:
    """``RCA_INGEST_TOPO_EVERY``: topology re-list cadence (sweeps)."""
    return env_int("RCA_INGEST_TOPO_EVERY", 1, 0, 100_000)


def ingest_log_fetch() -> bool:
    """``RCA_INGEST_LOGS``: fetch container logs into the live feed."""
    return env_str(
        "RCA_INGEST_LOGS", "1", choices=("0", "1", "on", "off"),
        lower=True,
    ) in ("1", "on")


def ingest_tick_s() -> float:
    """``RCA_INGEST_TICK_S``: ingest-worker capture cadence (seconds)."""
    return env_float("RCA_INGEST_TICK_S", 0.05, 0.0, 60.0)


def memory_sample_every() -> int:
    """``RCA_MEM_SAMPLE_EVERY``: ticks between device-memory samples."""
    return env_int("RCA_MEM_SAMPLE_EVERY", 10, 1, 100_000)


# -- persistent compilation cache (ISSUE 2 satellite) -----------------------
# enabled at most once per process; the dict is the recorded status the
# session health records and bench line carry
_COMPILE_CACHE: Optional[dict] = None


def enable_compile_cache() -> dict:
    """Point JAX's persistent compilation cache at ``RCA_COMPILE_CACHE``
    (a directory) so repeated sessions skip recompiling the tick
    executables — a 50k sharded session pays tens of seconds of XLA
    compile on first run that a warm cache turns into a disk read.
    Unset = disabled (the default: tests and one-off runs keep their
    hermetic no-cache behavior).  Idempotent; returns the status dict
    (``compile_cache_entries`` counts cache files at call time, so a
    caller sampling it before and after a session's first tick sees
    miss-compiles as new entries)."""
    global _COMPILE_CACHE
    if _COMPILE_CACHE is not None:
        return compile_cache_status()
    cache_dir = (os.environ.get("RCA_COMPILE_CACHE") or "").strip()
    if not cache_dir:
        _COMPILE_CACHE = {"enabled": False}
        return compile_cache_status()
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache every executable: the tick executables the streaming
        # sessions rely on compile in well under the 1s default floor
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _COMPILE_CACHE = {"enabled": True, "dir": cache_dir}
    except Exception as exc:  # pragma: no cover - depends on jax build
        # a missing cache feature must not take down the engine: record
        # why it is off and run uncached
        _COMPILE_CACHE = {
            "enabled": False, "dir": cache_dir,
            "error": f"{type(exc).__name__}: {exc}",
        }
    return compile_cache_status()


def compile_cache_status() -> dict:
    """Current cache status + entry count (cheap directory scan)."""
    status = dict(_COMPILE_CACHE or {"enabled": False})
    if status.get("enabled"):
        try:
            status["entries"] = sum(
                1 for e in os.scandir(status["dir"]) if e.is_file()
            )
        except OSError:
            status["entries"] = 0
    return status


def bucket_for(n: int, buckets) -> int:
    """Smallest shape bucket ≥ n (controls jit recompilation).

    Within ``buckets``: the explicit tier list.  Beyond it: round up to the
    next multiple of an eighth of n's power-of-two octave — relative
    padding ≤ 12.5% with at most 8 executables per octave, vs the pow2
    ladder's 2x worst case (which is real money when a scatter serializes
    over every padded lane)."""
    for b in buckets:
        if n <= b:
            return b
    n = int(n)
    quantum = max(1 << (n.bit_length() - 1), 8) // 8
    return ((n + quantum - 1) // quantum) * quantum
