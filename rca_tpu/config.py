"""Typed configuration for the framework.

Replaces the reference's ad-hoc env-var reads scattered across modules
(reference: app.py:45, utils/llm_client_improved.py:41-53) with one frozen
dataclass resolved once.  The ``RCA_BACKEND`` flag selects the correlation
engine per the north star: ``jax`` (TPU graph inference, default here),
``deterministic`` (CPU rule-based oracle), or ``llm`` (provider fusion).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

VALID_BACKENDS = ("jax", "deterministic", "llm")


@dataclasses.dataclass(frozen=True)
class RCAConfig:
    # Correlation backend: jax | deterministic | llm
    backend: str = "jax"
    # LLM provider for the optional LLM paths: anthropic | openai | offline
    llm_provider: str = "offline"
    # Where investigations / evidence / prompt logs are persisted
    log_dir: str = "logs"
    # Kubeconfig path for the live-cluster client
    kubeconfig: Optional[str] = None
    # Default namespace when the caller does not pass one
    namespace: str = "default"
    # Engine knobs
    propagation_steps: int = 8
    top_k_root_causes: int = 5
    # Shape-bucket tiers for jit recompilation control (padded node counts)
    shape_buckets: tuple = (64, 256, 1024, 4096, 16384, 65536)

    def __post_init__(self):
        if self.backend not in VALID_BACKENDS:
            raise ValueError(
                f"backend must be one of {VALID_BACKENDS}, got {self.backend!r}"
            )

    @classmethod
    def from_env(cls, **overrides) -> "RCAConfig":
        env = {
            "backend": os.environ.get("RCA_BACKEND", "jax"),
            "llm_provider": os.environ.get("LLM_PROVIDER", "offline"),
            "log_dir": os.environ.get("RCA_LOG_DIR", "logs"),
            "kubeconfig": os.environ.get("KUBECONFIG"),
        }
        env.update(overrides)
        return cls(**env)


def bucket_for(n: int, buckets) -> int:
    """Smallest shape bucket ≥ n (controls jit recompilation)."""
    for b in buckets:
        if n <= b:
            return b
    return int(n)
