"""Typed configuration for the framework.

Replaces the reference's ad-hoc env-var reads scattered across modules
(reference: app.py:45, utils/llm_client_improved.py:41-53) with one frozen
dataclass resolved once.  The ``RCA_BACKEND`` flag selects the correlation
engine per the north star: ``jax`` (TPU graph inference, default here),
``deterministic`` (CPU rule-based oracle), or ``llm`` (provider fusion).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

VALID_BACKENDS = ("jax", "deterministic", "llm")


@dataclasses.dataclass(frozen=True)
class RCAConfig:
    # Correlation backend: jax | deterministic | llm
    backend: str = "jax"
    # LLM provider for the optional LLM paths: anthropic | openai | offline
    llm_provider: str = "offline"
    # Where investigations / evidence / prompt logs are persisted
    log_dir: str = "logs"
    # Kubeconfig path for the live-cluster client
    kubeconfig: Optional[str] = None
    # Default namespace when the caller does not pass one
    namespace: str = "default"
    # Engine knobs
    propagation_steps: int = 8
    top_k_root_causes: int = 5
    # Shape-bucket tiers for jit recompilation control (padded node AND
    # edge counts).  Explicit power-of-two tiers up to 4096; above, sizes
    # round up to 8 sub-tiers per octave (bucket_for), because the
    # down-scan scatter serializes over the PADDED edge count (~33 ns/lane
    # on v5e, PERF.md): the round-1 4x tiers made a 10k-service graph pay
    # a 65536-lane scatter for ~20k real edges (3.3x waste), and a plain
    # pow2 ladder padded 50k's ~100k edges to 131072 (+31%, measured +20ms
    # per inference).  Relative tiers cap waste at 12.5% at any scale for
    # a bounded executable count (8 per octave).
    shape_buckets: tuple = (64, 128, 256, 512, 1024, 2048, 4096)

    def __post_init__(self):
        if self.backend not in VALID_BACKENDS:
            raise ValueError(
                f"backend must be one of {VALID_BACKENDS}, got {self.backend!r}"
            )

    @classmethod
    def from_env(cls, **overrides) -> "RCAConfig":
        env = {
            "backend": os.environ.get("RCA_BACKEND", "jax"),
            "llm_provider": os.environ.get("LLM_PROVIDER", "offline"),
            "log_dir": os.environ.get("RCA_LOG_DIR", "logs"),
            "kubeconfig": os.environ.get("KUBECONFIG"),
        }
        env.update(overrides)
        return cls(**env)


def bucket_for(n: int, buckets) -> int:
    """Smallest shape bucket ≥ n (controls jit recompilation).

    Within ``buckets``: the explicit tier list.  Beyond it: round up to the
    next multiple of an eighth of n's power-of-two octave — relative
    padding ≤ 12.5% with at most 8 executables per octave, vs the pow2
    ladder's 2x worst case (which is real money when a scatter serializes
    over every padded lane)."""
    for b in buckets:
        if n <= b:
            return b
    n = int(n)
    quantum = max(1 << (n.bit_length() - 1), 8) // 8
    return ((n + quantum - 1) // quantum) * quantum
