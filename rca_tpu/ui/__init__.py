"""UI surface: Streamlit app (thin) + pure render helpers."""

from rca_tpu.ui.render import (
    finding_markdown,
    initial_suggestions,
    report_markdown,
    response_markdown,
    root_causes_markdown,
    topology_plot_data,
)

__all__ = [
    "finding_markdown",
    "initial_suggestions",
    "report_markdown",
    "response_markdown",
    "root_causes_markdown",
    "topology_plot_data",
]
