"""Pure render helpers (no streamlit import) — testable without the UI.

These build the markdown/plot payloads the Streamlit layer displays, parity
with the reference's render logic (reference: components/report.py:57-196
tabbed report, components/visualization.py:647-764 topology scatter data,
components/chatbot_interface.py:90-143 starter suggestions).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List

from rca_tpu.findings import SEVERITY_ORDER as _SEVERITY_ASC  # noqa: N811
from rca_tpu.findings import max_severity

SEVERITY_ICONS = {
    "critical": "🔴", "high": "🟠", "medium": "🟡", "low": "🔵", "info": "⚪",
}

# severity + node-type palettes mirror the reference's per-type renderers
# (reference: components/visualization.py:424-431 severity color map,
# :692-699 node-type colors) so chart specs carry the same visual language
SEVERITY_COLORS = {
    "critical": "#FF0000", "high": "#FF6B6B", "medium": "#FFAC4B",
    "low": "#4B93FF", "info": "#6BCB77",
}
NODE_TYPE_COLORS = {
    "service": "#00BFFF", "workload": "#FF6B6B", "deployment": "#FF6B6B",
    "ingress": "#FFAC4B", "configmap": "#6BCB77", "secret": "#9775FA",
    "unknown": "#CCCCCC",
}
# display order (most severe first) DERIVED from the canonical
# ascending order in rca_tpu.findings — one source of severity truth
SEVERITY_DISPLAY_ORDER = list(reversed(_SEVERITY_ASC))


def initial_suggestions(namespace: str) -> List[Dict[str, Any]]:
    """Canned starter actions (reference: chatbot_interface.py:90-143)."""
    return [
        {"text": "Run a comprehensive analysis", "priority": "high",
         "reasoning": "correlates all signals into ranked root causes",
         "action": {"type": "run_agent", "agent_type": "comprehensive"}},
        {"text": "Check for problem pods", "priority": "medium",
         "reasoning": "fast pod-level health overview",
         "action": {"type": "query",
                    "query": f"Which pods in {namespace} have problems?"}},
        {"text": "Review warning events", "priority": "medium",
         "reasoning": "events often name the failure directly",
         "action": {"type": "run_agent", "agent_type": "events"}},
        {"text": "Inspect service topology", "priority": "low",
         "reasoning": "dependency structure shows blast radius",
         "action": {"type": "run_agent", "agent_type": "topology"}},
        {"text": "Check resource utilization", "priority": "low",
         "reasoning": "CPU/memory pressure causes cascading symptoms",
         "action": {"type": "run_agent", "agent_type": "metrics"}},
    ]


def finding_markdown(f: Dict[str, Any]) -> str:
    icon = SEVERITY_ICONS.get(str(f.get("severity", "info")).lower(), "⚪")
    return (
        f"{icon} **{f.get('component', '?')}** — {f.get('issue', '')}\n\n"
        f"- severity: `{f.get('severity', '')}`  · source: "
        f"`{f.get('source', 'rule')}`\n"
        f"- recommendation: {f.get('recommendation', '')}"
    )


def root_causes_markdown(correlated: Dict[str, Any]) -> str:
    lines = [f"### Ranked root causes ({correlated.get('backend', '?')} backend)"]
    for i, rc in enumerate(correlated.get("root_causes", [])[:10]):
        icon = SEVERITY_ICONS.get(str(rc.get("severity", "info")), "⚪")
        lines.append(
            f"{i + 1}. {icon} **{rc['component']}** — score "
            f"{rc.get('score', 0):.3f}, {rc.get('finding_count', 0)} "
            f"finding(s), max severity {rc.get('severity', '')}"
        )
    if correlated.get("engine_latency_ms"):
        lines.append(
            f"\n*TPU propagation latency: "
            f"{correlated['engine_latency_ms']:.1f} ms*"
        )
    return "\n".join(lines)


def response_markdown(response_data: Dict[str, Any]) -> str:
    lines = [f"- {p}" for p in response_data.get("points", [])]
    for sec in response_data.get("sections", []):
        lines.append(f"\n**{sec.get('title', '')}**")
        content = sec.get("content", [])
        if isinstance(content, list):
            lines += [f"  - {c}" for c in content]
        else:
            lines.append(f"  {content}")
    return "\n".join(lines)


def topology_plot_data(graph_dict: Dict[str, Any]) -> Dict[str, Any]:
    """Deterministic circular layout for the typed graph — node/edge coords
    ready for any scatter backend (reference used networkx spring_layout,
    components/visualization.py:647-764; a fixed layout keeps the UI stable
    across reruns)."""
    nodes = graph_dict.get("nodes", [])
    edges = graph_dict.get("edges", [])
    n = max(len(nodes), 1)
    pos = {}
    by_type: Dict[str, List[int]] = {}
    for i, node in enumerate(nodes):
        by_type.setdefault(node.get("type", "service"), []).append(i)
    # concentric rings per node type
    ring_radius = {"service": 1.0, "workload": 1.6, "ingress": 0.5,
                   "configmap": 2.1, "secret": 2.1}
    for ntype, members in by_type.items():
        r = ring_radius.get(ntype, 1.3)
        for k, i in enumerate(members):
            theta = 2 * math.pi * k / max(len(members), 1)
            pos[nodes[i]["id"]] = (r * math.cos(theta), r * math.sin(theta))
    # node-type coloring + per-type/per-relation legends (reference:
    # components/visualization.py:647-764 draws one colored scatter trace
    # per node type and per edge type, with a legend entry each)
    drawn = [
        e for e in edges if e["source"] in pos and e["target"] in pos
    ]
    relation_counts: Dict[str, int] = {}
    for e in drawn:
        rel = e.get("relation", "") or "related"
        relation_counts[rel] = relation_counts.get(rel, 0) + 1
    return {
        "nodes": [
            {"id": node["id"], "type": node.get("type", ""),
             "color": NODE_TYPE_COLORS.get(
                 node.get("type", ""), NODE_TYPE_COLORS["unknown"]),
             "x": pos[node["id"]][0], "y": pos[node["id"]][1]}
            for node in nodes
        ],
        "edges": [
            {
                "source": e["source"], "target": e["target"],
                # same normalized label the legend counts, so legend
                # entries always match drawable edge rows
                "relation": e.get("relation", "") or "related",
                "x0": pos[e["source"]][0],
                "y0": pos[e["source"]][1],
                "x1": pos[e["target"]][0],
                "y1": pos[e["target"]][1],
            }
            for e in drawn
        ],
        "node_legend": {
            ntype: NODE_TYPE_COLORS.get(ntype, NODE_TYPE_COLORS["unknown"])
            for ntype in sorted(by_type)
        },
        "edge_legend": dict(sorted(relation_counts.items())),
    }


def analysis_viz_data(agent_type: str, result: Dict[str, Any]) -> Dict[str, Any]:
    """Chart-ready payload per analysis type (reference:
    components/visualization.py renderers per type) — severity histogram for
    every agent plus type-specific series."""
    findings = result.get("findings", [])
    sev_counts: Dict[str, int] = {}
    for f in findings:
        sev = str(f.get("severity", "info")).lower()
        sev_counts[sev] = sev_counts.get(sev, 0) + 1
    out: Dict[str, Any] = {
        "agent_type": agent_type,
        "severity_histogram": sev_counts,
        "components": sorted({str(f.get("component", "")) for f in findings}),
    }
    if agent_type == "metrics":
        out["utilization"] = [
            {"component": f["component"], **f["evidence"]}
            for f in findings
            if isinstance(f.get("evidence"), dict)
            and "usage_percentage" in f["evidence"]
        ]
    elif agent_type == "resources":
        out["pod_buckets"] = result.get("data", {}).get("pod_buckets", {})
    elif agent_type == "logs":
        patterns: Dict[str, int] = {}
        comp_sev: Dict[str, Dict[str, int]] = {}
        for f in findings:
            ev = f.get("evidence")
            if isinstance(ev, dict) and ev.get("pattern"):
                patterns[ev["pattern"]] = (
                    patterns.get(ev["pattern"], 0) + int(ev.get("count", 1))
                )
            comp = str(f.get("component", "unknown"))
            sev = str(f.get("severity", "info")).lower()
            comp_sev.setdefault(comp, {})
            comp_sev[comp][sev] = comp_sev[comp].get(sev, 0) + 1
        out["pattern_counts"] = patterns
        out["component_severity"] = comp_sev
    elif agent_type == "topology":
        out["graph"] = result.get("data", {}).get("graph", {})
        out["service_pod_mapping"] = result.get("data", {}).get(
            "service_pod_mapping", {}
        )
    elif agent_type == "traces":
        out["error_rates"] = [
            {"component": f["component"],
             "error_rate": f["evidence"]["error_rate"]}
            for f in findings
            if isinstance(f.get("evidence"), dict)
            and "error_rate" in f["evidence"]
        ]
        out["latency"] = result.get("data", {}).get("latency", {})
        out["dependencies"] = result.get("data", {}).get("dependencies", {})
    elif agent_type == "events":
        out["reason_counts"] = result.get("data", {}).get("reason_counts", {})
        out["type_counts"] = result.get("data", {}).get("type_counts", {})
        kind_counts: Dict[str, int] = {}
        for f in findings:
            comp = str(f.get("component", "unknown"))
            kind = comp.split("/", 1)[0] if "/" in comp else comp
            kind_counts[kind] = kind_counts.get(kind, 0) + 1
        out["component_kind_counts"] = kind_counts
    # severity-tagged findings rows: the table every tab can render with
    # per-row severity coloring (reference: report/resource tables)
    out["finding_rows"] = [
        {
            "severity": str(f.get("severity", "info")).lower(),
            "icon": SEVERITY_ICONS.get(
                str(f.get("severity", "info")).lower(), "⚪"
            ),
            "component": str(f.get("component", "")),
            "issue": str(f.get("issue", ""))[:120],
        }
        for f in findings
    ]
    return out


def analysis_chart_series(viz: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Renderer-agnostic chart specs for one agent's viz payload
    (reference renders per-type Plotly views, components/visualization.py
    :8-764).  Each spec is ``{"title", "kind": "bar"|"table", "data"}`` —
    ``bar`` data is {label: value}, ``table`` data is a list of row dicts —
    so the Streamlit layer can draw st.bar_chart/st.dataframe without any
    plotly dependency."""
    charts: List[Dict[str, Any]] = []
    sev = viz.get("severity_histogram") or {}
    if sev:
        charts.append({
            "title": "Findings by severity", "kind": "bar",
            "data": {s: sev[s] for s in SEVERITY_DISPLAY_ORDER if s in sev},
            "colors": {
                s: SEVERITY_COLORS[s] for s in SEVERITY_DISPLAY_ORDER if s in sev
            },
        })
    agent = viz.get("agent_type", "")
    if agent == "metrics" and viz.get("utilization"):
        # CPU-vs-memory grouped view (reference: visualization.py:258-330
        # "Resource Usage Issues" splits the two resources into parallel
        # series over the affected pods)
        cpu = {
            row["component"]: row.get("usage_percentage", 0)
            for row in viz["utilization"]
            if str(row.get("resource", "")).lower() == "cpu"
        }
        mem = {
            row["component"]: row.get("usage_percentage", 0)
            for row in viz["utilization"]
            if str(row.get("resource", "")).lower() in ("memory", "mem")
        }
        if cpu or mem:
            charts.append({
                "title": "Resource usage issues (CPU vs memory)",
                "kind": "bar_grouped",
                "series": {"cpu": cpu, "memory": mem},
                "thresholds": [
                    {"value": 80, "label": "warn (80%)"},
                    {"value": 90, "label": "critical (90%)"},
                ],
            })
        # one component can carry several metrics findings (cpu AND memory)
        # — key by component+resource so neither overwrites the other.
        # Thresholds mirror the rule engine's 80%/90% utilization ladder
        # (reference: components/visualization.py utilization charts draw
        # the same warn/critical lines; agents/metrics_agent.py:88-151)
        charts.append({
            "title": "Utilization (% of limit)", "kind": "bar",
            "data": {
                (
                    f"{row['component']} ({row['resource']})"
                    if row.get("resource") else row["component"]
                ): row.get("usage_percentage", 0)
                for row in viz["utilization"]
            },
            "thresholds": [
                {"value": 80, "label": "warn (80%)"},
                {"value": 90, "label": "critical (90%)"},
            ],
        })
    elif agent == "logs":
        if viz.get("pattern_counts"):
            charts.append({
                "title": "Log error classes", "kind": "bar",
                "data": dict(viz["pattern_counts"]),
            })
        if viz.get("component_severity"):
            # component -> severity two-ring sunburst (reference:
            # components/visualization.py:399-447 builds exactly this
            # hierarchy with the severity color map)
            rows = []
            for comp, sevs in sorted(viz["component_severity"].items()):
                rows.append({
                    "id": comp, "parent": "",
                    "value": sum(sevs.values()), "color": "#CCCCCC",
                })
                for s in SEVERITY_DISPLAY_ORDER:
                    if s in sevs:
                        rows.append({
                            "id": f"{comp}/{s}", "parent": comp,
                            "value": sevs[s],
                            "color": SEVERITY_COLORS[s],
                        })
            charts.append({
                "title": "Log issues by component and severity",
                "kind": "sunburst", "data": rows,
            })
    elif agent == "resources" and viz.get("pod_buckets"):
        charts.append({
            "title": "Pod status buckets", "kind": "bar",
            "data": {k: v for k, v in viz["pod_buckets"].items() if v},
        })
    elif agent == "events":
        if viz.get("reason_counts"):
            charts.append({
                "title": "Events by reason", "kind": "bar",
                "data": dict(sorted(
                    viz["reason_counts"].items(),
                    key=lambda kv: -kv[1],
                )[:12]),
            })
        if viz.get("type_counts"):
            charts.append({
                "title": "Events by type", "kind": "bar",
                "data": dict(viz["type_counts"]),
            })
        if viz.get("component_kind_counts"):
            # donut of issues by component KIND (reference:
            # components/visualization.py:833-843, px.pie hole=0.4 over
            # the component-type split)
            charts.append({
                "title": "Event issues by component type", "kind": "pie",
                "hole": 0.4, "data": dict(viz["component_kind_counts"]),
            })
    elif agent == "traces":
        if viz.get("error_rates"):
            charts.append({
                "title": "Error rate per service", "kind": "bar",
                "data": {
                    row["component"]: row["error_rate"]
                    for row in viz["error_rates"]
                },
            })
        lat = viz.get("latency") or {}
        if lat:
            charts.append({
                "title": "p95 latency per service (ms)", "kind": "bar",
                "data": {
                    name: stats.get("p95", 0)
                    for name, stats in lat.items()
                },
            })
        deps = viz.get("dependencies") or {}
        if deps:
            # directed service-dependency edges with per-service issue
            # severity (reference: components/visualization.py:545-646
            # draws the dependency digraph with issue-colored nodes)
            by_comp: Dict[str, List[str]] = {}
            for row in viz.get("finding_rows", []):
                by_comp.setdefault(
                    row["component"].split("/", 1)[-1], []
                ).append(row["severity"])
            max_sev = {c: max_severity(s) for c, s in by_comp.items()}
            charts.append({
                "title": "Service dependencies", "kind": "digraph",
                "data": [
                    {"source": src, "target": dst,
                     "source_severity": max_sev.get(src, "info"),
                     "target_severity": max_sev.get(dst, "info")}
                    for src, dsts in sorted(deps.items())
                    for dst in dsts
                ],
            })
    elif agent == "topology" and viz.get("service_pod_mapping"):
        charts.append({
            "title": "Service → pod mapping", "kind": "table",
            "data": [
                {"service": svc, **(
                    info if isinstance(info, dict) else {"pods": info}
                )}
                for svc, info in viz["service_pod_mapping"].items()
            ],
        })
    # per-row severity-tagged findings table, every agent (reference:
    # resource/report tables with severity coloring)
    if viz.get("finding_rows"):
        charts.append({
            "title": "Findings", "kind": "findings_table",
            "data": viz["finding_rows"],
        })
    return charts


def comprehensive_chart_series(results: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Cross-agent overview specs (reference: visualization.py:38-236
    _render_comprehensive_visualizations — severity distribution over ALL
    agents' findings plus a findings-per-agent bar)."""
    sev_counts: Dict[str, int] = {}
    per_agent: Dict[str, int] = {}
    for agent_type, result in results.items():
        if not isinstance(result, dict) or "findings" not in result:
            continue
        findings = result.get("findings") or []
        if findings:
            per_agent[agent_type] = len(findings)
        for f in findings:
            sev = str(f.get("severity", "info")).lower()
            sev_counts[sev] = sev_counts.get(sev, 0) + 1
    charts: List[Dict[str, Any]] = []
    if sev_counts:
        charts.append({
            "title": "Distribution of findings by severity", "kind": "bar",
            "data": {
                s: sev_counts[s] for s in SEVERITY_DISPLAY_ORDER if s in sev_counts
            },
            "colors": {
                s: SEVERITY_COLORS[s] for s in SEVERITY_DISPLAY_ORDER
                if s in sev_counts
            },
        })
    if per_agent:
        charts.append({
            "title": "Findings by agent", "kind": "bar",
            "data": dict(sorted(per_agent.items())),
        })
    return charts


def correlated_markdown(correlated: Dict[str, Any]) -> str:
    """Correlated-findings tab body: grouped findings per component
    (reference: components/report.py Correlated tab)."""
    groups = correlated.get("groups", {})
    if not groups:
        return "_No correlated findings._"
    lines = [f"**{len(groups)} component(s) with findings**", ""]
    ranked_order = [r["component"] for r in correlated.get("root_causes", [])]
    rest = [c for c in groups if c not in ranked_order]
    for comp in ranked_order + sorted(rest):
        if comp not in groups:
            continue
        findings = groups[comp]
        worst = max_severity(
            str(f.get("severity", "info")) for f in findings
        )
        icon = SEVERITY_ICONS.get(worst.lower(), "⚪")
        lines.append(
            f"- {icon} **{comp}** — {len(findings)} finding(s) from "
            f"{', '.join(sorted({str(f.get('source', '')) for f in findings}))}"
        )
    return "\n".join(lines)


def wizard_stage_markdown(session: Dict[str, Any]) -> str:
    """Progress header for the 4-stage guided wizard (reference:
    components/interactive_session.py:107-114 stages)."""
    stages = ["Select finding", "Hypotheses", "Investigate", "Conclusion"]
    current = int(session.get("stage", 0))
    parts = []
    for i, s in enumerate(stages):
        mark = "✅" if i < current else ("▶️" if i == current else "⚪")
        parts.append(f"{mark} {s}")
    return "  →  ".join(parts)


_VERDICT_ICONS = {"supported": "🟢", "refuted": "🔴", "inconclusive": "🟡"}


def diagnostic_timeline_markdown(executed: List[Dict[str, Any]]) -> str:
    """Timeline of the diagnostic path taken so far — one line per executed
    investigation step with its evidence kind and verdict (reference:
    components/interactive_session.py renders a diagnostic-path timeline
    alongside the wizard)."""
    if not executed:
        return "_No steps executed yet._"
    lines = ["**Diagnostic path**", ""]
    for i, s in enumerate(executed):
        step = s.get("step", {}) or {}
        verdict = s.get("verdict", {}) or {}
        v = str(verdict.get("verdict", "n/a")).lower()
        icon = _VERDICT_ICONS.get(v, "⚪")
        lines.append(
            f"{i + 1}. {icon} {step.get('description', step.get('type', 'step'))}"
            f" — **{verdict.get('verdict', 'n/a')}**"
            f" ({float(verdict.get('confidence', 0) or 0):.0%})"
            f" · {str(verdict.get('reasoning', ''))[:120]}"
        )
    return "\n".join(lines)


def report_markdown(results: Dict[str, Any]) -> str:
    """Full comprehensive-analysis report (reference: components/report.py)."""
    correlated = results.get("correlated", {})
    parts = [
        "# Root Cause Analysis Report",
        "",
        results.get("summary", ""),
        "",
        root_causes_markdown(correlated),
        "",
        "## Per-agent findings",
    ]
    for agent, res in results.items():
        if not isinstance(res, dict) or "findings" not in res:
            continue
        parts.append(f"\n### {agent} ({len(res['findings'])} findings)")
        parts.append(res.get("summary", ""))
        for f in res["findings"][:15]:
            parts.append("")
            parts.append(finding_markdown(f))
    return "\n".join(parts)
