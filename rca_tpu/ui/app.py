"""Streamlit chat UI over the coordinator (thin — all logic lives below).

Surface parity with the reference's UI (reference: app.py:85-210 main flow,
components/chatbot_interface.py chat loop + suggestion buttons,
components/sidebar.py investigation list/create + connection status,
components/interactive_session.py 4-stage wizard, components/report.py,
components/visualization.py).  Run via ``python -m rca_tpu ui`` or
``streamlit run rca_tpu/ui/app.py``.
"""

from __future__ import annotations

from rca_tpu.config import env_str
from rca_tpu.ui.render import (
    analysis_chart_series,
    comprehensive_chart_series,
    analysis_viz_data,
    correlated_markdown,
    diagnostic_timeline_markdown,
    finding_markdown,
    initial_suggestions,
    report_markdown,
    response_markdown,
    root_causes_markdown,
    topology_plot_data,
)


def _build_services():
    """Construct client/coordinator/store once per session."""
    from rca_tpu.coordinator import RCACoordinator
    from rca_tpu.llm import LLMClient, make_provider
    from rca_tpu.obslog import EvidenceLogger, get_logger
    from rca_tpu.store import InvestigationStore

    fixture = env_str("RCA_FIXTURE", "")
    if fixture:
        from rca_tpu.cluster.fixtures import five_service_world
        from rca_tpu.cluster.mock_client import MockClusterClient

        client = MockClusterClient(five_service_world())
    else:
        from rca_tpu.cluster.k8s_client import K8sApiClient

        client = K8sApiClient()
    store = InvestigationStore(root="logs")
    prompt_logger = get_logger()
    llm = LLMClient(
        provider=make_provider(), log_fn=prompt_logger.as_log_fn()
    )
    coord = RCACoordinator(
        client, llm_client=llm,
        evidence_logger=EvidenceLogger(root="logs/evidence"),
    )
    return client, coord, store


def main() -> None:  # pragma: no cover - needs streamlit runtime
    import streamlit as st

    st.set_page_config(page_title="K8s RCA (TPU)", layout="wide")

    if "services" not in st.session_state:
        st.session_state.services = _build_services()
    client, coord, store = st.session_state.services

    # deep link: restore the investigation named in the URL
    # (?investigation=<id>, reference: app.py:88-105)
    url_inv = st.query_params.get("investigation")
    if url_inv and st.session_state.get("investigation_id") != url_inv:
        if store.get_investigation(url_inv):
            st.session_state.investigation_id = url_inv
        else:
            # unknown id: drop it so the URL can't keep advertising an
            # investigation this store doesn't have
            st.warning(f"Investigation {url_inv!r} not found in this store.")
            del st.query_params["investigation"]

    # ---- sidebar: investigations + connection (reference: sidebar.py) ----
    with st.sidebar:
        st.title("Investigations")
        connected = client.is_connected()
        st.caption(
            ("🟢 connected: " + client.get_cluster_info().get("name", ""))
            if connected else "🔴 no cluster — mock/offline mode"
        )
        # gate on the REAL API probe, not is_connected(): the latter is
        # True whenever kubectl is merely installed, which is exactly the
        # degraded state the repair flow exists for
        api_connected = bool(
            client.get_cluster_info().get("connected", connected)
        )
        if st.session_state.pop("repair-ok", False):
            st.success("Kubeconfig updated — reconnected.")
        if not api_connected and hasattr(client, "update_server_url"):
            # endpoint repair for tunneled clusters whose public URL rotated
            # (reference: components/sidebar.py:160-189 ngrok repair flow)
            with st.expander("Connection repair"):
                new_url = st.text_input(
                    "New API server URL", key="repair-url",
                    placeholder="https://<tunnel-host>:443",
                )
                if st.button("Update kubeconfig & reconnect") and new_url:
                    if client.update_server_url(new_url):
                        # flag survives the rerun; success renders above
                        st.session_state["repair-ok"] = True
                        st.rerun()
                    else:
                        errs = client.get_cluster_info().get("errors", [])
                        st.error(
                            "Repair failed: "
                            + (errs[-1]["error"] if errs else "unknown error")
                        )
        # kube-context picker for live clients (reference: sidebar.py
        # namespace/context pickers) — only when more than one exists.
        # Switching is behind an EXPLICIT button: auto-switch-on-change
        # would fire on plain render when no current-context is set, and
        # would retry a failed (blocking) connect on every rerun
        if hasattr(client, "list_contexts"):
            ctxs = client.list_contexts()
            if len(ctxs.get("contexts", [])) > 1:
                chosen = st.selectbox(
                    "Context", ctxs["contexts"],
                    index=(
                        ctxs["contexts"].index(ctxs["current"])
                        if ctxs.get("current") in ctxs["contexts"] else 0
                    ),
                )
                if chosen != ctxs.get("current") and st.button(
                    f"Switch to {chosen}"
                ):
                    if client.switch_context(chosen):
                        st.rerun()
                    else:
                        st.error(f"Could not connect to context {chosen!r}")
        namespaces = client.get_namespaces() or ["default"]
        namespace = st.selectbox("Namespace", namespaces)
        if st.button("New investigation"):
            inv = store.create_investigation(
                "New investigation", namespace=namespace
            )
            st.session_state.investigation_id = inv["id"]
            st.query_params["investigation"] = inv["id"]
            st.session_state.pop("suggestions", None)
            st.rerun()
        for row in store.list_investigations()[:15]:
            if st.button(
                f"{row['title'][:40]} · {row['messages']} msgs",
                key=f"inv-{row['id']}",
            ):
                st.session_state.investigation_id = row["id"]
                st.query_params["investigation"] = row["id"]
                st.rerun()

    inv_id = st.session_state.get("investigation_id")
    if not inv_id:
        inv = store.create_investigation("New investigation",
                                         namespace=namespace)
        inv_id = st.session_state.investigation_id = inv["id"]
        st.query_params["investigation"] = inv_id  # URL mirrors the view
    investigation = store.get_investigation(inv_id) or {}

    st.title("Kubernetes Root Cause Analysis")
    # view navigation with ?view= deep links (reference: app.py:88-105
    # reads ?investigation=<id>&view=chat): a radio nav (not st.tabs,
    # which cannot be preselected programmatically) restores the view
    # named in the URL and writes the user's choice back to it
    views = ["Chat", "Report", "Topology", "Investigate", "Stream"]
    url_view = str(st.query_params.get("view", "")).lower()
    default_idx = next(
        (i for i, v in enumerate(views) if v.lower() == url_view), 0
    )
    view = st.radio(
        "View", views, index=default_idx, horizontal=True,
        label_visibility="collapsed",
    )
    if st.query_params.get("view") != view.lower():
        st.query_params["view"] = view.lower()

    # per-namespace session keys: results/topology/wizard state from one
    # namespace must not leak into another after a sidebar switch
    results_key = f"last_results-{namespace}"
    topology_key = f"topology-{namespace}"
    wizard_key = f"wizard-{namespace}"

    # ---- chat view (reference: chatbot_interface.py) ---------------------
    if view == "Chat":
        for msg in investigation.get("conversation", []):
            with st.chat_message(msg["role"]):
                content = msg["content"]
                if isinstance(content, dict):
                    st.markdown(
                        response_markdown(content.get("response_data", {}))
                    )
                else:
                    st.markdown(str(content))

        suggestions = (
            investigation.get("next_actions")
            or initial_suggestions(namespace)
        )
        cols = st.columns(min(len(suggestions), 5) or 1)
        clicked = None
        for i, (col, sugg) in enumerate(zip(cols, suggestions)):
            with col:
                # index-keyed: suggestion texts can repeat across turns
                if st.button(sugg["text"], key=f"sugg-{i}"):
                    clicked = sugg

        query = st.chat_input("Ask about the cluster…")
        if clicked is not None:
            store.add_message(inv_id, "user", clicked["text"])
            out = coord.process_suggestion(
                clicked.get("action", {}), namespace,
                investigation.get("accumulated_findings"),
            )
            store.add_message(
                inv_id, "assistant", {"response_data": out["response"]}
            )
            store.set_next_actions(inv_id, out["suggestions"])
            store.add_accumulated_findings(inv_id, out["key_findings"])
            st.rerun()
        elif query:
            out = coord.process_user_query(
                query, namespace, investigation.get("accumulated_findings")
            )
            store.record_chat_turn(inv_id, query, out)
            if len(investigation.get("conversation", [])) == 0:
                title = coord.generate_summary_from_query(query, out)
                store.set_title(inv_id, title)
            st.rerun()

    # ---- report view (reference: report.py:57-196 tabbed report) ---------
    elif view == "Report":
        if st.button("Run comprehensive analysis"):
            with st.spinner("Analyzing (TPU fusion)…"):
                record = coord.run_analysis("comprehensive", namespace)
            if record.get("status") != "completed":
                st.error(
                    "Analysis failed: "
                    + str(record.get("error", "unknown error"))
                )
                # don't render a previous run's results under the error
                st.session_state.pop(results_key, None)
            else:
                st.session_state[results_key] = record.get("results", {})
                store.add_agent_findings(inv_id, "comprehensive", record)
        results = st.session_state.get(results_key)
        if results:
            if results.get("degraded"):
                st.warning(results["degraded"]["note"])
            agent_types = [
                a for a in ("resources", "metrics", "logs", "events",
                            "topology", "traces")
                if isinstance(results.get(a), dict)
            ]
            sub = st.tabs(["Root Causes", "Correlated"] + agent_types)
            with sub[0]:
                st.markdown(
                    root_causes_markdown(results.get("correlated", {}))
                )
                # cross-agent overview (reference: visualization.py:38-236)
                for chart in comprehensive_chart_series(results):
                    st.caption(chart["title"])
                    _render_chart(st, chart)
                with st.expander("Full report"):
                    st.markdown(report_markdown(results))
            with sub[1]:
                st.markdown(correlated_markdown(results.get("correlated", {})))
            for tab, agent in zip(sub[2:], agent_types):
                with tab:
                    res = results[agent]
                    st.markdown(res.get("summary", ""))
                    viz = analysis_viz_data(agent, res)
                    for chart in analysis_chart_series(viz):
                        st.caption(chart["title"])
                        _render_chart(st, chart)
                    if agent == "topology" and viz.get("graph"):
                        st.caption("Dependency graph")
                        st.json(topology_plot_data(viz["graph"]))
                    with st.expander("Finding details"):
                        for f in res.get("findings", [])[:12]:
                            st.markdown(finding_markdown(f))

    # ---- topology view (reference: visualization.py) ---------------------
    elif view == "Topology":
        if st.button("Build topology graph"):
            ctx = coord.capture(namespace)
            st.session_state[topology_key] = ctx.graph.to_dict()
        graph = st.session_state.get(topology_key)
        if graph:
            data = topology_plot_data(graph)
            try:
                import plotly.graph_objects as go

                fig = go.Figure()
                for e in data["edges"]:
                    fig.add_trace(
                        go.Scatter(
                            x=[e["x0"], e["x1"]], y=[e["y0"], e["y1"]],
                            mode="lines", line={"width": 1},
                            hoverinfo="none", showlegend=False,
                        )
                    )
                # one trace per node type -> colored legend (reference:
                # components/visualization.py:647-764 node-type colors)
                type_colors = {
                    "service": "#1f77b4", "workload": "#2ca02c",
                    "ingress": "#d62728", "configmap": "#9467bd",
                    "secret": "#8c564b",
                }
                by_type = {}
                for node in data["nodes"]:
                    by_type.setdefault(node["type"] or "other", []).append(node)
                for ntype, members in sorted(by_type.items()):
                    fig.add_trace(
                        go.Scatter(
                            x=[n["x"] for n in members],
                            y=[n["y"] for n in members],
                            text=[n["id"] for n in members],
                            name=ntype,
                            mode="markers+text", textposition="top center",
                            marker={"size": 10,
                                    "color": type_colors.get(ntype, "#7f7f7f")},
                        )
                    )
                st.plotly_chart(fig, use_container_width=True)
            except ImportError:
                st.json(data)

    # ---- guided 4-stage wizard (reference: interactive_session.py) -------
    elif view == "Investigate":
        from rca_tpu.ui.render import wizard_stage_markdown

        wiz = st.session_state.setdefault(wizard_key, {"stage": 0})
        st.markdown(wizard_stage_markdown(wiz))

        if wiz["stage"] == 0:
            results = st.session_state.get(results_key)
            if not results:
                st.info("Run a comprehensive analysis in the Report tab "
                        "first, then pick a finding to investigate.")
            else:
                findings = [
                    f
                    for res in results.values()
                    if isinstance(res, dict)
                    for f in res.get("findings", [])
                ]
                findings.sort(
                    key=lambda f: ["info", "low", "medium", "high",
                                   "critical"].index(
                        str(f.get("severity", "info")).lower()
                    ),
                    reverse=True,
                )
                for i, f in enumerate(findings[:12]):
                    if st.button(
                        f"{f['component']}: {f['issue'][:60]}",
                        key=f"wiz-f{i}",
                    ):
                        wiz.update(
                            {"stage": 1, "finding": f,
                             "component": f["component"]}
                        )
                        st.rerun()

        elif wiz["stage"] == 1:
            if "hypotheses" not in wiz:
                with st.spinner("Generating hypotheses…"):
                    wiz["hypotheses"] = coord.generate_hypotheses(
                        wiz["component"], wiz["finding"], namespace,
                        investigation_id=inv_id,
                    )
            for i, h in enumerate(wiz["hypotheses"]):
                if st.button(
                    f"{h['description'][:70]} ({h['confidence']:.0%})",
                    key=f"wiz-h{i}",
                ):
                    wiz.update(
                        {"stage": 2, "hypothesis": h, "executed": [],
                         "plan": coord.get_investigation_plan(h, namespace)}
                    )
                    st.rerun()

        elif wiz["stage"] == 2:
            plan = wiz["plan"]
            done = len(wiz["executed"])
            for i, step in enumerate(plan["steps"]):
                mark = "✅" if i < done else "⚪"
                st.markdown(f"{mark} {step['description']}")
            if done < len(plan["steps"]):
                if st.button("Execute next step"):
                    with st.spinner("Gathering evidence…"):
                        out = coord.execute_investigation_step(
                            plan["steps"][done], wiz["hypothesis"],
                            namespace, investigation_id=inv_id,
                        )
                    wiz["executed"].append(out)
                    st.rerun()
                if wiz["executed"]:
                    st.markdown(
                        diagnostic_timeline_markdown(wiz["executed"])
                    )
            else:
                if st.button("Accept conclusion"):
                    wiz["stage"] = 3
                    st.rerun()

        elif wiz["stage"] == 3:
            # generate + persist ONCE: streamlit reruns this block on every
            # widget interaction, which would otherwise regenerate the
            # report (an LLM call on non-offline backends) and rewrite the
            # store file each time
            if "report" not in wiz:
                wiz["report"] = coord.generate_root_cause_report(
                    {
                        "component": wiz["component"],
                        "accepted_hypothesis": wiz["hypothesis"],
                        "steps": wiz["executed"],
                        "finding": wiz["finding"],
                    }
                )
                store.add_evidence(inv_id, "root_cause_report", wiz["report"])
            st.markdown(wiz["report"])
            if st.button("Start a new investigation"):
                st.session_state[wizard_key] = {"stage": 0}
                st.rerun()

    # ---- live streaming view (engine/live.py; no reference equivalent) ---
    elif view == "Stream":
        _render_stream_tab(st, client, namespace)


def _render_chart(st, chart) -> None:
    """Draw one renderer-agnostic chart spec (ui.render.
    analysis_chart_series).  Bars with ``thresholds`` draw the 80/90%
    rule-engine lines when plotly is available (reference:
    components/visualization.py utilization charts) and degrade to a plain
    bar chart otherwise; ``findings_table`` rows carry severity icons so
    the table reads severity-colored without a pandas Styler dependency."""
    kind = chart.get("kind")
    if kind == "bar":
        thresholds = chart.get("thresholds") or []
        colors = chart.get("colors") or {}
        if thresholds or colors:
            try:
                import plotly.graph_objects as go

                data = chart["data"]
                bar = go.Bar(x=list(data.keys()), y=list(data.values()))
                if colors:
                    bar.marker = {
                        "color": [
                            colors.get(k, "#888888") for k in data.keys()
                        ]
                    }
                fig = go.Figure(bar)
                for t in thresholds:
                    fig.add_hline(
                        y=t["value"], line_dash="dash",
                        annotation_text=t.get("label", str(t["value"])),
                    )
                st.plotly_chart(fig, use_container_width=True)
                return
            except ImportError:
                if thresholds:
                    st.caption(
                        "thresholds: "
                        + ", ".join(t.get("label", "") for t in thresholds)
                    )
        st.bar_chart(chart["data"])
    elif kind == "findings_table":
        st.dataframe(
            [
                {"": row["icon"], "severity": row["severity"],
                 "component": row["component"], "issue": row["issue"]}
                for row in chart["data"]
            ],
            use_container_width=True,
        )
    elif kind == "pie":
        try:
            import plotly.express as px

            fig = px.pie(
                values=list(chart["data"].values()),
                names=list(chart["data"].keys()),
                hole=chart.get("hole", 0),
            )
            st.plotly_chart(fig, use_container_width=True)
        except ImportError:
            st.bar_chart(chart["data"])
    elif kind == "sunburst":
        try:
            import plotly.graph_objects as go

            rows = chart["data"]
            fig = go.Figure(go.Sunburst(
                ids=[r["id"] for r in rows],
                parents=[r["parent"] for r in rows],
                values=[r["value"] for r in rows],
                marker={"colors": [r["color"] for r in rows]},
                branchvalues="total",
            ))
            st.plotly_chart(fig, use_container_width=True)
        except ImportError:
            # leaf rows only: component/severity -> count
            st.dataframe([r for r in chart["data"] if r["parent"]])
    elif kind == "bar_grouped":
        series = chart.get("series", {})
        try:
            import plotly.graph_objects as go

            fig = go.Figure([
                go.Bar(name=name, x=list(vals.keys()),
                       y=list(vals.values()))
                for name, vals in series.items() if vals
            ])
            for t in chart.get("thresholds") or []:
                fig.add_hline(
                    y=t["value"], line_dash="dash",
                    annotation_text=t.get("label", str(t["value"])),
                )
            fig.update_layout(barmode="group")
            st.plotly_chart(fig, use_container_width=True)
        except ImportError:
            # wide-form rows: one column per series
            keys = sorted({k for vals in series.values() for k in vals})
            st.dataframe([
                {"component": k,
                 **{name: vals.get(k) for name, vals in series.items()}}
                for k in keys
            ])
    elif kind == "digraph":
        sev_icon = {"critical": "🔴", "high": "🟠", "medium": "🟡",
                    "low": "🔵", "info": "⚪"}
        st.dataframe([
            {"from": f"{sev_icon.get(e.get('source_severity'), '⚪')} "
                     f"{e['source']}",
             "to": f"{sev_icon.get(e.get('target_severity'), '⚪')} "
                   f"{e['target']}"}
            for e in chart["data"]
        ], use_container_width=True)
    else:
        st.dataframe(chart["data"])


def _render_stream_tab(st, client, namespace) -> None:
    """Live streaming surface over engine/live.py: each poll diffs the
    cluster against the device-resident features and re-ranks in one fused
    dispatch (no reference equivalent — its closest analog re-ran a full
    analysis per chat turn).

    Auto-poll runs as a scoped ``st.fragment(run_every=...)`` so only this
    tab's body re-executes on the timer — a top-level sleep+rerun loop
    would block every widget in the app for the poll interval and hit the
    cluster API from the sidebar on each cycle.  The checkbox that arms
    the timer lives OUTSIDE the fragment: toggling it must trigger a full
    rerun so the fragment is re-registered with the new ``run_every``
    (from inside, the toggle would only rerun the fragment body and the
    old timer would stay armed)."""
    auto = st.checkbox("Auto-poll every 2 s", value=False, key="stream-auto")
    if hasattr(st, "fragment"):
        st.fragment(run_every="2s" if auto else None)(
            lambda: _stream_tab_body(st, client, namespace)
        )()
    else:
        _stream_tab_body(st, client, namespace)


def _stream_tab_body(st, client, namespace) -> None:
    from rca_tpu.engine import LiveStreamingSession

    sess_key = f"live-stream-{namespace}"
    if st.button("Start / reset stream"):
        # one live session at a time: every stream pins a device-resident
        # feature matrix + edge arrays, so drop any other namespace's
        for key in [k for k in st.session_state
                    if str(k).startswith("live-stream-")]:
            del st.session_state[key]
        st.session_state[sess_key] = {
            "live": LiveStreamingSession(client, namespace, k=8),
            "history": [],
        }
    state = st.session_state.get(sess_key)
    if not state:
        st.info("Start the stream to rank root causes continuously; each "
                "poll uploads only the services whose signals changed.")
        return
    if st.button("Poll now") or st.session_state.get("stream-auto"):
        out = state["live"].poll()
        state["history"].append({
            "tick": out["tick"],
            "latency_ms": round(out["latency_ms"], 1),
            "capture_ms": out["capture_ms"],
            "quiet": out.get("quiet", False),
            "changed_rows": out["changed_rows"],
            "upload_rows": out["upload_rows"],
            "resynced": out["resynced"],
            "top": (out["ranked"][0]["component"]
                    if out["ranked"] else "—"),
        })
        state["history"] = state["history"][-50:]
        st.markdown(
            f"**Top root causes** (tick {out['tick']}, "
            f"{out['changed_rows']} changed, "
            f"{'resynced, ' if out['resynced'] else ''}"
            f"{out['latency_ms']:.0f} ms)"
        )
        st.dataframe(out["ranked"])
    if state["history"]:
        st.caption("Tick history (newest last)")
        st.dataframe(state["history"])


if __name__ == "__main__":  # pragma: no cover
    main()
