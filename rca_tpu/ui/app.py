"""Streamlit chat UI over the coordinator (thin — all logic lives below).

Surface parity with the reference's UI (reference: app.py:85-210 main flow,
components/chatbot_interface.py chat loop + suggestion buttons,
components/sidebar.py investigation list/create + connection status,
components/interactive_session.py 4-stage wizard, components/report.py,
components/visualization.py).  Run via ``python -m rca_tpu ui`` or
``streamlit run rca_tpu/ui/app.py``.
"""

from __future__ import annotations

import os

from rca_tpu.ui.render import (
    initial_suggestions,
    report_markdown,
    response_markdown,
    root_causes_markdown,
    topology_plot_data,
)


def _build_services():
    """Construct client/coordinator/store once per session."""
    from rca_tpu.coordinator import RCACoordinator
    from rca_tpu.llm import LLMClient, make_provider
    from rca_tpu.obslog import EvidenceLogger, get_logger
    from rca_tpu.store import InvestigationStore

    fixture = os.environ.get("RCA_FIXTURE", "")
    if fixture:
        from rca_tpu.cluster.fixtures import five_service_world
        from rca_tpu.cluster.mock_client import MockClusterClient

        client = MockClusterClient(five_service_world())
    else:
        from rca_tpu.cluster.k8s_client import K8sApiClient

        client = K8sApiClient()
    store = InvestigationStore(root="logs")
    prompt_logger = get_logger()
    llm = LLMClient(
        provider=make_provider(), log_fn=prompt_logger.as_log_fn()
    )
    coord = RCACoordinator(
        client, llm_client=llm,
        evidence_logger=EvidenceLogger(root="logs/evidence"),
    )
    return client, coord, store


def main() -> None:  # pragma: no cover - needs streamlit runtime
    import streamlit as st

    st.set_page_config(page_title="K8s RCA (TPU)", layout="wide")

    if "services" not in st.session_state:
        st.session_state.services = _build_services()
    client, coord, store = st.session_state.services

    # ---- sidebar: investigations + connection (reference: sidebar.py) ----
    with st.sidebar:
        st.title("Investigations")
        connected = client.is_connected()
        st.caption(
            ("🟢 connected: " + client.get_cluster_info().get("name", ""))
            if connected else "🔴 no cluster — mock/offline mode"
        )
        namespaces = client.get_namespaces() or ["default"]
        namespace = st.selectbox("Namespace", namespaces)
        if st.button("New investigation"):
            inv = store.create_investigation(
                "New investigation", namespace=namespace
            )
            st.session_state.investigation_id = inv["id"]
            st.session_state.pop("suggestions", None)
            st.rerun()
        for row in store.list_investigations()[:15]:
            if st.button(
                f"{row['title'][:40]} · {row['messages']} msgs",
                key=f"inv-{row['id']}",
            ):
                st.session_state.investigation_id = row["id"]
                st.rerun()

    inv_id = st.session_state.get("investigation_id")
    if not inv_id:
        inv = store.create_investigation("New investigation",
                                         namespace=namespace)
        inv_id = st.session_state.investigation_id = inv["id"]
    investigation = store.get_investigation(inv_id) or {}

    st.title("Kubernetes Root Cause Analysis")
    tab_chat, tab_report, tab_topology = st.tabs(
        ["Chat", "Report", "Topology"]
    )

    # ---- chat tab (reference: chatbot_interface.py) ----------------------
    with tab_chat:
        for msg in investigation.get("conversation", []):
            with st.chat_message(msg["role"]):
                content = msg["content"]
                if isinstance(content, dict):
                    st.markdown(
                        response_markdown(content.get("response_data", {}))
                    )
                else:
                    st.markdown(str(content))

        suggestions = (
            investigation.get("next_actions")
            or initial_suggestions(namespace)
        )
        cols = st.columns(min(len(suggestions), 5) or 1)
        clicked = None
        for col, sugg in zip(cols, suggestions):
            with col:
                if st.button(sugg["text"], key=f"sugg-{sugg['text'][:30]}"):
                    clicked = sugg

        query = st.chat_input("Ask about the cluster…")
        if clicked is not None:
            store.add_message(inv_id, "user", clicked["text"])
            out = coord.process_suggestion(
                clicked.get("action", {}), namespace,
                investigation.get("accumulated_findings"),
            )
            store.add_message(
                inv_id, "assistant", {"response_data": out["response"]}
            )
            store.set_next_actions(inv_id, out["suggestions"])
            store.add_accumulated_findings(inv_id, out["key_findings"])
            st.rerun()
        elif query:
            store.add_message(inv_id, "user", query)
            out = coord.process_user_query(
                query, namespace, investigation.get("accumulated_findings")
            )
            store.add_message(
                inv_id, "assistant",
                {"response_data": out["response_data"],
                 "summary": out["summary"]},
            )
            store.set_next_actions(inv_id, out["suggestions"])
            store.add_accumulated_findings(inv_id, out["key_findings"])
            if len(investigation.get("conversation", [])) == 0:
                title = coord.generate_summary_from_query(query, out)
                store._update(
                    inv_id, lambda inv: inv.__setitem__("title", title)
                )
            st.rerun()

    # ---- report tab (reference: report.py) -------------------------------
    with tab_report:
        if st.button("Run comprehensive analysis"):
            with st.spinner("Analyzing (TPU fusion)…"):
                record = coord.run_analysis("comprehensive", namespace)
            st.session_state.last_results = record.get("results", {})
            store.add_agent_findings(inv_id, "comprehensive", record)
        results = st.session_state.get("last_results")
        if results:
            st.markdown(root_causes_markdown(results.get("correlated", {})))
            with st.expander("Full report"):
                st.markdown(report_markdown(results))

    # ---- topology tab (reference: visualization.py) ----------------------
    with tab_topology:
        if st.button("Build topology graph"):
            ctx = coord.capture(namespace)
            st.session_state.topology = ctx.graph.to_dict()
        graph = st.session_state.get("topology")
        if graph:
            data = topology_plot_data(graph)
            try:
                import plotly.graph_objects as go

                fig = go.Figure()
                for e in data["edges"]:
                    fig.add_trace(
                        go.Scatter(
                            x=[e["x0"], e["x1"]], y=[e["y0"], e["y1"]],
                            mode="lines", line={"width": 1},
                            hoverinfo="none", showlegend=False,
                        )
                    )
                fig.add_trace(
                    go.Scatter(
                        x=[n["x"] for n in data["nodes"]],
                        y=[n["y"] for n in data["nodes"]],
                        text=[n["id"] for n in data["nodes"]],
                        mode="markers+text", textposition="top center",
                    )
                )
                st.plotly_chart(fig, use_container_width=True)
            except ImportError:
                st.json(data)


if __name__ == "__main__":  # pragma: no cover
    main()
