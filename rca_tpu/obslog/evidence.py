"""EvidenceLogger: per-hypothesis / per-step / per-conclusion JSON audit files.

Format parity with the reference (reference: utils/logging_helper.py —
``log_hypothesis`` :32, ``log_investigation_step`` :69, ``log_conclusion``
:107, retrieval by filename scan + description match
``get_evidence_for_hypothesis`` :144).  Filenames keep the reference's
``<ts>_<component-kind>_<slug>_<kind>.json`` shape so archived evidence
remains greppable the same way.
"""

from __future__ import annotations

import datetime
import json
import re
from pathlib import Path
from typing import Any, Dict, List, Optional


def _slug(text: str, max_len: int = 40) -> str:
    s = re.sub(r"[^A-Za-z0-9]+", "-", text).strip("-")
    return s[:max_len] or "item"


class EvidenceLogger:
    def __init__(self, root: str = "logs/evidence"):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _write(self, kind: str, component: str, title: str,
               payload: Dict[str, Any]) -> Path:
        ts = datetime.datetime.now().strftime("%Y%m%d_%H%M%S")
        path = self.root / f"{ts}_{_slug(component)}_{_slug(title)}_{kind}.json"
        payload = {
            "logged_at": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "kind": kind,
            **payload,
        }
        path.write_text(json.dumps(payload, indent=2, default=str))
        return path

    def log_hypothesis(
        self,
        investigation_id: str,
        component: str,
        hypothesis: Dict[str, Any],
        evidence: Any = None,
    ) -> Path:
        return self._write(
            "hypothesis", component,
            str(hypothesis.get("description", "hypothesis")),
            {
                "investigation_id": investigation_id,
                "component": component,
                "hypothesis": hypothesis,
                "evidence": evidence,
            },
        )

    def log_investigation_step(
        self,
        investigation_id: str,
        component: str,
        step: Dict[str, Any],
        result: Any = None,
        verdict: Optional[Dict[str, Any]] = None,
    ) -> Path:
        return self._write(
            "step", component, str(step.get("description", "step")),
            {
                "investigation_id": investigation_id,
                "component": component,
                "step": step,
                "result": result,
                "verdict": verdict,
            },
        )

    def log_conclusion(
        self,
        investigation_id: str,
        component: str,
        conclusion: Dict[str, Any],
    ) -> Path:
        return self._write(
            "conclusion", component,
            str(conclusion.get("root_cause", "conclusion")),
            {
                "investigation_id": investigation_id,
                "component": component,
                "conclusion": conclusion,
            },
        )

    def get_evidence_for_hypothesis(
        self, description: str
    ) -> List[Dict[str, Any]]:
        """Scan logged hypothesis files whose description matches
        (reference: logging_helper.py:144)."""
        out = []
        for path in sorted(self.root.glob("*_hypothesis.json")):
            try:
                rec = json.loads(path.read_text())
            except (json.JSONDecodeError, OSError):
                continue
            desc = str(
                (rec.get("hypothesis") or {}).get("description", "")
            )
            if description.lower() in desc.lower():
                out.append(rec)
        return out
