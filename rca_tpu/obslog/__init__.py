"""Observability: evidence audit files + LLM prompt JSONL log."""

from rca_tpu.obslog.evidence import EvidenceLogger
from rca_tpu.obslog.prompts import PromptLogger, get_logger

__all__ = ["EvidenceLogger", "PromptLogger", "get_logger"]
