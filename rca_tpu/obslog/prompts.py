"""PromptLogger: JSONL audit trail of every LLM interaction.

Record-format parity with the reference (reference: utils/prompt_logger.py
:76-89 — ``{timestamp, investigation_id, user_query, prompt, response,
namespace, accumulated_findings, additional_context{provider, model,
temperature}}``; global singleton ``get_logger`` :129; files at
``logs/prompts/prompt_log_<ts>.jsonl``).
"""

from __future__ import annotations

import datetime
import json
from pathlib import Path
from typing import Any, Dict, List, Optional

from rca_tpu.util.threads import make_lock


class PromptLogger:
    def __init__(self, root: str = "logs/prompts"):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        ts = datetime.datetime.now().strftime("%Y%m%d_%H%M%S")
        self.path = self.root / f"prompt_log_{ts}.jsonl"
        self._lock = make_lock("PromptLogger._lock")

    def log_interaction(
        self,
        prompt: str,
        response: str,
        investigation_id: str = "",
        user_query: str = "",
        namespace: str = "",
        accumulated_findings: Optional[List[str]] = None,
        additional_context: Optional[Dict[str, Any]] = None,
    ) -> None:
        record = {
            "timestamp": datetime.datetime.now(
                datetime.timezone.utc
            ).isoformat(),
            "investigation_id": investigation_id,
            "user_query": user_query,
            "prompt": prompt,
            "response": response,
            "namespace": namespace,
            "accumulated_findings": accumulated_findings or [],
            "additional_context": additional_context or {},
        }
        line = json.dumps(record, default=str)
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")

    def log_system_event(self, event: str, details: Any = None) -> None:
        self.log_interaction(
            prompt="", response="",
            additional_context={"system_event": event, "details": details},
        )

    def read_all(self) -> List[Dict[str, Any]]:
        if not self.path.exists():
            return []
        out = []
        for line in self.path.read_text().splitlines():
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return out

    def as_log_fn(self, investigation_id: str = "", namespace: str = ""):
        """Adapter for :class:`rca_tpu.llm.client.LLMClient`'s ``log_fn``."""

        def log_fn(record: Dict[str, Any]) -> None:
            self.log_interaction(
                prompt=record.get("prompt", ""),
                response=record.get("response", ""),
                investigation_id=investigation_id,
                namespace=namespace,
                additional_context=record.get("additional_context", {}),
            )

        return log_fn


_logger: Optional[PromptLogger] = None
_logger_lock = make_lock("obslog.prompts._logger_lock")


def get_logger(root: str = "logs/prompts") -> PromptLogger:
    """Process-wide singleton (reference: prompt_logger.py:129)."""
    global _logger
    with _logger_lock:
        if _logger is None:
            _logger = PromptLogger(root)
        return _logger
