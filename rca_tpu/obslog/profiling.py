"""Per-stage latency timing + optional jax.profiler device traces.

The reference had no runtime profiler (SURVEY.md §5 tracing row — its only
"tracing" was application-level prompt/evidence logs).  Here every
comprehensive analysis carries a stage-latency breakdown (the north-star
metric is end-to-end graph-inference latency, BASELINE.md), and
``RCA_JAX_PROFILE=<dir>`` wraps the engine stage in a ``jax.profiler``
trace for TensorBoard.
"""

from __future__ import annotations

import contextlib
import os
import time

from rca_tpu.config import env_raw
from typing import Dict, List, Optional


class StageTimer:
    """Collects (stage, seconds) pairs; nestable via context manager."""

    def __init__(self) -> None:
        self.stages: List[Dict[str, float]] = []

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.stages.append(
                {"stage": name, "ms": (time.perf_counter() - t0) * 1e3}
            )

    def report(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for s in self.stages:
            out[s["stage"]] = out.get(s["stage"], 0.0) + round(s["ms"], 3)
        out["total_ms"] = round(sum(s["ms"] for s in self.stages), 3)
        return out


class PhaseStats:
    """Per-phase duration accumulator for repeated loops (streaming ticks):
    ``record("capture", ms)`` per iteration, ``summary()`` at the end.

    The streaming pipeline (engine/streaming.py dispatch/fetch split) uses
    this to publish the capture/dispatch/fetch breakdown the bench records
    (``tick_phases_*``): medians are robust to the tunnel RTT's multi-ms
    jitter, and the p90 keeps the tail visible instead of averaged away."""

    def __init__(self) -> None:
        self._samples: Dict[str, List[float]] = {}

    def record(self, phase: str, ms: float) -> None:
        self._samples.setdefault(phase, []).append(float(ms))

    def record_tick(self, out: Dict[str, object]) -> None:
        """Pull the standard phase keys off one tick/poll record."""
        for key, phase in (("capture_ms", "capture"),
                           ("dispatch_ms", "dispatch"),
                           ("fetch_ms", "fetch")):
            v = out.get(key)
            if v is not None:
                self.record(phase, float(v))  # type: ignore[arg-type]

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.record(name, (time.perf_counter() - t0) * 1e3)

    def summary(self) -> Dict[str, Dict[str, float]]:
        out: Dict[str, Dict[str, float]] = {}
        for name, xs in self._samples.items():
            s = sorted(xs)
            out[name] = {
                "median_ms": round(s[len(s) // 2], 3),
                "p90_ms": round(s[min(len(s) - 1, (len(s) * 9) // 10)], 3),
                "n": len(s),
            }
        return out

    def quantile(self, phase: str, q: float) -> Optional[float]:
        """Nearest-rank quantile of one phase's samples (q in [0, 1]);
        None when the phase has no samples.  The serving scheduler's
        per-tenant queue-time p50/p99 ride this (rca_tpu/serve/metrics.py)
        — same robustness rationale as summary()'s median/p90."""
        xs = self._samples.get(phase)
        if not xs:
            return None
        s = sorted(xs)
        i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
        return round(s[i], 3)

    def count(self, phase: str) -> int:
        return len(self._samples.get(phase, []))

    def phases(self) -> List[str]:
        return list(self._samples)

    def snapshot(self) -> "PhaseStats":
        """A deep-copied twin of the current samples.  PhaseStats itself
        is lock-free by design (per-instance accumulators on one thread);
        holders that share one across threads (ServeMetrics, the gateway
        metrics) take the copy UNDER their own lock and derive quantiles
        off-lock, so an exporter scrape never interleaves with the hot
        path's appends (ISSUE 9 snapshot-consistency fix)."""
        twin = PhaseStats()
        twin._samples = {k: list(v) for k, v in self._samples.items()}
        return twin


@contextlib.contextmanager
def maybe_jax_profile(tag: str):
    """Device trace when RCA_JAX_PROFILE=<dir> is set; no-op otherwise."""
    trace_dir: Optional[str] = env_raw("RCA_JAX_PROFILE")
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(os.path.join(trace_dir, tag)):
        yield
