"""Rule ``thread-discipline``: threads, locks, and sockets are built in
ONE place each.

Raw ``threading.Thread(...)`` / ``threading.Lock()`` (and the rest of
the lock family) construction anywhere in ``rca_tpu/`` outside
``rca_tpu/util/threads.py`` is a finding.  The seam is what makes the
gravelock analyses trustworthy: every thread is named with an explicit
daemon flag (root discovery cannot miss one), every lock carries its
``"Class.attr"`` identity (the static model and the rsan runtime record
agree on names), and flipping ``RCA_RSAN=1`` shims every lock in the
process without touching a call site.

The same discipline covers SOCKETS (ISSUE 9): raw ``socket.socket(...)``
(or ``socket.create_server`` / ``create_connection``) construction
outside ``rca_tpu/util/net.py`` is a finding — the gateway is the
package's only network surface and its listeners are named, reuse-flag
and backlog decisions are made once, and an address-in-use failure is
attributable to its owner.  Library-internal sockets (``http.client``,
the HTTP server's accepted connections) are stdlib code, out of scope
by construction.

Subclassing ``threading.Thread`` stays legal (the subclass calls
``super().__init__(name=..., daemon=...)`` — it IS a named, explicit
thread, and the model roots its ``run``); ``threading.Event`` stays
legal too (an event is a signal, not a mutual-exclusion region — it has
no acquisition order to record).
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

SEAM = "rca_tpu/util/threads.py"
NET_SEAM = "rca_tpu/util/net.py"
#: the rsan shim wraps the raw primitives by definition
EXEMPT = (SEAM, "rca_tpu/analysis/concurrency/rsan.py")
NET_EXEMPT = (NET_SEAM,)

BANNED = {
    "Thread", "Lock", "RLock", "Condition", "Semaphore",
    "BoundedSemaphore",
}

#: socket-constructing callables (module attribute form: socket.<name>)
NET_BANNED = {"socket", "create_server", "create_connection"}

MESSAGE = (
    "raw `threading.{name}(...)` construction outside {seam} — use "
    "make_lock/make_rlock/make_condition/make_thread/spawn so the "
    "primitive is named, rsan-shimmable, and visible to gravelock's "
    "thread-root discovery"
)

NET_MESSAGE = (
    "raw `socket.{name}(...)` construction outside {seam} — use "
    "make_server_socket so the listener is named, reuse/backlog policy "
    "is decided once, and bind failures are attributable"
)


@register
class ThreadDisciplineRule(Rule):
    name = "thread-discipline"
    summary = ("threading.Thread/Lock/... constructed only via "
               "rca_tpu/util/threads.py (named, rsan-shimmable); "
               "socket.socket only via rca_tpu/util/net.py")
    why = ("an anonymous raw thread, lock, or listening socket is "
           "invisible to gravelock's root discovery, the rsan "
           "cross-check, and fd attribution — the analyses are only as "
           "sound as the constructor seams are complete")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/") and relpath not in EXEMPT

    def scan(self, ctx: FileContext) -> List[Finding]:
        # names imported straight from threading/socket count as raw too
        from_threading = set()
        from_socket = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "threading":
                    for alias in node.names:
                        if alias.name in BANNED:
                            from_threading.add(alias.asname or alias.name)
                elif node.module == "socket":
                    for alias in node.names:
                        if alias.name in NET_BANNED:
                            from_socket.add(alias.asname or alias.name)

        net_applies = ctx.relpath not in NET_EXEMPT
        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Call):
                f = node.func
                bad = None
                bad_net = None
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)):
                    if f.value.id == "threading" and f.attr in BANNED:
                        bad = f.attr
                    elif f.value.id == "socket" and f.attr in NET_BANNED:
                        bad_net = f.attr
                elif isinstance(f, ast.Name):
                    if f.id in from_threading:
                        bad = f.id
                    elif f.id in from_socket:
                        bad_net = f.id
                if bad is not None:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        MESSAGE.format(name=bad, seam=SEAM), func=func,
                    ))
                elif bad_net is not None and net_applies:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        NET_MESSAGE.format(name=bad_net, seam=NET_SEAM),
                        func=func,
                    ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
