"""Rule ``thread-discipline``: threads, locks, and sockets are built in
ONE place each.

Raw ``threading.Thread(...)`` / ``threading.Lock()`` (and the rest of
the lock family) construction anywhere in ``rca_tpu/`` outside
``rca_tpu/util/threads.py`` is a finding.  The seam is what makes the
gravelock analyses trustworthy: every thread is named with an explicit
daemon flag (root discovery cannot miss one), every lock carries its
``"Class.attr"`` identity (the static model and the rsan runtime record
agree on names), and flipping ``RCA_RSAN=1`` shims every lock in the
process without touching a call site.

The same discipline covers SOCKETS (ISSUE 9): raw ``socket.socket(...)``
(or ``socket.create_server`` / ``create_connection``) construction
outside ``rca_tpu/util/net.py`` is a finding — the gateway is the
package's only network surface and its listeners are named, reuse-flag
and backlog decisions are made once, and an address-in-use failure is
attributable to its owner.  Library-internal sockets (``http.client``,
the HTTP server's accepted connections) are stdlib code, out of scope
by construction.

The discipline covers long-lived CHILD PROCESSES too (ISSUE 15): raw
``subprocess.Popen(...)``, ``os.fork()``, and any ``multiprocessing``
construction outside ``rca_tpu/util/procs.py`` is a finding — the serve
federation supervises worker processes, and a child spawned outside the
seam has no owner name, no output capture, and no termination ladder,
which is how orphaned workers and pipe-deadlocked chaos runs are born.
One-shot ``subprocess.run``/``check_output`` calls (kubectl, git) stay
legal: they own no life cycle to supervise.

Subclassing ``threading.Thread`` stays legal (the subclass calls
``super().__init__(name=..., daemon=...)`` — it IS a named, explicit
thread, and the model roots its ``run``); ``threading.Event`` stays
legal too (an event is a signal, not a mutual-exclusion region — it has
no acquisition order to record).
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

SEAM = "rca_tpu/util/threads.py"
NET_SEAM = "rca_tpu/util/net.py"
PROC_SEAM = "rca_tpu/util/procs.py"
#: the rsan shim wraps the raw primitives by definition
EXEMPT = (SEAM, "rca_tpu/analysis/concurrency/rsan.py")
NET_EXEMPT = (NET_SEAM,)
PROC_EXEMPT = (PROC_SEAM,)

BANNED = {
    "Thread", "Lock", "RLock", "Condition", "Semaphore",
    "BoundedSemaphore",
}

#: socket-constructing callables (module attribute form: socket.<name>)
NET_BANNED = {"socket", "create_server", "create_connection"}

#: long-lived child-process constructors: subprocess.Popen and os.fork
#: (subprocess.run/call/check_output are one-shots and stay legal);
#: multiprocessing is banned wholesale — ANY attribute call on the
#: module (Process, Pool, fork helpers) builds unsupervised children
PROC_BANNED = {("subprocess", "Popen"), ("os", "fork")}

MESSAGE = (
    "raw `threading.{name}(...)` construction outside {seam} — use "
    "make_lock/make_rlock/make_condition/make_thread/spawn so the "
    "primitive is named, rsan-shimmable, and visible to gravelock's "
    "thread-root discovery"
)

NET_MESSAGE = (
    "raw `socket.{name}(...)` construction outside {seam} — use "
    "make_server_socket so the listener is named, reuse/backlog policy "
    "is decided once, and bind failures are attributable"
)

PROC_MESSAGE = (
    "raw `{name}(...)` child-process construction outside {seam} — use "
    "spawn_worker so the child is named, its output is drained into "
    "bounded buffers, and it dies through the SIGTERM→SIGKILL ladder "
    "(one-shot subprocess.run stays legal)"
)


@register
class ThreadDisciplineRule(Rule):
    name = "thread-discipline"
    summary = ("threading.Thread/Lock/... constructed only via "
               "rca_tpu/util/threads.py (named, rsan-shimmable); "
               "socket.socket only via rca_tpu/util/net.py; "
               "subprocess.Popen/os.fork/multiprocessing only via "
               "rca_tpu/util/procs.py")
    why = ("an anonymous raw thread, lock, listening socket, or child "
           "process is invisible to gravelock's root discovery, the "
           "rsan cross-check, and fd/pid attribution — the analyses "
           "are only as sound as the constructor seams are complete")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/") and relpath not in EXEMPT

    def scan(self, ctx: FileContext) -> List[Finding]:
        # names imported straight from threading/socket/subprocess/os
        # count as raw too
        from_threading = set()
        from_socket = set()
        from_proc = set()
        mp_aliases = {"multiprocessing"}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module == "threading":
                    for alias in node.names:
                        if alias.name in BANNED:
                            from_threading.add(alias.asname or alias.name)
                elif node.module == "socket":
                    for alias in node.names:
                        if alias.name in NET_BANNED:
                            from_socket.add(alias.asname or alias.name)
                elif node.module in ("subprocess", "os"):
                    for alias in node.names:
                        if (node.module, alias.name) in PROC_BANNED:
                            from_proc.add(alias.asname or alias.name)
                elif node.module == "multiprocessing":
                    for alias in node.names:
                        from_proc.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "multiprocessing":
                        mp_aliases.add(alias.asname or alias.name)

        net_applies = ctx.relpath not in NET_EXEMPT
        proc_applies = ctx.relpath not in PROC_EXEMPT
        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Call):
                f = node.func
                bad = None
                bad_net = None
                bad_proc = None
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)):
                    if f.value.id == "threading" and f.attr in BANNED:
                        bad = f.attr
                    elif f.value.id == "socket" and f.attr in NET_BANNED:
                        bad_net = f.attr
                    elif (f.value.id, f.attr) in PROC_BANNED:
                        bad_proc = f"{f.value.id}.{f.attr}"
                    elif f.value.id in mp_aliases:
                        bad_proc = f"{f.value.id}.{f.attr}"
                elif isinstance(f, ast.Name):
                    if f.id in from_threading:
                        bad = f.id
                    elif f.id in from_socket:
                        bad_net = f.id
                    elif f.id in from_proc:
                        bad_proc = f.id
                if bad is not None:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        MESSAGE.format(name=bad, seam=SEAM), func=func,
                    ))
                elif bad_net is not None and net_applies:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        NET_MESSAGE.format(name=bad_net, seam=NET_SEAM),
                        func=func,
                    ))
                elif bad_proc is not None and proc_applies:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        PROC_MESSAGE.format(name=bad_proc, seam=PROC_SEAM),
                        func=func,
                    ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
