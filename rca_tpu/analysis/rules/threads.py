"""Rule ``thread-discipline``: threads and locks are built in ONE place.

Raw ``threading.Thread(...)`` / ``threading.Lock()`` (and the rest of
the lock family) construction anywhere in ``rca_tpu/`` outside
``rca_tpu/util/threads.py`` is a finding.  The seam is what makes the
gravelock analyses trustworthy: every thread is named with an explicit
daemon flag (root discovery cannot miss one), every lock carries its
``"Class.attr"`` identity (the static model and the rsan runtime record
agree on names), and flipping ``RCA_RSAN=1`` shims every lock in the
process without touching a call site.

Subclassing ``threading.Thread`` stays legal (the subclass calls
``super().__init__(name=..., daemon=...)`` — it IS a named, explicit
thread, and the model roots its ``run``); ``threading.Event`` stays
legal too (an event is a signal, not a mutual-exclusion region — it has
no acquisition order to record).
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

SEAM = "rca_tpu/util/threads.py"
#: the rsan shim wraps the raw primitives by definition
EXEMPT = (SEAM, "rca_tpu/analysis/concurrency/rsan.py")

BANNED = {
    "Thread", "Lock", "RLock", "Condition", "Semaphore",
    "BoundedSemaphore",
}

MESSAGE = (
    "raw `threading.{name}(...)` construction outside {seam} — use "
    "make_lock/make_rlock/make_condition/make_thread/spawn so the "
    "primitive is named, rsan-shimmable, and visible to gravelock's "
    "thread-root discovery"
)


@register
class ThreadDisciplineRule(Rule):
    name = "thread-discipline"
    summary = ("threading.Thread/Lock/... constructed only via "
               "rca_tpu/util/threads.py (named, rsan-shimmable)")
    why = ("an anonymous raw thread or lock is invisible to gravelock's "
           "root discovery and to the rsan cross-check — the analyses "
           "are only as sound as the constructor seam is complete")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/") and relpath not in EXEMPT

    def scan(self, ctx: FileContext) -> List[Finding]:
        # names imported straight from threading count as raw too
        from_threading = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ImportFrom) \
                    and node.module == "threading":
                for alias in node.names:
                    if alias.name in BANNED:
                        from_threading.add(alias.asname or alias.name)

        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Call):
                f = node.func
                bad = None
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "threading"
                        and f.attr in BANNED):
                    bad = f.attr
                elif isinstance(f, ast.Name) and f.id in from_threading:
                    bad = f.id
                if bad is not None:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        MESSAGE.format(name=bad, seam=SEAM), func=func,
                    ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
