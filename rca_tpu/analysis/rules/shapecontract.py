"""Rule ``shape-contract``: the staging modules' layout invariants hold
statically (ISSUE 19 — graftspec; ANALYSIS.md §graftspec).

Four checks, all over the declarative tables in
:mod:`rca_tpu.analysis.dataplane.contracts`:

1. **pow2 padding** — every ``*_pad`` assignment in a dataplane staging
   module must be PROVABLY produced by a recognized stable-shape
   producer: ``bucket_for``, ``1 << ...`` / ``2 ** ...``, a pow2
   literal, another ``*_pad`` value, ``max``/``min``/ternary over
   provables, or the ceil-to-multiple alignment idiom
   ``-(-x // d) * d``.  A pad that is merely pow2 *at runtime* but not
   provably so is one refactor away from a per-graph recompile storm.
2. **COO staging discipline** — ``np.zeros/full/empty/ones`` staging
   buffers carry an explicit dtype, and an int32 ``np.full`` fill must
   not be a non-negative literal (a REAL row index: padding must point
   at the dummy row, spelled as ``n_pad - 1`` or a named dummy).
3. **jit signature conformance** — the abstract interpreter walks each
   executable in ``JIT_SIGNATURES`` with its declared input facts and
   proves the returned expressions match the declared output contract.
4. **fetch-surface roles + budget soundness** — a ``device_get`` inside
   a budgeted surface may only move the declared roles (leaf names are
   matched against the FETCH_BUDGETS row), and the contract table
   itself must pass the grid domination proof (roles always fit the
   budget) and cover every resident-fetch allowlist entry.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register
from rca_tpu.analysis.dataplane import absint, contracts

CONTRACTS_REL = "rca_tpu/analysis/dataplane/contracts.py"
_STAGING_FNS = ("zeros", "full", "empty", "ones")
_POW2_PRODUCER_SUFFIXES = ("_pad", "_bucket")
_POW2_PRODUCER_NAMES = ("bucket_for", "next_pow2", "pow2_ceil", "int")


def _callee_name(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _is_pow2_literal(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def pow2_provable(node: ast.expr) -> bool:
    """Can ``node`` be statically proven to come from a sanctioned
    stable-shape producer?  (See the rule docstring for the grammar.)"""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, int) and _is_pow2_literal(node.value)
    if isinstance(node, ast.Name):
        return node.id.endswith(_POW2_PRODUCER_SUFFIXES)
    if isinstance(node, ast.Attribute):
        return node.attr.endswith(_POW2_PRODUCER_SUFFIXES)
    if isinstance(node, ast.IfExp):
        return pow2_provable(node.body) and pow2_provable(node.orelse)
    if isinstance(node, ast.Call):
        name = _callee_name(node.func)
        if name.endswith(_POW2_PRODUCER_SUFFIXES) \
                or name in _POW2_PRODUCER_NAMES:
            if name == "int":
                return bool(node.args) and pow2_provable(node.args[0])
            return True
        if name in ("max", "min"):
            return all(pow2_provable(a) for a in node.args)
        return False
    if isinstance(node, ast.BinOp):
        if isinstance(node.op, ast.LShift):
            return (isinstance(node.left, ast.Constant)
                    and node.left.value in (1, 2))
        if isinstance(node.op, ast.Pow):
            return isinstance(node.left, ast.Constant) \
                and node.left.value == 2
        # the alignment idiom: -(-x // d) * d (ceil to a multiple of d —
        # the batch lanes' data-parallel round-up; x must be provable)
        if isinstance(node.op, ast.Mult):
            left = node.left
            if (isinstance(left, ast.UnaryOp)
                    and isinstance(left.op, ast.USub)
                    and isinstance(left.operand, ast.BinOp)
                    and isinstance(left.operand.op, ast.FloorDiv)
                    and isinstance(left.operand.left, ast.UnaryOp)
                    and isinstance(left.operand.left.op, ast.USub)):
                return pow2_provable(left.operand.left.operand)
        return False
    return False


def _np_call(node: ast.Call, names=_STAGING_FNS) -> bool:
    f = node.func
    return (isinstance(f, ast.Attribute) and f.attr in names
            and isinstance(f.value, ast.Name) and f.value.id == "np")


def _has_dtype(node: ast.Call) -> bool:
    if any(kw.arg == "dtype" for kw in node.keywords):
        return True
    return any(absint.dtype_of_node(a) is not None for a in node.args)


@register
class ShapeContractRule(Rule):
    name = "shape-contract"
    summary = ("staging shapes prove their contracts: pow2 pads, "
               "explicit-dtype COO staging, jit signature conformance, "
               "budgeted fetch roles")
    why = ("a pad that is pow2 only by accident recompiles per graph the "
           "day the producer changes; a drifted executable shape or an "
           "undeclared fetch role ships as a silent latency cliff, not a "
           "test failure")

    def applies_to(self, relpath: str) -> bool:
        return (relpath in contracts.DATAPLANE_MODULES
                or relpath == CONTRACTS_REL
                or any(relpath == p for p, _ in contracts.FETCH_BUDGETS)
                or any(relpath == p for p, _ in contracts.JIT_SIGNATURES))

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        if ctx.relpath == CONTRACTS_REL:
            self._check_tables(ctx, hits)
        if ctx.relpath in contracts.DATAPLANE_MODULES:
            self._check_staging(ctx, hits)
        self._check_signatures(ctx, hits)
        self._check_fetch_roles(ctx, hits)
        return hits

    # -- 4: the contract tables themselves ---------------------------------

    def _check_tables(self, ctx: FileContext, hits: List[Finding]) -> None:
        for v in contracts.budget_violations():
            hits.append(ctx.finding(
                self, 1,
                f"FETCH_BUDGETS unsound: {v['surface']} roles need "
                f"{v['roles_bytes']}B > budget {v['budget_bytes']}B at "
                f"{v['binding']}", func="<module>",
            ))
        for missing in contracts.coverage():
            hits.append(ctx.finding(
                self, 1,
                f"audited fetch surface {missing} has no FETCH_BUDGETS "
                "row — every allowlisted surface declares its byte "
                "budget", func="<module>",
            ))

    # -- 1 + 2: pads and staging constructors ------------------------------

    def _check_staging(self, ctx: FileContext, hits: List[Finding]) -> None:
        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Assign):
                pads = [
                    t for t in node.targets
                    if (isinstance(t, ast.Name) and t.id.endswith("_pad"))
                    or (isinstance(t, ast.Attribute)
                        and t.attr.endswith("_pad"))
                ]
                if pads and not pow2_provable(node.value):
                    hits.append(ctx.finding(
                        self, node.lineno,
                        "`*_pad` not provably a stable-shape producer "
                        "(bucket_for / 1<<ceil-log2 / pow2 literal / "
                        "*_pad / max-min-ternary over those / dp "
                        "alignment) — a pad that is pow2 only by "
                        "accident recompiles per graph when the "
                        "producer drifts", func=func,
                    ))
            if isinstance(node, ast.Call) and _np_call(node):
                if not _has_dtype(node):
                    hits.append(ctx.finding(
                        self, node.lineno,
                        f"np.{_callee_name(node.func)} staging buffer "
                        "without an explicit dtype — host default "
                        "float64 doubles the upload and recompiles the "
                        "executable", func=func,
                    ))
                if (_callee_name(node.func) == "full"
                        and len(node.args) >= 3
                        and absint.dtype_of_node(node.args[2])
                        in ("int32", "int64")
                        and isinstance(node.args[1], ast.Constant)
                        and isinstance(node.args[1].value, int)
                        and node.args[1].value >= 0):
                    hits.append(ctx.finding(
                        self, node.lineno,
                        "int index padding filled with a literal row id "
                        f"({node.args[1].value}) — COO padding must "
                        "point at the dummy row (`n_pad - 1` or a named "
                        "dummy), or padded lanes corrupt a real row",
                        func=func,
                    ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")

    # -- 3: jit signature conformance --------------------------------------

    def _check_signatures(self, ctx: FileContext, hits: List[Finding]) -> None:
        table = {
            fname: spec for (path, fname), spec
            in contracts.JIT_SIGNATURES.items() if path == ctx.relpath
        }
        if not table:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.FunctionDef) \
                    or node.name not in table:
                continue
            spec = table[node.name]
            interp = absint.interpret_function(node, spec["inputs"])
            declared = spec["outputs"]
            for ret in interp.returns:
                actual = ret if isinstance(ret, tuple) else (ret,)
                if len(actual) != len(declared):
                    hits.append(ctx.finding(
                        self, node.lineno,
                        f"{node.name} returns {len(actual)} values, "
                        f"contract declares {len(declared)}",
                        func=node.name,
                    ))
                    continue
                for got, role in zip(actual, declared):
                    msg = absint.fact_conforms(got, role)
                    if msg:
                        hits.append(ctx.finding(
                            self, node.lineno,
                            f"{node.name} breaks its jit signature "
                            f"contract: {msg}", func=node.name,
                        ))

    # -- 4: fetch surfaces move only declared roles ------------------------

    def _check_fetch_roles(self, ctx: FileContext, hits: List[Finding]) -> None:
        budgets = {
            fname: b for (path, fname), b in contracts.FETCH_BUDGETS.items()
            if path == ctx.relpath
        }
        if not budgets:
            return

        def leaf_names(node: ast.expr) -> List[str]:
            """Resolvable leaf names of a device_get argument: tuple
            elements and attribute leaves.  Bare Names stay unresolved
            (aggregates like the attribution `out`)."""
            if isinstance(node, (ast.Tuple, ast.List)):
                out = []
                for e in node.elts:
                    if isinstance(e, ast.Name):
                        out.append(e.id)
                    elif isinstance(e, ast.Attribute):
                        out.append(e.attr)
                return out
            if isinstance(node, ast.Attribute):
                return [node.attr]
            return []

        def check_names(names: List[str], budget, lineno: int,
                        func: str) -> None:
            roles = {r.name for r in budget.roles}
            for n in names:
                rn = contracts.role_name(n)
                if rn not in roles:
                    hits.append(ctx.finding(
                        self, lineno,
                        f"fetch of `{n}` is not a declared FETCH_BUDGETS "
                        f"role for this surface (roles: "
                        f"{', '.join(sorted(roles))}) — audit it into "
                        "the contract or keep it on device", func=func,
                    ))

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if (isinstance(node, ast.Assign) and func in budgets
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "device_get"
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], (ast.Tuple, ast.List))):
                names = [t.id for t in node.targets[0].elts
                         if isinstance(t, ast.Name)]
                check_names(names, budgets[func], node.lineno, func)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "device_get"
                    and func in budgets and node.args):
                check_names(leaf_names(node.args[0]), budgets[func],
                            node.lineno, func)
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
