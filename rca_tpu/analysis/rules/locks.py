"""Rule ``lock-discipline``: every ``acquire()`` has a guaranteed release.

``lock.acquire()`` whose release is not guaranteed by an
immediately-following ``try/finally: lock.release()`` deadlocks every
other thread the first time the guarded body raises; ``with lock:`` is
the only shape a new early return cannot break.  Scope: all of
``rca_tpu/``.

History: through PR 6 this rule also carried an intra-function
"lock-owned attribute mutated outside the lock" check scoped to
``rca_tpu/serve/`` + ``rca_tpu/store/``.  That half is subsumed —
strictly — by gravelock's interprocedural ``race-guard``
(rules/gravelock.py): where the old check saw one method body in two
hand-picked directories, race-guard knows which thread roots reach each
write, which locks are held across call boundaries, and which instances
can alias, so it covers the whole package.  The rule name and CLI
contract are unchanged.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

MESSAGE_ACQUIRE = (
    "`.acquire()` without an immediately-following try/finally release — "
    "use `with lock:` (an exception in the guarded body deadlocks every "
    "other thread)"
)


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    summary = ("acquire() needs try/finally (prefer `with`); guarded-by "
               "races are gravelock's race-guard rule")
    why = ("an unreleased lock deadlocks the serve worker; every thread "
           "that touches the lock afterwards parks forever — a hang, "
           "never a crash")
    # the rsan shim's acquire() IS the passthrough this rule polices —
    # its release is the caller's contract, exactly like the primitive's
    allow = {
        "rca_tpu/analysis/concurrency/rsan.py": {"acquire", "__enter__"},
    }

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/")

    def scan(self, ctx: FileContext) -> List[Finding]:
        # each acquire() is judged exactly once, at its immediate
        # statement: safe only as `x.acquire()` directly followed by
        # `try: ... finally: x.release()` in the same body
        parents: Dict[ast.AST, ast.AST] = {}
        funcs: Dict[ast.AST, str] = {ctx.tree: "<module>"}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    funcs[child] = node.name

        def enclosing_func(node: ast.AST) -> str:
            cur = node
            while cur is not None:
                if cur in funcs:
                    return funcs[cur]
                cur = parents.get(cur)
            return "<module>"

        def body_of(stmt: ast.stmt) -> Optional[List[ast.stmt]]:
            parent = parents.get(stmt)
            for field in ("body", "orelse", "finalbody"):
                body = getattr(parent, field, None)
                if isinstance(body, list) and stmt in body:
                    return body
            return None

        hits: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            stmt = node
            while stmt in parents and not isinstance(stmt, ast.stmt):
                stmt = parents[stmt]
            safe = False
            body = body_of(stmt) if isinstance(stmt, ast.stmt) else None
            if (body is not None and isinstance(stmt, ast.Expr)
                    and stmt.value is node):
                i = body.index(stmt)
                nxt = body[i + 1] if i + 1 < len(body) else None
                safe = isinstance(nxt, ast.Try) and any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "release"
                    for fin in nxt.finalbody
                    for n in ast.walk(fin)
                )
            if not safe:
                hits.append(ctx.finding(
                    self, node.lineno, MESSAGE_ACQUIRE,
                    func=enclosing_func(node),
                ))
        return hits
