"""Rule ``lock-discipline``: shared mutable state mutates under its lock.

Two checks, both scoped to where they are load-bearing:

1. **bare acquire** (everywhere in ``rca_tpu/``): ``lock.acquire()``
   whose release is not guaranteed by an immediately-following
   ``try/finally: lock.release()`` deadlocks the serve worker the first
   time the guarded body raises.  ``with lock:`` is the only shape that
   cannot be broken by a new early return.
2. **unguarded mutation** (``rca_tpu/serve/``, ``rca_tpu/store/``): for
   each class that builds a ``threading.Lock``/``RLock``/``Condition``
   in ``__init__``, every ``self._x`` attribute that is mutated under
   ``with self._lock`` anywhere is *lock-owned*; mutating it outside a
   with-lock block (outside ``__init__``) is a finding.  This is exactly
   the race class the serve queue's weighted-fair accounting and the
   store's read-modify-write records cannot tolerate — a lost update
   there is a stuck request or a vanished investigation note, not a
   crash.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

GUARDED_PREFIXES = ("rca_tpu/serve/", "rca_tpu/store/")

MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort", "reverse",
}

MESSAGE_ACQUIRE = (
    "`.acquire()` without an immediately-following try/finally release — "
    "use `with lock:` (an exception in the guarded body deadlocks every "
    "other thread)"
)
MESSAGE_MUTATION = (
    "mutation of lock-owned attribute `self.{attr}` outside `with "
    "self.{lock}` — racing the locked writers loses updates silently"
)


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attribute names assigned a threading.Lock/RLock/Condition (or a
    lock-ish factory) in __init__."""
    out: Set[str] = set()
    for item in cls.body:
        if not (isinstance(item, ast.FunctionDef)
                and item.name == "__init__"):
            continue
        for node in ast.walk(item):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            f = node.value.func
            is_lock = (
                isinstance(f, ast.Attribute)
                and f.attr in ("Lock", "RLock", "Condition", "Semaphore",
                               "BoundedSemaphore")
            )
            if not is_lock:
                continue
            for t in node.targets:
                if (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    out.add(t.attr)
    return out


def _with_holds_lock(node: ast.With, locks: Set[str]) -> bool:
    """Does this with-statement enter one of the class's locks?  Accepts
    ``with self._lock:``, ``with self._cond:``, and lock-returning helper
    methods like ``with self._locked(id):``."""
    for item in node.items:
        expr = item.context_expr
        for sub in ast.walk(expr):
            if (isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    and (sub.attr in locks or "lock" in sub.attr.lower())):
                return True
    return False


def _mutated_self_attr(node: ast.AST) -> Optional[str]:
    """The self-attribute this statement/expression mutates, if any."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            base = t
            while isinstance(base, ast.Subscript):
                base = base.value
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                return base.attr
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in MUTATING_METHODS:
            base = node.func.value
            while isinstance(base, ast.Subscript):
                base = base.value
            if (isinstance(base, ast.Attribute)
                    and isinstance(base.value, ast.Name)
                    and base.value.id == "self"):
                return base.attr
    return None


@register
class LockDisciplineRule(Rule):
    name = "lock-discipline"
    summary = ("acquire() needs try/finally (prefer `with`); serve/store "
               "lock-owned state mutates only under its lock")
    why = ("an unreleased lock deadlocks the serve worker; an unguarded "
           "mutation races the locked writers and loses updates — a "
           "stuck request or vanished record, never a crash")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/")

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits = self._bare_acquires(ctx)
        if any(ctx.relpath.startswith(p) for p in GUARDED_PREFIXES):
            hits += self._unguarded_mutations(ctx)
        return hits

    # -- 1: bare acquire ----------------------------------------------------
    def _bare_acquires(self, ctx: FileContext) -> List[Finding]:
        # each acquire() is judged exactly once, at its immediate
        # statement: safe only as `x.acquire()` directly followed by
        # `try: ... finally: x.release()` in the same body
        parents: Dict[ast.AST, ast.AST] = {}
        funcs: Dict[ast.AST, str] = {ctx.tree: "<module>"}
        for node in ast.walk(ctx.tree):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    funcs[child] = node.name

        def enclosing_func(node: ast.AST) -> str:
            cur = node
            while cur is not None:
                if cur in funcs:
                    return funcs[cur]
                cur = parents.get(cur)
            return "<module>"

        def body_of(stmt: ast.stmt) -> Optional[List[ast.stmt]]:
            parent = parents.get(stmt)
            for field in ("body", "orelse", "finalbody"):
                body = getattr(parent, field, None)
                if isinstance(body, list) and stmt in body:
                    return body
            return None

        hits: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                continue
            stmt = node
            while stmt in parents and not isinstance(stmt, ast.stmt):
                stmt = parents[stmt]
            safe = False
            body = body_of(stmt) if isinstance(stmt, ast.stmt) else None
            if (body is not None and isinstance(stmt, ast.Expr)
                    and stmt.value is node):
                i = body.index(stmt)
                nxt = body[i + 1] if i + 1 < len(body) else None
                safe = isinstance(nxt, ast.Try) and any(
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "release"
                    for fin in nxt.finalbody
                    for n in ast.walk(fin)
                )
            if not safe:
                hits.append(ctx.finding(
                    self, node.lineno, MESSAGE_ACQUIRE,
                    func=enclosing_func(node),
                ))
        return hits

    # -- 2: unguarded mutation of lock-owned attrs --------------------------
    def _unguarded_mutations(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            locks = _lock_attrs(cls)
            if not locks:
                continue
            # the legacy `lock.acquire()` + `try/finally: release` shape
            # holds the lock for its Try body exactly like `with lock:`
            locked_trys = self._trys_after_acquire(cls, locks)
            owned: Dict[str, str] = {}  # attr -> lock name (for message)

            def entered_lock(node: ast.AST) -> Optional[str]:
                if isinstance(node, ast.With) \
                        and _with_holds_lock(node, locks):
                    return self._with_lock_name(node, locks)
                if node in locked_trys:
                    return locked_trys[node]
                return None

            def collect(node: ast.AST, under: Optional[str]) -> None:
                under = entered_lock(node) or under
                attr = _mutated_self_attr(node)
                if attr is not None and under is not None \
                        and attr not in locks:
                    owned.setdefault(attr, under)
                for child in ast.iter_child_nodes(node):
                    collect(child, under)

            def check(node: ast.AST, under: bool, func: str) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    func = node.name
                    if func == "__init__":
                        return  # construction happens-before sharing
                under = under or entered_lock(node) is not None
                attr = _mutated_self_attr(node)
                if attr in owned and not under:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        MESSAGE_MUTATION.format(attr=attr,
                                                lock=owned[attr]),
                        func=func,
                    ))
                for child in ast.iter_child_nodes(node):
                    check(child, under, func)

            collect(cls, None)
            for item in cls.body:
                check(item, False, "<class>")
        return hits

    @staticmethod
    def _trys_after_acquire(cls: ast.ClassDef,
                            locks: Set[str]) -> Dict[ast.Try, str]:
        """Try statements directly preceded by ``self.<lock>.acquire()``
        in the same body — the region the acquire check blesses."""
        out: Dict[ast.Try, str] = {}
        for node in ast.walk(cls):
            for field in ("body", "orelse", "finalbody"):
                body = getattr(node, field, None)
                if not (isinstance(body, list) and body
                        and isinstance(body[0], ast.stmt)):
                    continue
                for prev, nxt in zip(body, body[1:]):
                    if not isinstance(nxt, ast.Try):
                        continue
                    if not (isinstance(prev, ast.Expr)
                            and isinstance(prev.value, ast.Call)):
                        continue
                    f = prev.value.func
                    if (isinstance(f, ast.Attribute)
                            and f.attr == "acquire"
                            and isinstance(f.value, ast.Attribute)
                            and isinstance(f.value.value, ast.Name)
                            and f.value.value.id == "self"
                            and (f.value.attr in locks
                                 or "lock" in f.value.attr.lower())):
                        out[nxt] = f.value.attr
        return out

    @staticmethod
    def _with_lock_name(node: ast.With, locks: Set[str]) -> str:
        for item in node.items:
            for sub in ast.walk(item.context_expr):
                if (isinstance(sub, ast.Attribute)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id == "self"
                        and (sub.attr in locks
                             or "lock" in sub.attr.lower())):
                    return sub.attr
        return "_lock"
