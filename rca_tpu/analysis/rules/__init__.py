"""Bundled graftlint rules: importing this package registers them all.

One module per rule (plus :mod:`jitscan`, the shared JAX-aware AST
helpers).  A new invariant is a new module here with a ``@register``
class — see ANALYSIS.md for the authoring contract.
"""

from rca_tpu.analysis.rules import dictscan       # noqa: F401
from rca_tpu.analysis.rules import donationguard  # noqa: F401
from rca_tpu.analysis.rules import dtypediscipline  # noqa: F401
from rca_tpu.analysis.rules import env            # noqa: F401
from rca_tpu.analysis.rules import faults         # noqa: F401
from rca_tpu.analysis.rules import gravelock      # noqa: F401
from rca_tpu.analysis.rules import kerneldispatch  # noqa: F401
from rca_tpu.analysis.rules import locks          # noqa: F401
from rca_tpu.analysis.rules import nondet         # noqa: F401
from rca_tpu.analysis.rules import residentfetch  # noqa: F401
from rca_tpu.analysis.rules import retrace        # noqa: F401
from rca_tpu.analysis.rules import rng            # noqa: F401
from rca_tpu.analysis.rules import shapecontract  # noqa: F401
from rca_tpu.analysis.rules import spans          # noqa: F401
from rca_tpu.analysis.rules import threads        # noqa: F401
from rca_tpu.analysis.rules import ticksync       # noqa: F401
from rca_tpu.analysis.rules import tracer         # noqa: F401
