"""Rule ``dtype-discipline``: low-precision arithmetic lives ONLY in the
quantized kernel module (ISSUE 19 — graftspec; ANALYSIS.md §graftspec).

The quantized kernel (bf16 evidence + per-row int8 messages) is legal
precisely because ``engine/quantized.py`` owns the scale bookkeeping and
the rank-parity gate that certifies it.  A ``bfloat16``/``int8`` cast
anywhere else — or an implicit f32↔low-precision promotion inside a jit
body — changes ranking arithmetic with no test failing until a tie
breaks differently on hardware (SCORE_EPS is calibrated per dtype).

Three checks, driven by ``DTYPE_RULES``:

1. an explicit low-precision cast (``.astype(jnp.bfloat16)``, a typed
   constructor, ``jnp.int8(x)``) outside the allowlisted modules;
2. an implicit mixed-precision promotion the abstract interpreter can
   prove inside a jit-reachable function (a binop whose operands' dtype
   facts straddle the low-precision boundary);
3. float64 staging in the dataplane modules (``np.zeros(..., float64)``
   or ``astype(float64)``) — doubles upload bytes, de-optimizes TPU ops.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register
from rca_tpu.analysis.dataplane import absint, contracts
from rca_tpu.analysis.rules.jitscan import jit_functions

_CONSTRUCTORS = frozenset({
    "zeros", "ones", "full", "empty", "asarray", "array", "arange",
    "zeros_like", "ones_like", "full_like", "astype", "view",
})

#: float low-precision is kernel arithmetic wherever it appears; int8 is
#: flagged only in DEVICE contexts (a jnp call, or a dataplane staging
#: module) — host-side int8 metadata tags (graph node/edge types) are a
#: legitimate compact encoding, not ranking arithmetic
_FLOAT_LOW = frozenset({
    "bfloat16", "float16",
    "float8_e4m3fn", "float8_e5m2", "float8_e4m3b11_fnuz",
})


def _is_device_call(node: ast.Call) -> bool:
    """Does this call spell a jnp/jax root anywhere in its callee?"""
    f = node.func
    while isinstance(f, ast.Attribute):
        f = f.value
        if isinstance(f, ast.Name) and f.id in ("jnp", "jax", "lax"):
            return True
    return False


def _dtype_root_is_jnp(node: ast.expr) -> bool:
    while isinstance(node, ast.Attribute):
        node = node.value
    return isinstance(node, ast.Name) and node.id in ("jnp", "jax", "lax")


def _cast_dtype(node: ast.Call):
    """(dtype, dtype_node) this call casts/constructs to, else ('', None)."""
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else "")
    # direct constructor: jnp.bfloat16(x) / np.int8(x)
    direct = absint.dtype_of_node(f)
    if direct is not None and node.args:
        return direct, f
    if name not in _CONSTRUCTORS:
        return "", None
    for kw in node.keywords:
        if kw.arg == "dtype":
            d = absint.dtype_of_node(kw.value)
            if d:
                return d, kw.value
    for a in node.args:
        d = absint.dtype_of_node(a)
        if d is not None:
            return d, a
    return "", None


@register
class DtypeDisciplineRule(Rule):
    name = "dtype-discipline"
    summary = ("bf16/int8 casts only in engine/quantized.py; no implicit "
               "mixed-precision promotion; no float64 staging")
    why = ("the quantized kernel is legal because quantized.py owns the "
           "scale bookkeeping and the rank-parity gate; a low-precision "
           "cast or implicit promotion anywhere else shifts ranking "
           "arithmetic with no test failing until a tie breaks "
           "differently on hardware")

    def applies_to(self, relpath: str) -> bool:
        return (relpath.startswith("rca_tpu/")
                and relpath not in contracts.DTYPE_RULES["low_precision_ok"])

    _TRIGGERS = ("bfloat16", "float16", "float8", "int8", "float64")

    def scan(self, ctx: FileContext) -> List[Finding]:
        # fast path: every finding this rule can emit requires one of
        # the trigger dtype names to be SPELLED in the file (facts in
        # the interpreter originate from dtype references), so a file
        # without them cannot fire
        if not any(t in ctx.source for t in self._TRIGGERS):
            return []
        hits: List[Finding] = []
        f64_scope = ctx.relpath in contracts.DTYPE_RULES[
            "no_float64_staging"]

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Call):
                dt, dt_node = _cast_dtype(node)
                device = (ctx.relpath in contracts.DATAPLANE_MODULES
                          or _is_device_call(node)
                          or (dt_node is not None
                              and _dtype_root_is_jnp(dt_node)))
                if dt in _FLOAT_LOW or (dt == "int8" and device):
                    hits.append(ctx.finding(
                        self, node.lineno,
                        f"low-precision cast to {dt} outside "
                        "engine/quantized.py — quantization lives behind "
                        "the rank-parity-gated kernel, not inline "
                        "(SCORE_EPS is calibrated per dtype)", func=func,
                    ))
                elif dt == "float64" and f64_scope:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        "float64 staging in a dataplane module — doubles "
                        "host->device upload bytes and de-optimizes every "
                        "downstream TPU op; stage float32", func=func,
                    ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")

        # implicit promotions the interpreter can prove inside jit bodies
        for jf in jit_functions(ctx):
            interp = absint.interpret_function(jf.node, {})
            for lineno, a, b in interp.events.promotions:
                hits.append(ctx.finding(
                    self, lineno,
                    f"implicit {a}<->{b} promotion inside a jit body — "
                    "mixed-precision arithmetic outside the quantized "
                    "kernel changes ranking results silently",
                    func=jf.node.name,
                ))
        return hits
