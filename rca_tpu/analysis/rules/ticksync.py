"""Rule ``tick-sync``: no device synchronization outside fetch on the
tick/serve hot paths (absorbs ``tools/lint_tick_sync.py``, PR 2/3).

The streaming tick pipeline and the serving scheduler only deliver their
latency wins because JAX dispatch is async: tick N's device round trip
hides behind tick N+1's host capture, batch N's behind batch N+1's
assembly.  ONE stray ``jax.device_get`` / ``.block_until_ready()`` in a
capture or dispatch path re-serializes the whole pipeline — silently,
with no test failing, just the win gone.  The designated sync points are
``StreamingHostState.fetch`` and ``BatchDispatcher.fetch`` (and only
them): every module on the hot path below lists the functions allowed to
synchronize; a sync spelling anywhere else in those files fails the rule.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

# the banned synchronization spellings (attribute accesses — catches
# jax.device_get, jax.block_until_ready, and x.block_until_ready())
SYNC_ATTRS = ("device_get", "block_until_ready")

# hot-path modules -> function names allowed to synchronize there
TICK_MODULES = {
    "rca_tpu/engine/streaming.py": {"fetch"},
    "rca_tpu/parallel/streaming.py": {"fetch"},
    "rca_tpu/engine/live.py": set(),
    "rca_tpu/features/extract.py": set(),
    "rca_tpu/cluster/snapshot.py": set(),
    # columnar capture (ISSUE 10) is pure host-side table work — it may
    # never synchronize with the device
    "rca_tpu/cluster/columnar.py": set(),
    # live ingest (ISSUE 17): watch-pump capture, the multi-cluster
    # merge, and the ingest runner are host-side capture paths — none
    # may ever touch the device
    "rca_tpu/cluster/live_columnar.py": set(),
    "rca_tpu/cluster/clusterset.py": set(),
    "rca_tpu/serve/ingest.py": set(),
    "rca_tpu/serve/dispatcher.py": {"fetch"},
    "rca_tpu/serve/loop.py": set(),
    "rca_tpu/serve/queue.py": set(),
    "rca_tpu/serve/batcher.py": set(),
    "rca_tpu/serve/client.py": set(),
    "rca_tpu/serve/metrics.py": set(),
    # serve pool (ISSUE 8): replicas and the router sync ONLY through
    # BatchDispatcher.fetch — including the steal path's orphan fetch
    "rca_tpu/serve/replica.py": set(),
    "rca_tpu/serve/pool.py": set(),
    # federation (ISSUE 15): the coordinator routes WIRE frames and the
    # worker agent parks on req.result() — neither may ever touch the
    # device; each worker's own ServeLoop keeps fetch as its one sync
    "rca_tpu/serve/federation.py": set(),
    "rca_tpu/serve/worker.py": set(),
    "rca_tpu/serve/fedwire.py": set(),
    # elasticmesh (ISSUE 16): scale decisions are pure control-plane
    # arithmetic over already-exported telemetry — never a device sync
    "rca_tpu/serve/autoscale.py": set(),
    "rca_tpu/util/procs.py": set(),
    # gateway (ISSUE 9): the wire front door never touches the device —
    # handlers park on req.result() like any in-process submitter, so
    # fetch stays the serve path's ONE sync point even under wire load
    "rca_tpu/gateway/server.py": set(),
    "rca_tpu/gateway/wire.py": set(),
    "rca_tpu/gateway/client.py": set(),
    "rca_tpu/gateway/export.py": set(),
    "rca_tpu/gateway/canary.py": set(),
}

MESSAGE = (
    "`{attr}` in the tick capture/dispatch path — device sync belongs "
    "ONLY in StreamingHostState.fetch (it re-serializes the tick "
    "pipeline; see PERF.md round-6)"
)


@register
class TickSyncRule(Rule):
    name = "tick-sync"
    summary = ("no jax.device_get / block_until_ready outside fetch() on "
               "the tick/serve hot paths")
    why = ("a stray sync re-serializes the dispatch/fetch pipeline: the "
           "device round trip stops hiding behind host capture and every "
           "tick pays the full tunnel RTT again")
    allow = TICK_MODULES

    def applies_to(self, relpath: str) -> bool:
        return relpath in TICK_MODULES

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Attribute) and node.attr in SYNC_ATTRS:
                hits.append(ctx.finding(
                    self, node.lineno, MESSAGE.format(attr=node.attr),
                    func=func,
                ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
