"""Rule ``rng-key-reuse``: a ``jax.random`` key is consumed at most once.

JAX PRNG keys are values, not stateful generators: passing the same key
to two samplers yields **identical** (or pathologically correlated)
draws.  In this codebase that failure mode is vicious precisely because
nothing crashes — a domain-randomized training sweep or a chaos schedule
silently loses entropy and every downstream accuracy number is quietly
wrong.  The rule does a forward pass per function: names bound from
``PRNGKey``/``key``/``split``/``fold_in`` are live keys; any other
``jax.random.*`` call consumes its first argument; a second consumption
without an intervening re-bind is a finding, as is any consumption
inside a loop of a key created outside it (iteration two reuses it).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

# jax.random calls that MAKE keys rather than consuming entropy for output
KEY_MAKERS = ("PRNGKey", "key", "split", "fold_in", "wrap_key_data", "clone")

MESSAGE_REUSE = (
    "PRNG key `{name}` consumed twice without split — identical draws "
    "from both sites (split the key, use a fresh subkey per consumer)"
)
MESSAGE_LOOP = (
    "PRNG key `{name}` (created outside the loop) consumed inside a loop "
    "body — iteration 2 reuses iteration 1's key; fold_in or split per "
    "iteration"
)


def _random_aliases(tree: ast.AST) -> Set[str]:
    """Module-level names bound to ``jax.random`` (``import jax.random as
    jr`` / ``from jax import random [as r]``)."""
    aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                # bare `import jax.random` binds `jax`; the jax.random.<fn>
                # attribute chain is matched structurally, not via aliases
                if a.name == "jax.random" and a.asname:
                    aliases.add(a.asname)
        elif isinstance(node, ast.ImportFrom):
            if node.module == "jax":
                for a in node.names:
                    if a.name == "random":
                        aliases.add(a.asname or "random")
    return aliases


class _KeyTracker(ast.NodeVisitor):
    """Forward pass over ONE function body (statement order)."""

    def __init__(self, rule: Rule, ctx: FileContext, aliases: Set[str],
                 func: str):
        self.rule = rule
        self.ctx = ctx
        self.aliases = aliases
        self.func = func
        self.live: Set[str] = set()        # key names not yet consumed
        self.consumed: Dict[str, int] = {}  # key name -> first-use line
        self.loop_depth = 0
        self.outer_keys: List[Set[str]] = []  # keys live at each loop entry
        self.hits: List[Finding] = []

    # -- helpers ------------------------------------------------------------
    def _is_random_call(self, node: ast.expr) -> bool:
        if not isinstance(node, ast.Call):
            return False
        f = node.func
        if not isinstance(f, ast.Attribute):
            return False
        base = f.value
        if isinstance(base, ast.Name) and base.id in self.aliases:
            return True
        # jax.random.<fn>
        return (isinstance(base, ast.Attribute) and base.attr == "random"
                and isinstance(base.value, ast.Name)
                and base.value.id == "jax")

    def _consume(self, node: ast.Call) -> None:
        """Record the key argument of a jax.random call as consumed."""
        if not node.args:
            return
        arg = node.args[0]
        if not isinstance(arg, ast.Name):
            return
        name = arg.id
        if name in self.consumed:
            self.hits.append(self.ctx.finding(
                self.rule, node.lineno, MESSAGE_REUSE.format(name=name),
                func=self.func,
            ))
            return
        if self.loop_depth and any(
            name in outer for outer in self.outer_keys
        ):
            self.hits.append(self.ctx.finding(
                self.rule, node.lineno, MESSAGE_LOOP.format(name=name),
                func=self.func,
            ))
            return
        if name in self.live:
            self.live.discard(name)
            self.consumed[name] = node.lineno

    def _bind(self, target: ast.expr) -> None:
        """Targets of a key-producing expression become fresh live keys."""
        if isinstance(target, ast.Name):
            self.live.add(target.id)
            self.consumed.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e)
        elif isinstance(target, ast.Starred):
            self._bind(target.value)

    # -- visitors -----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        if self._is_random_call(node):
            fn = node.func.attr
            if fn not in KEY_MAKERS:
                self._consume(node)
            elif fn in ("split", "fold_in"):
                # split/fold_in retire the parent key too: using it again
                # after splitting is the same correlated-draws bug
                self._consume(node)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        self.visit(node.value)
        if isinstance(node.value, ast.Call) \
                and self._is_random_call(node.value) \
                and node.value.func.attr in KEY_MAKERS:
            for t in node.targets:
                self._bind(t)
        # subscripts of a split result: keys[0], keys[1]...
        elif (isinstance(node.value, ast.Subscript)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id in self.live | set(self.consumed)):
            for t in node.targets:
                self._bind(t)

    def _visit_loop(self, node) -> None:
        self.loop_depth += 1
        self.outer_keys.append(set(self.live) | set(self.consumed))
        self.generic_visit(node)
        self.outer_keys.pop()
        self.loop_depth -= 1

    visit_For = _visit_loop
    visit_While = _visit_loop

    def visit_FunctionDef(self, node) -> None:
        # nested defs get their own tracker; don't mix key states
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


@register
class RngKeyReuseRule(Rule):
    name = "rng-key-reuse"
    summary = ("a jax.random key is consumed once — reuse without split "
               "silently correlates draws")
    why = ("two samplers fed the same key return identical values: "
           "domain randomization, chaos schedules, and init all lose "
           "entropy with zero crashes or test failures")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/")

    def scan(self, ctx: FileContext) -> List[Finding]:
        aliases = _random_aliases(ctx.tree)
        hits: List[Finding] = []

        def visit_functions(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    tracker = _KeyTracker(self, ctx, aliases, child.name)
                    for stmt in child.body:
                        tracker.visit(stmt)
                    hits.extend(tracker.hits)
                visit_functions(child)

        visit_functions(ctx.tree)
        return hits
