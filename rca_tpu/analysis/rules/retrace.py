"""Rule ``retrace-hazard``: constructs that silently recompile or
re-upload per call on the tick/serve hot paths.

Three shapes, each a real way the "compile once per bucket, dispatch
async" contract dies without any test failing:

1. **per-call jnp literals** — ``jnp.array([0.0, 1.0])`` built inside a
   hot-path function re-uploads a host constant every call (an H2D
   transfer on the latency path) and, as a fresh Python object, defeats
   jit donation/caching heuristics.  Hoist to a module-level constant.
   Scoped to the same hot-path modules as the tick-sync rule, where a
   per-tick transfer is real money.
2. **data-dependent output shapes under jit** — one-arg ``jnp.where``,
   ``jnp.nonzero``/``flatnonzero``/``argwhere``/``unique`` without
   ``size=`` have value-dependent shapes: under jit they either raise or
   (with shape polymorphism) force a retrace per distinct cardinality.
3. **unhashable static args** — a ``static_argnames`` parameter whose
   default (or a same-module call-site value) is a list/dict/set literal
   raises ``ValueError: unhashable static arguments`` only on the first
   call that actually hits the default — typically in production, not in
   the test that always passes the argument.
"""

from __future__ import annotations

import ast
from typing import List, Set

from rca_tpu.analysis.core import FileContext, Finding, Rule, register
from rca_tpu.analysis.rules.jitscan import is_jnp_call, jit_functions
from rca_tpu.analysis.rules.ticksync import TICK_MODULES

# hot-path modules where a per-call host->device constant upload matters
HOT_MODULES = set(TICK_MODULES)

DATA_DEP = ("nonzero", "flatnonzero", "argwhere", "unique")

MESSAGE_LITERAL = (
    "per-call jnp literal on the hot path — hoist to a module-level "
    "constant (each call re-uploads the constant host->device on the "
    "latency path)"
)
MESSAGE_DATA_DEP = (
    "`jnp.{fn}` without size= inside a jit function — data-dependent "
    "output shape: raises under jit, or retraces per distinct "
    "cardinality"
)
MESSAGE_UNHASHABLE = (
    "static argument `{arg}` takes an unhashable {kind} — jit static "
    "args are cache keys and must hash; use a tuple (raises "
    "`ValueError: unhashable static arguments` on first real call)"
)


def _is_const_literal(node: ast.expr) -> bool:
    """A list/tuple literal of constants (possibly nested)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.List, ast.Tuple)):
        return all(_is_const_literal(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _is_const_literal(node.operand)
    return False


@register
class RetraceHazardRule(Rule):
    name = "retrace-hazard"
    summary = ("no per-call jnp literals on hot paths, no data-dependent "
               "shapes or unhashable static args under jit")
    why = ("each shape retraces or re-uploads silently: the latency "
           "budget assumes one executable per shape bucket and zero "
           "per-tick constant transfers")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/")

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        hits += self._literal_uploads(ctx)
        hits += self._data_dependent_shapes(ctx)
        hits += self._unhashable_statics(ctx)
        return hits

    # -- 1: per-call literals on hot-path modules ---------------------------
    def _literal_uploads(self, ctx: FileContext) -> List[Finding]:
        if ctx.relpath not in HOT_MODULES:
            return []
        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if (func != "<module>"
                    and is_jnp_call(node, {"array", "asarray"})
                    and node.args
                    and isinstance(node.args[0], (ast.List, ast.Tuple))
                    and _is_const_literal(node.args[0])):
                hits.append(ctx.finding(self, node.lineno, MESSAGE_LITERAL,
                                        func=func))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits

    # -- 2: data-dependent shapes under jit ---------------------------------
    def _data_dependent_shapes(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        for fn in jit_functions(ctx):

            def walk(node: ast.AST, func: str) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    func = node.name
                if isinstance(node, ast.Call):
                    kwargs = {kw.arg for kw in node.keywords}
                    if "size" not in kwargs:
                        if is_jnp_call(node, set(DATA_DEP)):
                            hits.append(ctx.finding(
                                self, node.lineno,
                                MESSAGE_DATA_DEP.format(
                                    fn=node.func.attr), func=func,
                            ))
                        elif (is_jnp_call(node, {"where"})
                                and len(node.args) == 1):
                            hits.append(ctx.finding(
                                self, node.lineno,
                                MESSAGE_DATA_DEP.format(fn="where"),
                                func=func,
                            ))
                for child in ast.iter_child_nodes(node):
                    walk(child, func)

            walk(fn.node, fn.node.name)
        return hits

    # -- 3: unhashable static args ------------------------------------------
    def _unhashable_statics(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        static_by_fn: dict = {}
        for fn in jit_functions(ctx):
            node = fn.node
            static_by_fn[node.name] = fn.static
            args = node.args
            ordered = args.posonlyargs + args.args
            # defaults align to the TAIL of the positional params
            for param, default in zip(
                ordered[len(ordered) - len(args.defaults):], args.defaults
            ):
                self._check_static_value(
                    ctx, hits, fn.static, param.arg, default, node.name
                )
            for param, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None:
                    self._check_static_value(
                        ctx, hits, fn.static, param.arg, default, node.name
                    )
        # same-module call sites passing a literal for a static kwarg
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in static_by_fn):
                continue
            static = static_by_fn[node.func.id]
            for kw in node.keywords:
                if kw.arg in static:
                    self._check_static_value(
                        ctx, hits, static, kw.arg, kw.value, "<call>"
                    )
        return hits

    def _check_static_value(self, ctx: FileContext, hits: List[Finding],
                            static: Set[str], arg: str, value: ast.expr,
                            func: str) -> None:
        kind = {ast.List: "list", ast.Dict: "dict", ast.Set: "set",
                ast.ListComp: "list", ast.DictComp: "dict",
                ast.SetComp: "set"}.get(type(value))
        if arg in static and kind is not None:
            hits.append(ctx.finding(
                self, value.lineno,
                MESSAGE_UNHASHABLE.format(arg=arg, kind=kind), func=func,
            ))
