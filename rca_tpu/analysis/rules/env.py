"""Rule ``env-discipline``: raw ``os.environ`` reads live in config.py
only.

Every env knob in ``rca_tpu/`` resolves through the range/choice-validated
accessors in :mod:`rca_tpu.config` (``env_str``/``env_int``/``env_raw``),
so a typo'd value fails loudly in exactly one place instead of silently
selecting a default deep in the engine.  The reference codebase scattered
``os.environ.get`` across modules (reference: app.py:45,
utils/llm_client_improved.py:41-53); this rule keeps that from creeping
back.  Scope is the ``rca_tpu`` package — tools, tests, and bench manage
process environments deliberately and are exempt.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

ALLOWED_FILE = "rca_tpu/config.py"

MESSAGE = (
    "raw os.environ read outside rca_tpu/config.py — route it through a "
    "range-validated accessor (config.env_str / env_int / env_raw)"
)


@register
class EnvDisciplineRule(Rule):
    name = "env-discipline"
    summary = ("os.environ / os.getenv only in rca_tpu/config.py — "
               "everything else uses the validated accessors")
    why = ("a scattered raw read means a typo'd knob silently falls back "
           "to a default: the operator asked for a layout/depth/cache and "
           "quietly did not get it")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/") and relpath != ALLOWED_FILE

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if (isinstance(node, ast.Attribute)
                    and node.attr in ("environ", "getenv")
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "os"):
                hits.append(ctx.finding(self, node.lineno, MESSAGE,
                                        func=func))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
