"""Rules ``race-guard`` + ``lock-order``: the gravelock static analyses.

Both rules consult the whole-package concurrency model
(:mod:`rca_tpu.analysis.concurrency`) built once per lint run from
``ctx.root`` — thread-root discovery, interprocedural held-lock
propagation, guarded-by inference, nested-acquire graph — and emit only
the findings that live in the file currently being scanned, so the
normal graftlint suppression/baseline machinery applies per line.

``race-guard`` subsumes (and retires) the old intra-function
"lock-owned attribute mutated outside the lock" half of
``lock-discipline``: where that check could only see a single method
body in two hand-picked directories, this one knows which threads reach
each write, which locks are held across call boundaries, and which
instances can actually alias — so it covers all of ``rca_tpu/`` without
drowning the build in single-threaded false positives.

``lock-order`` reports cycles in the nested-acquire graph as potential
deadlocks, with the full acquire chains (who held what where, and where
the nested acquisition happened) in the message.
"""

from __future__ import annotations

from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register


def _model(ctx: FileContext):
    from rca_tpu.analysis.concurrency import model_for

    return model_for(ctx.root)


@register
class RaceGuardRule(Rule):
    name = "race-guard"
    summary = ("shared attributes written from >=2 thread roots hold "
               "their inferred guard lock at every write site")
    why = ("a lost update in serve/resilience state is a stuck request "
           "or a silently-wrong counter, never a crash — the race only "
           "fires under production concurrency, where no test is "
           "watching")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/")

    def scan(self, ctx: FileContext) -> List[Finding]:
        from rca_tpu.analysis.concurrency.races import (
            analyze_class_attrs,
            analyze_races,
        )

        model = _model(ctx)
        hits: List[Finding] = []
        for f in analyze_races(model):
            if f.relpath == ctx.relpath:
                hits.append(ctx.finding(self, f.lineno, f.message(),
                                        func=f.func))
        for f in analyze_class_attrs(model):
            if f.relpath == ctx.relpath:
                hits.append(ctx.finding(self, f.lineno, f.message(),
                                        func=f.func))
        return hits


@register
class LockOrderRule(Rule):
    name = "lock-order"
    summary = ("the interprocedural nested-acquire graph stays acyclic "
               "(cycles are potential deadlocks, chains reported)")
    why = ("an A->B order in one call path and B->A in another deadlocks "
           "the first time the two threads interleave — typically in "
           "production under load, holding the serve queue hostage")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/")

    def scan(self, ctx: FileContext) -> List[Finding]:
        from rca_tpu.analysis.concurrency.lockorder import (
            analyze_lock_order,
        )

        model = _model(ctx)
        return [
            ctx.finding(self, f.lineno, f.message(), func=f.func)
            for f in analyze_lock_order(model)
            if f.relpath == ctx.relpath
        ]
