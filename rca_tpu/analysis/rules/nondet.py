"""Rule ``nondet-discipline``: replay-covered modules read no wall clock
and draw no unseeded randomness outside the injectable seams.

The flight recorder (rca_tpu/replay, REPLAY.md) replays a session by
re-serving its recorded cluster responses to the real engine — which is
only sound while every OTHER input is deterministic.  A stray
``time.time()`` feeding a feature, a ``datetime.now()`` in a capture
path, or a module-level ``random.random()`` makes recordings
host-dependent and replay divergence unexplainable.  This rule fences
the replay-covered modules:

- **forbidden**: direct CALLS to ``time.time/monotonic/perf_counter``
  (and ``_ns`` twins), ``datetime.now/utcnow/today``, the ``findings``
  helper ``utcnow_iso``, module-level ``random.<fn>()`` draws, and
  UNSEEDED RNG constructors (``random.Random()`` /
  ``np.random.default_rng()`` with no arguments);
- **seams (legal)**: passing a clock FUNCTION into an injectable
  parameter (``clock: Callable = time.monotonic`` — a reference, not a
  call; every covered module times through ``self._clock()``), and
  SEEDED RNG construction (``random.Random(seed)``,
  ``default_rng(seed)`` — a (seed, call-sequence) pair replays exactly,
  which is the chaos scheduler's whole design).

Ships with the standard per-file allowlist mechanism; the two entries it
carries ARE seams: ``MockClusterClient.get_current_time`` (wall time only
behind its ``frozen_time=False`` escape hatch) and the recorder's
``wall_now`` (header metadata — nothing replayed depends on it).
Baseline ships empty: every pre-existing read was routed through the
seams in the same PR that added the rule.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

#: replay-covered scope: everything a stream or serve recording's
#: determinism argument rests on (prefix match on repo-relative paths)
REPLAY_SCOPE = (
    "rca_tpu/replay/",
    # the gateway (ISSUE 9) fronts the serve plane and its canary mints
    # recordings — wall reads there would make sampled corpora
    # host-dependent, so the whole package times through clock seams
    "rca_tpu/gateway/",
    "rca_tpu/engine/streaming.py",
    "rca_tpu/engine/live.py",
    "rca_tpu/parallel/streaming.py",
    "rca_tpu/serve/",
    "rca_tpu/cluster/watch_pump.py",
    "rca_tpu/cluster/mock_client.py",
    "rca_tpu/cluster/world.py",
    "rca_tpu/cluster/snapshot.py",
    # columnar tables (ISSUE 10): coldiff frames replay the row writes,
    # so the whole module is clock-free by construction
    "rca_tpu/cluster/columnar.py",
    # live ingest (ISSUE 17): the watch-pump columnar adapter and the
    # multi-cluster merge feed recorded sessions — both must stay
    # clock-free so merged corpora replay host-independently
    "rca_tpu/cluster/live_columnar.py",
    "rca_tpu/cluster/clusterset.py",
    "rca_tpu/features/extract.py",
    "rca_tpu/resilience/chaos.py",
    "rca_tpu/resilience/policy.py",
    # tracing (ISSUE 11): spans are embedded in recordings (tick health
    # records) and must replay host-independently — the tracer times
    # through its injectable clock, never the wall
    "rca_tpu/observability/",
)

_TIME_FNS = frozenset({
    "time", "time_ns", "monotonic", "monotonic_ns",
    "perf_counter", "perf_counter_ns",
})
_DATETIME_FNS = frozenset({"now", "utcnow", "today"})

MSG_TIME = (
    "direct {call}() in a replay-covered module — time through the "
    "injectable clock seam (self._clock / the clock= parameter) so "
    "recordings stay host-independent"
)
MSG_RANDOM = (
    "module-level random.{fn}() in a replay-covered module — draw from a "
    "seeded random.Random(seed) instance so a (seed, call-sequence) pair "
    "replays exactly"
)
MSG_UNSEEDED = (
    "unseeded {ctor}() in a replay-covered module — pass a seed so the "
    "stream is replayable"
)
MSG_WALLHELPER = (
    "utcnow_iso() in a replay-covered module — wall time must come from "
    "the client (get_current_time) or an allowlisted metadata seam"
)


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of an attribute chain ('np.random.default_rng')."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


@register
class NondetDisciplineRule(Rule):
    name = "nondet-discipline"
    summary = ("no wall-clock reads or unseeded randomness in "
               "replay-covered modules outside the clock/RNG seams")
    why = ("the flight recorder replays recorded cluster responses "
           "through the real engine; one stray time.time() or global "
           "random draw makes the replay diverge on a different host "
           "with nothing in the log to explain why")

    allow = {
        # frozen_time=False escape hatch — the documented wall seam
        "rca_tpu/cluster/mock_client.py": {"get_current_time"},
        # recording METADATA stamp (header created_at); never replayed
        "rca_tpu/replay/recorder.py": {"wall_now"},
    }

    def applies_to(self, relpath: str) -> bool:
        return any(relpath.startswith(p) for p in REPLAY_SCOPE)

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []

        def check_call(node: ast.Call, func: str) -> None:
            dotted = _dotted(node.func)
            parts = dotted.split(".")
            if len(parts) < 1:
                return
            head, tail = parts[0], parts[-1]
            # time.<fn>() — only as a CALL; a bare reference passed into
            # a clock= parameter is the seam itself and stays legal
            if head == "time" and len(parts) == 2 and tail in _TIME_FNS:
                hits.append(ctx.finding(
                    self, node.lineno, MSG_TIME.format(call=dotted),
                    func=func,
                ))
                return
            # datetime.now()/utcnow()/today() (datetime.datetime.now too)
            if tail in _DATETIME_FNS and "datetime" in parts[:-1]:
                hits.append(ctx.finding(
                    self, node.lineno, MSG_TIME.format(call=dotted),
                    func=func,
                ))
                return
            if dotted == "utcnow_iso":
                hits.append(ctx.finding(
                    self, node.lineno, MSG_WALLHELPER, func=func,
                ))
                return
            # random.<fn>() module-level draws; random.Random(seed) and
            # any seeded constructor stay legal
            if head == "random" and len(parts) == 2:
                if tail == "Random":
                    if not node.args and not node.keywords:
                        hits.append(ctx.finding(
                            self, node.lineno,
                            MSG_UNSEEDED.format(ctor=dotted), func=func,
                        ))
                else:
                    hits.append(ctx.finding(
                        self, node.lineno,
                        MSG_RANDOM.format(fn=tail), func=func,
                    ))
                return
            # np.random.default_rng() / numpy.random.default_rng() unseeded
            if (tail == "default_rng" and "random" in parts[:-1]
                    and not node.args and not node.keywords):
                hits.append(ctx.finding(
                    self, node.lineno, MSG_UNSEEDED.format(ctor=dotted),
                    func=func,
                ))

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Call):
                check_call(node, func)
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
