"""Rule ``tracer-leak``: no host Python branching on traced arrays inside
jit-reachable engine functions.

``if``/``while``/``bool()``/``int()``/``float()`` on a traced array
forces JAX to concretize the tracer.  Best case that raises
``ConcretizationTypeError`` in CI; worst case (when the value happens to
be weakly-typed or the branch sits behind a rarely-taken path) it
silently splits the trace — the function recompiles per branch outcome
and the "compiled once per bucket" latency story quietly dies (the GNN
survey's classic host/device serialization trap, arXiv:2306.14052 §4).
Inside jit, control flow belongs to ``jax.lax.cond`` / ``jnp.where`` /
``jax.lax.while_loop``; host branching is fine on static arguments and
on shapes (``x.shape[0]``), which the taint analysis treats as static.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register
from rca_tpu.analysis.rules.jitscan import (
    involves_traced,
    jit_functions,
    traced_names,
)

MESSAGE_BRANCH = (
    "Python `{kind}` on a traced value inside a jit function — use "
    "jax.lax.cond/jnp.where/jax.lax.while_loop (host branching "
    "concretizes the tracer: ConcretizationTypeError, or a silent "
    "per-branch retrace)"
)
MESSAGE_CAST = (
    "`{kind}()` on a traced value inside a jit function — a host cast "
    "concretizes the tracer and serializes the dispatch"
)


@register
class TracerLeakRule(Rule):
    name = "tracer-leak"
    summary = ("no Python if/while/bool/int/float on traced arrays inside "
               "jit-reachable functions")
    why = ("a concretized tracer either crashes "
           "(ConcretizationTypeError) or silently re-traces per branch "
           "outcome, destroying the compile-once-per-bucket guarantee "
           "the tick latency budget is built on")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/")

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        for fn in jit_functions(ctx):
            traced = traced_names(fn)

            def walk(node: ast.AST, func: str) -> None:
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    func = node.name
                if isinstance(node, (ast.If, ast.While)):
                    if involves_traced(node.test, traced):
                        kind = "if" if isinstance(node, ast.If) else "while"
                        hits.append(ctx.finding(
                            self, node.lineno,
                            MESSAGE_BRANCH.format(kind=kind), func=func,
                        ))
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id in ("bool", "int", "float")
                        and node.args
                        and involves_traced(node.args[0], traced)):
                    hits.append(ctx.finding(
                        self, node.lineno,
                        MESSAGE_CAST.format(kind=node.func.id), func=func,
                    ))
                for child in ast.iter_child_nodes(node):
                    walk(child, func)

            walk(fn.node, fn.node.name)
        return hits
