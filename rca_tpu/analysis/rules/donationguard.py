"""Rule ``donation-guard``: a buffer passed through a ``donate_argnums``
position is DEAD — reading it afterwards is a finding (ISSUE 19 —
graftspec; ANALYSIS.md §graftspec).

The resident sessions' whole update path rides on donation: the delta
executables take the resident feature buffer at argument 0 with
``donate_argnums=(0,)`` so XLA scatters in place.  The calling
convention that makes this safe is *rebind-in-the-same-statement*::

    self._features, vals, idx, n_bad = _flush_propagate_ranked(
        self._features, ...)

Anything else leaves a dangling reference to a deleted buffer: the read
crashes on real hardware (`DELETED` array) but often *works on CPU*
where donation is a no-op — the classic lands-in-review,
explodes-on-TPU bug this rule exists to catch before the TPU round.

Detection: module-local jit functions declaring ``donate_argnums`` (the
decorator and ``jax.jit(fn, donate_argnums=...)`` call forms), plus the
``DONATED_ATTR_CALLABLES`` contract table for runtime-built jit
wrappers bound to attributes (``self._fn``).  At every call site, the
expression at a donated position must be rebound by the same statement;
otherwise any later read of that expression in the function (before a
rebind) is flagged.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from rca_tpu.analysis.core import FileContext, Finding, Rule, register
from rca_tpu.analysis.dataplane.contracts import DONATED_ATTR_CALLABLES


def _donate_argnums(call: ast.Call) -> Tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            out = []
            node = kw.value
            elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
                else [node]
            for e in elts:
                if isinstance(e, ast.Constant) and isinstance(e.value, int):
                    out.append(e.value)
            return tuple(out)
    return ()


def _is_jit_callee(node: ast.expr) -> bool:
    if isinstance(node, ast.Name) and node.id == "jit":
        return True
    return (isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name) and node.value.id == "jax")


def donated_functions(tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """Function name -> donated positions, for both spellings: the
    ``@partial(jax.jit, donate_argnums=...)`` decorator and a module-
    level ``jax.jit(fn, donate_argnums=...)`` wrap of a named function."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    f = dec.func
                    is_partial = (
                        (isinstance(f, ast.Name) and f.id == "partial")
                        or (isinstance(f, ast.Attribute)
                            and f.attr == "partial")
                    )
                    wraps_jit = (
                        (is_partial and dec.args
                         and _is_jit_callee(dec.args[0]))
                        or _is_jit_callee(f)
                    )
                    if wraps_jit:
                        nums = _donate_argnums(dec)
                        if nums:
                            out[node.name] = nums
        elif isinstance(node, ast.Call) and _is_jit_callee(node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                nums = _donate_argnums(node)
                if nums:
                    out[node.args[0].id] = nums
        elif isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and _is_jit_callee(node.value.func):
            # bound wrap: step = jax.jit(raw, donate_argnums=(0,)) —
            # call sites go through the BOUND name, so track that too
            nums = _donate_argnums(node.value)
            if nums:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out[t.id] = nums
    return out


def _stmts_in_order(body: List[ast.stmt]) -> Iterator[ast.stmt]:
    """Statements in source order, descending into compound blocks but
    NOT into nested function/class definitions (their frames are fresh)."""
    for stmt in body:
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        for field in ("body", "orelse", "finalbody"):
            inner = getattr(stmt, field, None)
            if inner:
                yield from _stmts_in_order(inner)
        for handler in getattr(stmt, "handlers", []) or []:
            yield from _stmts_in_order(handler.body)


def _expr_text(node: ast.expr) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - defensive
        return ""


def _assign_target_texts(stmt: ast.stmt) -> Set[str]:
    texts: Set[str] = set()
    targets: List[ast.expr] = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        if isinstance(t, (ast.Tuple, ast.List)):
            texts |= {_expr_text(e) for e in t.elts}
        else:
            texts.add(_expr_text(t))
    return texts


def _own_exprs(stmt: ast.stmt) -> List[ast.AST]:
    """The expressions evaluated by THIS statement alone — compound
    statements contribute only their header (test / iter / context
    managers), because their body statements are yielded separately by
    :func:`_stmts_in_order` (walking the whole subtree here would see
    every inner statement twice)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        return [item.context_expr for item in stmt.items]
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef, ast.Try)):
        return []
    if isinstance(stmt, ast.Assign):
        return [stmt.value]
    if isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        return [stmt.value] if stmt.value is not None else []
    return [stmt]  # a simple statement: walk it whole


def _reads_in(stmt: ast.stmt, text: str) -> int:
    """First lineno where ``text`` is read in the statement's own
    expressions, excluding assignment-target occurrences; 0 if none."""
    for root in _own_exprs(stmt):
        for n in ast.walk(root):
            if isinstance(n, (ast.Name, ast.Attribute)) \
                    and _expr_text(n) == text:
                return getattr(n, "lineno", getattr(stmt, "lineno", 0))
    return 0


MESSAGE = (
    "`{buf}` was donated to `{callee}` at line {line} and never rebound "
    "— this read touches a DELETED device buffer (works on CPU where "
    "donation is a no-op, crashes on TPU); rebind in the donating "
    "statement: `{buf}, ... = {callee}({buf}, ...)`"
)


@register
class DonationGuardRule(Rule):
    name = "donation-guard"
    summary = ("no read of a buffer after it passed a donate_argnums "
               "position without a same-statement rebind")
    why = ("donation is what makes the resident delta path O(changed "
           "rows); a read of the donated buffer is a use-after-free that "
           "CPU runs hide (donation is a no-op there) and TPU turns into "
           "a DELETED-array crash")

    def applies_to(self, relpath: str) -> bool:
        return relpath.startswith("rca_tpu/") or relpath.endswith(".py")

    def scan(self, ctx: FileContext) -> List[Finding]:
        # fast path: no donation spelled anywhere and no contract-table
        # entry for this file — nothing to track
        if "donate_argnums" not in ctx.source and not any(
            path == ctx.relpath for path, _ in DONATED_ATTR_CALLABLES
        ):
            return []
        donated = donated_functions(ctx.tree)
        donated_attrs = {
            attr: nums for (path, attr), nums
            in DONATED_ATTR_CALLABLES.items() if path == ctx.relpath
        }
        if not donated and not donated_attrs:
            return []
        hits: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(ctx, node, donated, donated_attrs, hits)
        return hits

    def _donated_positions(self, call: ast.Call,
                           donated: Dict[str, Tuple[int, ...]],
                           donated_attrs: Dict[str, Tuple[int, ...]]):
        f = call.func
        if isinstance(f, ast.Name) and f.id in donated:
            return f.id, donated[f.id]
        text = _expr_text(f)
        if text in donated_attrs:
            return text, donated_attrs[text]
        return None, ()

    def _scan_function(self, ctx: FileContext, fn, donated, donated_attrs,
                       hits: List[Finding]) -> None:
        stmts = list(_stmts_in_order(fn.body))
        #: expr text -> (donation lineno, callee) for currently-dead bufs
        dead: Dict[str, Tuple[int, str]] = {}
        for stmt in stmts:
            # reads of dead buffers come first: the donating statement's
            # own call arguments legitimately read the buffer
            for text, (line, callee) in list(dead.items()):
                read_line = _reads_in(stmt, text)
                if read_line:
                    hits.append(ctx.finding(
                        self, read_line,
                        MESSAGE.format(buf=text, callee=callee, line=line),
                        func=fn.name,
                    ))
                    del dead[text]  # one report per donation
            rebinds = _assign_target_texts(stmt)
            for text in list(dead):
                if text in rebinds:
                    del dead[text]
            for call in [
                n for root in _own_exprs(stmt)
                for n in ast.walk(root) if isinstance(n, ast.Call)
            ]:
                callee, nums = self._donated_positions(
                    call, donated, donated_attrs)
                if not callee:
                    continue
                for pos in nums:
                    if pos >= len(call.args):
                        continue
                    arg = call.args[pos]
                    if not isinstance(arg, (ast.Name, ast.Attribute)):
                        continue  # a temporary: nothing outlives the call
                    text = _expr_text(arg)
                    if text in rebinds:
                        continue  # the sanctioned same-statement rebind
                    dead[text] = (call.lineno, callee)
