"""Rule ``kernel-dispatch``: the registry is the ONLY dispatch seam.

ISSUE 12 made :mod:`rca_tpu.engine.registry` the single place a
propagation surface learns which combine kernel a padded shape engages
(``engaged_kernel``).  The regression this rule prevents is the one the
refactor removed: a call surface re-deriving the kernel choice locally —
calling the Pallas/XLA evidence bodies directly, or the legacy
process-level autotune shims — so that a new kernel (segscan, quantized;
ROADMAP item 4) or a changed eligibility gate lands in N-1 of N
surfaces and the cross-path bit-parity contract silently breaks.

Flagged inside ``rca_tpu/``: calls to the kernel bodies
(``noisy_or_pair_pallas`` / ``noisy_or_pair_xla``), the shared traced
core (``propagate_core``), and the legacy shims (``noisyor_autotune`` /
``noisyor_path``) anywhere outside the seam files — the registry itself,
the kernel definitions, the propagation core, and the ONE traced
evidence branch (``runner.propagate_auto``).  bench.py and tests stay
out of scope (measurement code times the bodies on purpose)."""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

#: call targets that constitute bypassing the registry seam (ISSUE 13
#: satellite: the quantized and doubling kernel bodies, and segscan's
#: layout gate, are seam-guarded exactly like the Pallas/XLA pair —
#: bypassing the seam in a NEW module is as unlandable as in an old one)
TARGETS = frozenset({
    "noisy_or_pair_pallas",
    "noisy_or_pair_xla",
    "propagate_core",
    "noisyor_autotune",
    "noisyor_path",
    # segscan engagement + assembly (registry-resident since ISSUE 13)
    "seg_layouts_for",
    "build_seg_layouts",
    # quantized kernel bodies (engine/quantized.py)
    "noisy_or_pair_bf16",
    "quant_up_step",
    "quant_imp_step",
    # doubling kernel bodies + layout gate (engine/doubling.py)
    "doubling_up",
    "doubling_down",
    "doubling_layouts_for",
    "build_doubling",
    # causelens attribution executables (engine/attribution.py, ISSUE
    # 14): the counterfactual sweep + gradient saliency re-propagate
    # through the registry's `attribution` variant — callers go through
    # compute_attribution / EngineResult.attribution(), never the
    # executables directly
    "attribution_sweep",
    "attribution_saliency",
})

#: files that ARE the seam (definitions + the registry's own timing/cost)
ALLOWED_FILES = frozenset({
    "rca_tpu/engine/registry.py",
    "rca_tpu/engine/pallas_kernels.py",
    "rca_tpu/engine/propagate.py",
    "rca_tpu/engine/segscan.py",
    "rca_tpu/engine/quantized.py",
    "rca_tpu/engine/doubling.py",
})

MESSAGE = (
    "{name}() called outside the kernel-dispatch seam — propagation "
    "surfaces must ask rca_tpu/engine/registry.py (engaged_kernel) "
    "which kernel a shape engages; calling the kernel bodies or the "
    "legacy autotune shims directly lets kernel choices drift between "
    "call surfaces (ISSUE 12)"
)


@register
class KernelDispatchRule(Rule):
    name = "kernel-dispatch"
    summary = ("propagation entry points outside engine/registry.py may "
               "not call the Pallas/XLA kernel bodies or the legacy "
               "autotune shims — the registry is the only dispatch seam")
    why = ("a kernel choice re-derived locally at one call surface "
           "diverges from the registry's per-shape row the moment a new "
           "kernel or eligibility gate lands, breaking the cross-path "
           "bit-parity contract the serve/streaming/resident surfaces "
           "rely on — the exact drift ISSUE 12's refactor removed")
    # the ONE traced evidence branch every executable shares (the
    # per-kernel dispatch lives there by design — runner.py docstring),
    # the one per-graph layout-assembly step beside it (kernel_plan asks
    # the registry FIRST, then builds the winner's layouts), and the
    # training loss's differentiable forward (it fits weights THROUGH
    # the core; it never serves a request, so no kernel choice can
    # drift from it)
    allow = {
        "rca_tpu/engine/runner.py": {"propagate_auto", "kernel_plan"},
        "rca_tpu/engine/train.py": {"_forward"},
        # the causelens host wrapper (ISSUE 14): asks the registry's
        # `attribution` variant first, then invokes the attribution
        # executables it owns — the one function allowed to call them
        "rca_tpu/engine/attribution.py": {"compute_attribution"},
    }

    def applies_to(self, relpath: str) -> bool:
        return (
            relpath.startswith("rca_tpu/")
            and relpath not in ALLOWED_FILES
        )

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        func_stack: List[str] = []

        def visit(node: ast.AST) -> None:
            is_func = isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef)
            )
            if is_func:
                func_stack.append(node.name)
            if isinstance(node, ast.Call):
                target = node.func
                name = None
                if isinstance(target, ast.Name):
                    name = target.id
                elif isinstance(target, ast.Attribute):
                    name = target.attr
                if name in TARGETS:
                    hits.append(ctx.finding(
                        self, node.lineno, MESSAGE.format(name=name),
                        func=func_stack[-1] if func_stack else "<module>",
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child)
            if is_func:
                func_stack.pop()

        visit(ctx.tree)
        return hits
