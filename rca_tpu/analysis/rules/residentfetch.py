"""Rule ``resident-fetch``: the analyze/tick/serve hot paths may fetch
only top-k-sized results (ISSUE 6 — the tick-sync rule family extended to
the resident-session era).

The device-resident refactor's whole win is that a request moves O(changed
rows) up and O(top-k) down: every designated fetch surface moves the
[4, k] diagnostic gather + the top-k pair + a scalar, and the full
[4, n_pad] stack stays parked on device behind ``EngineResult.
full_diagnostics``'s deferred bulk fetch.  One stray ``jax.device_get``
of a full-width array on an analyze/tick/serve path silently restores the
~100× host sync floor (BENCH_r02–r05) with no test failing — the latency
budget just evaporates.

Enforcement: in the hot-path modules below, a sync spelling
(``device_get`` / ``block_until_ready``) is legal ONLY inside the listed
functions — the audited top-k fetch surfaces plus the explicitly
documented bulk seams (the lazy diagnostics fetch; bulk staging paths
like ``set_all``/resync upload, which SEND rather than fetch, never sync
and so never appear here).  Everything else in those files fails the
rule.  The baseline ships empty: new fetch surfaces must be audited into
the allowlist, not baselined.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

SYNC_ATTRS = ("device_get", "block_until_ready")

# hot-path modules -> functions allowed to synchronize there.  Two kinds,
# both audited: top-k fetch surfaces (move O(k) bytes by construction)
# and the one deferred bulk seam (EngineResult.full_diagnostics — lazy,
# consumer-triggered, off the latency path by definition).
FETCH_SURFACES = {
    # one-shot + resident analyze path
    "rca_tpu/engine/runner.py": {
        "timed_fetch",        # top-k: fetches diag/vals/idx/n_bad only
        "analyze_batch",      # top-k: per-lane diag/vals/idx/n_bad
        "full_diagnostics",   # BULK, deferred: the documented lazy seam
    },
    "rca_tpu/engine/resident.py": {"_fetch_topk"},
    # causelens (ISSUE 14): compute_attribution fetches the [5,k] diag,
    # the [m,k] counterfactual deltas, and the [k,P] path arrays — all
    # top-k/top-m-sized by construction; the masked-score matrix and
    # the full saliency stay on device
    "rca_tpu/engine/attribution.py": {"compute_attribution"},
    "rca_tpu/engine/sharded_runner.py": {"analyze_batch"},
    # streaming tick + serve paths (tick-sync's fetch-only contract,
    # restated here with the top-k-size obligation)
    "rca_tpu/engine/streaming.py": {"fetch"},
    "rca_tpu/parallel/streaming.py": {"fetch"},
    # sharded resident session (ISSUE 8): same audited top-k fetch
    # surface as the dense session's _fetch_topk
    "rca_tpu/parallel/sharded.py": {"_fetch_topk"},
    "rca_tpu/engine/live.py": set(),
    "rca_tpu/serve/dispatcher.py": {"fetch"},
    "rca_tpu/serve/loop.py": set(),
    "rca_tpu/serve/client.py": set(),
    # serve pool (ISSUE 8): replicas/router never sync directly — the
    # steal path completes an orphan via BatchDispatcher.fetch
    "rca_tpu/serve/replica.py": set(),
    "rca_tpu/serve/pool.py": set(),
}

MESSAGE = (
    "`{attr}` outside an audited fetch surface on the analyze/tick/serve "
    "hot path — fetches there may move only top-k-sized results; park "
    "full arrays on device behind EngineResult.full_diagnostics (a stray "
    "bulk fetch restores the ~100x host sync floor; see PERF.md round-7)"
)


@register
class ResidentFetchRule(Rule):
    name = "resident-fetch"
    summary = ("hot-path device fetches are top-k-sized and live only in "
               "audited fetch surfaces")
    why = ("the resident-session refactor moves O(changed rows) up and "
           "O(top-k) down per request; one stray full-array device_get "
           "silently re-pays the ~100x host/staging/fetch floor the "
           "refactor erased")
    allow = FETCH_SURFACES

    def applies_to(self, relpath: str) -> bool:
        return relpath in FETCH_SURFACES

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Attribute) and node.attr in SYNC_ATTRS:
                hits.append(ctx.finding(
                    self, node.lineno, MESSAGE.format(attr=node.attr),
                    func=func,
                ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
