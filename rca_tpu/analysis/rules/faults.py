"""Rule ``swallowed-faults``: no silently-discarded exceptions outside the
resilience layer (absorbs ``tools/lint_swallowed_faults.py``, PR 1).

``except Exception: pass`` / bare ``except: pass`` anywhere outside
``rca_tpu/resilience/`` fails the rule.  A swallowed fault must go through
a policy — :func:`rca_tpu.resilience.policy.suppressed` records it into
the bounded fault log the streaming health records drain, so "it failed
and nobody ever knew" cannot happen again.  Narrow handlers
(``except OSError: pass``) stay allowed: catching a SPECIFIC exception is
a decision; catching everything and discarding it is a bug farm.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

ALLOWED_PREFIX = "rca_tpu/resilience/"

MESSAGE = (
    "swallowed fault — replace `except Exception: pass` with "
    "rca_tpu.resilience.policy.suppressed(op)"
)


def is_swallow(handler: ast.ExceptHandler) -> bool:
    """True for ``except Exception:``/bare ``except:`` whose body is only
    ``pass`` (docstring-style constants also count as doing nothing)."""
    if handler.type is not None:
        # only the catch-everything shapes are banned
        if not (isinstance(handler.type, ast.Name)
                and handler.type.id in ("Exception", "BaseException")):
            return False
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant))
        for stmt in handler.body
    )


@register
class SwallowedFaultsRule(Rule):
    name = "swallowed-faults"
    summary = ("no `except Exception: pass` outside rca_tpu/resilience/ — "
               "swallowed faults go through policy.suppressed()")
    why = ("a fault discarded outside the policy layer leaves no record in "
           "the bounded fault log, so degraded behavior in production has "
           "no evidence trail")

    def applies_to(self, relpath: str) -> bool:
        return not relpath.startswith(ALLOWED_PREFIX)

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.ExceptHandler) and is_swallow(node):
                hits.append(ctx.finding(self, node.lineno, MESSAGE,
                                        func=func))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
