"""Rule ``span-discipline``: spans open only via the Tracer seam, and
every opened span closes.

The tracing subsystem (rca_tpu/observability, OBSERVABILITY.md) keeps
its overhead honest through two structural invariants this rule makes
unlandable:

- **no raw spans**: ``Span(...)`` is constructed ONLY inside
  ``rca_tpu/observability/spans.py`` (the seam).  A hand-built span
  bypasses the ring buffer's bounds, the null-tracer zero-cost path, and
  the id-minting discipline that keeps traces connected;
- **with-block only**: every ``.span(...)`` call is the context
  expression of a ``with`` statement — the form whose ``finally``
  guarantees the span records even when the body raises.  A bare
  ``tracer.span(...)`` call is a span that may never close (it is a
  context manager nobody entered), which silently truncates traces
  exactly when something went wrong — the moment they were needed.
  Phases whose start and end live in different methods use
  ``tracer.record(start, end, ...)``, which takes COMPLETE timestamps
  and cannot leak.

Wall-clock hygiene inside ``observability/`` itself is the
nondet-discipline rule's job (its REPLAY_SCOPE covers the package);
this rule owns the structural span contract.
"""

from __future__ import annotations

import ast
from typing import List, Set

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

#: the one file allowed to construct Span objects
SEAM = "rca_tpu/observability/spans.py"

MSG_RAW_SPAN = (
    "raw Span(...) construction outside the tracer seam "
    f"({SEAM}) — mint spans through tracer.span(...) / "
    "tracer.record(...) so they land in the bounded buffer and the "
    "RCA_TRACE=0 path stays zero-cost"
)
MSG_BARE_SPAN = (
    "bare .span(...) call — tracer.span() is a context manager and "
    "MUST be the context expression of a `with` block (its finally is "
    "what guarantees the span closes); for cross-method phases use "
    "tracer.record(start, end, ...) with complete timestamps"
)


@register
class SpanDisciplineRule(Rule):
    name = "span-discipline"
    summary = ("spans open only via the Tracer seam and always close "
               "(with-block); no raw Span construction outside it")
    why = ("an unclosed span truncates the trace of exactly the request "
           "that failed, and a hand-built span bypasses the bounded "
           "ring buffer — both turn the observability layer into a "
           "liability precisely when it is being read")

    def applies_to(self, relpath: str) -> bool:
        # repo-wide (tests included): the seam must hold everywhere
        return relpath.endswith(".py")

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []
        in_seam = ctx.relpath == SEAM

        # calls that ARE a with-item context expression are the blessed
        # form; collect their ids first, then flag every other .span(
        with_items: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, ast.Call):
                        with_items.add(id(expr))

        def walk(node: ast.AST, func: str) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                func = node.name
            if isinstance(node, ast.Call):
                callee = node.func
                if (not in_seam and isinstance(callee, ast.Name)
                        and callee.id == "Span"):
                    hits.append(ctx.finding(
                        self, node.lineno, MSG_RAW_SPAN, func=func,
                    ))
                if (isinstance(callee, ast.Attribute)
                        and callee.attr == "span"
                        and id(node) not in with_items):
                    hits.append(ctx.finding(
                        self, node.lineno, MSG_BARE_SPAN, func=func,
                    ))
            for child in ast.iter_child_nodes(node):
                walk(child, func)

        walk(ctx.tree, "<module>")
        return hits
