"""Rule ``no-dict-scan``: vectorized capture paths stay vectorized.

ISSUE 10 turned capture from per-object dict scans into columnar slices
(:mod:`rca_tpu.cluster.columnar`); the whole win evaporates if a future
edit quietly re-introduces a ``for pod in pods`` loop inside one of the
assembly functions.  This rule guards exactly those functions: inside the
columnar capture scope, any function whose docstring carries the
``[no-dict-scan]`` marker must contain NO ``for``/``while`` statements —
per-row work belongs in the row-write encoders (which run once per
mutation), not in the per-capture assembly.

Comprehensions over the small registries (distinct label sets, node
names, service metadata) are the documented allowlist: they are O(distinct)
rather than O(pods), which is the quantity this rule protects.  A loop
that genuinely must exist in a marked function takes a
``# graftlint: disable=no-dict-scan`` with a justification, same as every
other rule.
"""

from __future__ import annotations

import ast
from typing import List

from rca_tpu.analysis.core import FileContext, Finding, Rule, register

#: files whose marked functions are the vectorized capture surface
SCOPE = (
    "rca_tpu/cluster/columnar.py",
    "rca_tpu/features/extract.py",
    # live ingest (ISSUE 17): the watch-pump adapter's payload() is the
    # per-capture surface — per-mutation loops stay behind _sync
    "rca_tpu/cluster/live_columnar.py",
)

MARKER = "[no-dict-scan]"

MESSAGE = (
    "{stmt} loop in {func}(), a [no-dict-scan]-marked vectorized capture "
    "function — per-row work belongs in the row-write encoders (paid per "
    "mutation), not in per-capture assembly; use column slices, or move "
    "the loop behind the marker boundary"
)


@register
class NoDictScanRule(Rule):
    name = "no-dict-scan"
    summary = ("no for/while statements inside [no-dict-scan]-marked "
               "capture-assembly functions — columnar capture stays "
               "O(dirty rows), not O(objects)")
    why = ("one per-pod Python loop creeping back into the assembly path "
           "silently re-inflates a 100k-pod sweep from milliseconds to "
           "seconds — the exact regression ISSUE 10 removed")

    def applies_to(self, relpath: str) -> bool:
        return relpath in SCOPE

    def scan(self, ctx: FileContext) -> List[Finding]:
        hits: List[Finding] = []

        def check_function(fn: ast.AST) -> None:
            doc = ast.get_docstring(fn) or ""
            if MARKER not in doc:
                return
            for node in ast.walk(fn):
                if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    stmt = "while" if isinstance(node, ast.While) else "for"
                    hits.append(ctx.finding(
                        self, node.lineno,
                        MESSAGE.format(stmt=stmt, func=fn.name),
                        func=fn.name,
                    ))

        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                check_function(node)
        return hits
