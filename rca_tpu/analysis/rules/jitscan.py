"""Shared JAX-aware AST analysis: which functions trace under jit, which
of their names hold traced values.

Both the tracer-leak and retrace-hazard rules need the same two facts
about a module:

1. **jit-reachable functions** — decorated with ``jax.jit`` / ``jit`` /
   ``functools.partial(jax.jit, ...)``, passed by name to a ``jax.jit(fn)``
   call anywhere in the module, or nested inside either (a closure traced
   by its enclosing jit function traces too);
2. **traced names** inside such a function — parameters not named static
   by ``static_argnames``/``static_argnums``, plus locals assigned from
   expressions involving traced names or ``jnp``/``jax.lax`` calls.
   Shape/dtype accessors (``x.shape``, ``x.ndim``, ``x.dtype``,
   ``x.size``, ``len(x)``) are *static under trace* and deliberately do
   not propagate taint — ``if x.shape[0] > 4`` is legal jit Python.

This is a linter, not an abstract interpreter: the dataflow is a single
forward pass per function, which is exactly enough to catch the bug
classes that land in review (host branching on device values, per-call
literals) without drowning the repo in false positives.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

# calls whose results are traced arrays when they appear inside a jit
# function (module roots; `jnp.zeros(...)`, `jax.lax.scan(...)`, ...)
_TRACED_ROOTS = ("jnp", "lax")
# attribute accesses that are static under trace even on a traced value
STATIC_ATTRS = ("shape", "ndim", "dtype", "size", "weak_type", "sharding")


def _dec_is_jit(dec: ast.expr) -> Optional[Tuple[Set[str], Set[int]]]:
    """If ``dec`` marks a function as jit, return (static_argnames,
    static_argnums); else None."""

    def _is_jit_name(node: ast.expr) -> bool:
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
        return (
            isinstance(node, ast.Attribute) and node.attr == "jit"
            and isinstance(node.value, ast.Name) and node.value.id == "jax"
        )

    if _is_jit_name(dec):
        return set(), set()
    if isinstance(dec, ast.Call):
        f = dec.func
        is_partial = (
            (isinstance(f, ast.Name) and f.id == "partial")
            or (isinstance(f, ast.Attribute) and f.attr == "partial")
        )
        if is_partial and dec.args and _is_jit_name(dec.args[0]):
            return _static_kwargs(dec)
        if _is_jit_name(f):  # @jax.jit(static_argnames=...) direct call form
            return _static_kwargs(dec)
    return None


def _static_kwargs(call: ast.Call) -> Tuple[Set[str], Set[int]]:
    names: Set[str] = set()
    nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            names |= _const_strs(kw.value)
        elif kw.arg == "static_argnums":
            nums |= _const_ints(kw.value)
    return names, nums


def _const_strs(node: ast.expr) -> Set[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[str] = set()
        for e in node.elts:
            out |= _const_strs(e)
        return out
    return set()


def _const_ints(node: ast.expr) -> Set[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return {node.value}
    if isinstance(node, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for e in node.elts:
            out |= _const_ints(e)
        return out
    return set()


class JitFunction:
    """One jit-traced function plus its statically-known params."""

    def __init__(self, node, static_names: Set[str], static_nums: Set[int]):
        self.node = node
        args = node.args
        ordered = [a.arg for a in args.posonlyargs + args.args]
        static = set(static_names)
        static |= {ordered[i] for i in static_nums if i < len(ordered)}
        self.params = set(ordered) | {a.arg for a in args.kwonlyargs}
        self.static = static
        self.traced_params = self.params - static


def jit_functions(ctx) -> List["JitFunction"]:
    """Per-file memo of :func:`collect_jit_functions` (several rules need
    the same scan; the walk is the analyzer's most expensive pass)."""
    if "jit_functions" not in ctx.cache:
        ctx.cache["jit_functions"] = collect_jit_functions(ctx.tree)
    return ctx.cache["jit_functions"]


def collect_jit_functions(tree: ast.AST) -> List[JitFunction]:
    """Every function in the module that traces under jit (see module
    docstring for the three spellings), outermost only — nested defs are
    analyzed as part of their enclosing jit function's body."""
    # names passed to a bare jax.jit(fn, ...) call anywhere in the module
    wrapped: Dict[str, Tuple[Set[str], Set[int]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _dec_is_jit(node.func) is not None:
            if node.args and isinstance(node.args[0], ast.Name):
                wrapped[node.args[0].id] = _static_kwargs(node)

    out: List[JitFunction] = []
    claimed: Set[ast.AST] = set()

    def visit(node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spec = None
            for dec in node.decorator_list:
                spec = _dec_is_jit(dec)
                if spec is not None:
                    break
            if spec is None and node.name in wrapped:
                spec = wrapped[node.name]
            if spec is not None and node not in claimed:
                out.append(JitFunction(node, *spec))
                # nested defs belong to this traced body
                for child in ast.walk(node):
                    claimed.add(child)
        for child in ast.iter_child_nodes(node):
            if child not in claimed:
                visit(child)

    visit(tree)
    return out


def is_jnp_call(node: ast.expr, attrs: Optional[Set[str]] = None) -> bool:
    """Is ``node`` a call like ``jnp.<attr>`` / ``jax.lax.<attr>`` /
    ``jax.nn.<attr>`` (optionally restricted to ``attrs``)?"""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if not isinstance(f, ast.Attribute):
        return False
    if attrs is not None and f.attr not in attrs:
        return False
    base = f.value
    if isinstance(base, ast.Name) and base.id in _TRACED_ROOTS:
        return True
    if (isinstance(base, ast.Attribute)
            and base.attr in ("lax", "nn", "numpy")
            and isinstance(base.value, ast.Name) and base.value.id == "jax"):
        return True
    return False


def involves_traced(node: ast.expr, traced: Set[str]) -> bool:
    """Does evaluating ``node`` touch a traced value?  Shape/dtype/len
    accesses are static under trace and terminate the walk."""

    def walk(n: ast.AST) -> bool:
        if isinstance(n, ast.Attribute) and n.attr in STATIC_ATTRS:
            return False
        if isinstance(n, ast.Call):
            f = n.func
            if isinstance(f, ast.Name) and f.id in ("len", "isinstance"):
                return False
            if is_jnp_call(n):
                return True
        if isinstance(n, ast.Name) and n.id in traced:
            return True
        return any(walk(c) for c in ast.iter_child_nodes(n))

    return walk(node)


def _target_names(target: ast.expr) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in target.elts:
            out += _target_names(e)
        return out
    if isinstance(target, ast.Starred):
        return _target_names(target.value)
    return []


def traced_names(fn: JitFunction) -> Set[str]:
    """Single forward dataflow pass: the set of names that may hold traced
    values anywhere in the function.  Conservative in ONE direction — a
    name once tainted stays tainted (loops may re-bind in either order),
    so rules only report constructs whose *test expression* touches the
    set, which keeps false positives to genuinely suspicious lines."""
    traced: Set[str] = set(fn.traced_params)

    class Tainter(ast.NodeVisitor):
        def visit_FunctionDef(self, node):
            # nested defs: params are traced too (closures under trace)
            traced.update(a.arg for a in node.args.args)
            self.generic_visit(node)

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Assign(self, node):
            if involves_traced(node.value, traced):
                for t in node.targets:
                    traced.update(_target_names(t))
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            if involves_traced(node.value, traced):
                traced.update(_target_names(node.target))
            self.generic_visit(node)

        def visit_For(self, node):
            if involves_traced(node.iter, traced):
                traced.update(_target_names(node.target))
            self.generic_visit(node)

    # two passes so later-defined helpers that feed earlier loops settle;
    # visit the BODY (visiting fn.node itself would re-taint the static
    # params via the nested-def branch)
    for _ in range(2):
        before = len(traced)
        tainter = Tainter()
        for stmt in fn.node.body:
            tainter.visit(stmt)
        if len(traced) == before:
            break
    return traced
