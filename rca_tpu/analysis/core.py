"""graftlint core: rule registry, suppressions, baseline, runner.

The framework behind ``rca lint`` (ANALYSIS.md).  PR 1 and PR 2 each
shipped an invariant defended by a bespoke script (``tools/lint_*.py``);
this package replaces one-rule-one-script with a pluggable AST analyzer
so the next invariant is a ~50-line rule module, not another parallel
toolchain.  The moving parts:

- :class:`Rule` subclasses register themselves via :func:`register`; each
  rule scopes itself (``applies_to``), carries per-file/per-function
  allowlists (``allow``), and emits :class:`Finding`\\ s from one shared
  parse of each file;
- ``# graftlint: disable=<rule>[,<rule>]`` on a flagged line suppresses it;
  ``# graftlint: disable-file=<rule>`` anywhere in a file suppresses the
  rule for the whole file (``all`` works in both);
- a checked-in baseline (``rca_tpu/analysis/baseline.json``) holds
  accepted legacy hits as content fingerprints (rule + path + source
  line), so baselined findings survive line drift but die with the code
  that earned them; stale entries are reported so the baseline only ever
  shrinks;
- exit-code contract (``python -m rca_tpu.analysis``): 0 clean, 1
  findings, 2 usage/internal error.
"""

from __future__ import annotations

import ast
import collections
import dataclasses
import hashlib
import json
import os
import re
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

# scanned by default, relative to the repo root (rules narrow further via
# applies_to; tests are included so e.g. swallowed-fault hygiene covers
# the test suite exactly as the PR-1 script did)
SCAN_DIRS = ("rca_tpu", "tools", "tests")
SCAN_FILES = ("bench.py",)

_SUPPRESS_LINE = re.compile(r"#\s*graftlint:\s*disable=([\w,\- ]+)")
_SUPPRESS_FILE = re.compile(r"^\s*#\s*graftlint:\s*disable-file=([\w,\- ]+)")

#: process-wide shared parse cache: ONE ast.parse per file per run even
#: though graftlint, the concurrency model, and the dataplane analyzer
#: all walk the same files.  Keyed by absolute path, validated by
#: (mtime_ns, size) so an edited file reparses.  Single-threaded by
#: design (the lint is sequential; a stale double-parse is the only
#: failure mode anyway).  Trees served from here are SHARED — callers
#: must treat them as read-only.
_PARSE_CACHE: Dict[str, Tuple[Tuple[int, int], str, ast.AST]] = {}
_PARSE_STATS = {"hits": 0, "misses": 0}


def parse_file(full: str) -> Tuple[str, ast.AST]:
    """(source, tree) for ``full`` via the shared cache.  SyntaxError /
    OSError propagate to the caller, exactly like the direct parse."""
    full = os.path.abspath(full)
    st = os.stat(full)
    key = (st.st_mtime_ns, st.st_size)
    hit = _PARSE_CACHE.get(full)
    if hit is not None and hit[0] == key:
        _PARSE_STATS["hits"] += 1
        return hit[1], hit[2]
    _PARSE_STATS["misses"] += 1
    with open(full, encoding="utf-8") as f:
        source = f.read()
    tree = ast.parse(source, filename=full)
    _PARSE_CACHE[full] = (key, source, tree)
    return source, tree


def parse_cache_stats() -> Dict[str, int]:
    """Cumulative process-wide hit/miss counters (bench reads the delta
    around a lint+model run to report ``parse_cache_hit_rate``)."""
    return dict(_PARSE_STATS)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str          # repo-relative, forward slashes
    line: int          # 1-based
    message: str
    snippet: str = ""  # stripped source of the flagged line
    func: str = ""     # enclosing function ("<module>" at top level)

    def fingerprint(self) -> str:
        """Content fingerprint for the baseline: stable across pure line
        drift (code above moving), invalidated when the flagged line
        itself changes — a baselined hit cannot silently mutate."""
        blob = f"{self.rule}|{self.path}|{self.snippet}"
        return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "message": self.message, "snippet": self.snippet,
            "fingerprint": self.fingerprint(),
        }


class FileContext:
    """One parsed file, shared by every rule that scans it."""

    def __init__(self, relpath: str, source: str, tree: ast.AST,
                 root: Optional[str] = None):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # repo root of this lint run: interprocedural rules (gravelock)
        # build their whole-package model from it, then report only the
        # findings that live in THIS file
        self.root = root or repo_root()
        self.cache: Dict[str, object] = {}  # cross-rule analysis memos

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def finding(self, rule: "Rule", lineno: int, message: str,
                func: str = "") -> Finding:
        return Finding(
            rule=rule.name, path=self.relpath, line=lineno,
            message=message, snippet=self.line(lineno), func=func,
        )

    def file_suppressed(self) -> Set[str]:
        """Rule names disabled for the whole file."""
        out: Set[str] = set()
        for line in self.lines:
            m = _SUPPRESS_FILE.match(line)
            if m:
                out |= {r.strip() for r in m.group(1).split(",") if r.strip()}
        return out

    def line_suppressed(self, lineno: int) -> Set[str]:
        """Rule names disabled on one line (trailing comment)."""
        m = _SUPPRESS_LINE.search(self.line(lineno))
        if not m:
            return set()
        return {r.strip() for r in m.group(1).split(",") if r.strip()}


class Rule:
    """One lint rule.  Subclass, set ``name``/``summary``/``why``, implement
    ``scan``, and decorate with :func:`register`."""

    name: str = ""
    summary: str = ""   # one line for --list-rules / README
    why: str = ""       # the TPU/production failure mode this rule prevents
    # per-file allowlist: repo-relative path -> function names exempt from
    # this rule in that file (the framework filters on Finding.func)
    allow: Dict[str, Set[str]] = {}

    def applies_to(self, relpath: str) -> bool:
        return True

    def scan(self, ctx: FileContext) -> List[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate the rule and add it to the registry."""
    rule = cls()
    if not rule.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if rule.name in _REGISTRY:
        raise ValueError(f"duplicate rule name {rule.name!r}")
    _REGISTRY[rule.name] = rule
    return cls


def all_rules() -> Dict[str, Rule]:
    """The registry, importing the bundled rule modules on first use."""
    import rca_tpu.analysis.rules  # noqa: F401  (registers on import)

    return dict(sorted(_REGISTRY.items()))


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)
    )))


def default_baseline_path(root: Optional[str] = None) -> str:
    return os.path.join(root or repo_root(), "rca_tpu", "analysis",
                        "baseline.json")


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> List[dict]:
    """Baseline entries (``[]`` when the file is absent)."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", [])
    for e in entries:
        if not {"rule", "path", "fingerprint"} <= set(e):
            raise ValueError(f"malformed baseline entry: {e!r}")
    return entries


def write_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [
        {"rule": f.rule, "path": f.path, "fingerprint": f.fingerprint(),
         "snippet": f.snippet}
        for f in sorted(findings, key=lambda f: (f.rule, f.path, f.line))
    ]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=2)
        f.write("\n")


# -- incremental lint (`rca lint --changed`) ---------------------------------
#
# A fingerprint index (content sha1 per scanned file) lives under
# .graftlint/ in the repo root; every lint run that scans the default set
# refreshes it.  `--changed` lints only the files that are git-dirty OR
# whose content no longer matches the index — against the SAME
# whole-package concurrency model a full run builds, so the findings for
# a touched file are identical either way (asserted by
# tests/test_gravelock.py::test_changed_parity).


def index_path(root: str) -> str:
    return os.path.join(root, ".graftlint", "index.json")


def _sha1_file(path: str) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def load_index(root: str) -> Dict[str, str]:
    path = index_path(root)
    if not os.path.exists(path):
        return {}
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        files = data.get("files", {})
        return {k: v for k, v in files.items() if isinstance(v, str)}
    except (json.JSONDecodeError, OSError):
        return {}


def update_index(root: str, files: Sequence[str]) -> None:
    """Refresh index entries for ``files`` (repo-relative).  Best-effort:
    an unwritable tree must not fail the lint."""
    idx = load_index(root)
    for rel in files:
        full = os.path.join(root, rel)
        try:
            idx[rel] = _sha1_file(full)
        except OSError:
            idx.pop(rel, None)
    # atomic publish: write a sibling temp file and rename over the
    # index, so a crash mid-write leaves the previous index intact
    # (readers never observe a torn JSON document)
    tmp = index_path(root) + f".tmp.{os.getpid()}"
    try:
        os.makedirs(os.path.dirname(index_path(root)), exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "files": idx}, f, indent=0,
                      sort_keys=True)
            f.write("\n")
        os.replace(tmp, index_path(root))
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _git_dirty(root: str) -> Set[str]:
    """Repo-relative paths git considers modified/untracked (empty set
    when git is unavailable — the fingerprint index still works)."""
    import subprocess

    try:
        proc = subprocess.run(
            ["git", "-C", root, "status", "--porcelain", "-z"],
            capture_output=True, timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return set()
    if proc.returncode != 0:
        return set()
    out: Set[str] = set()
    for entry in proc.stdout.decode("utf-8", "replace").split("\0"):
        if len(entry) < 4:
            continue
        path = entry[3:]
        out.add(path.replace(os.sep, "/"))
    return out


def changed_files(root: str) -> List[str]:
    """The subset of the default scan set that is git-dirty or whose
    content differs from the cached fingerprint index."""
    scan = discover_files(root)
    dirty = _git_dirty(root)
    idx = load_index(root)
    out = []
    for rel in scan:
        if rel in dirty:
            out.append(rel)
            continue
        try:
            digest = _sha1_file(os.path.join(root, rel))
        except OSError:
            out.append(rel)
            continue
        if idx.get(rel) != digest:
            out.append(rel)
    return out


# -- runner -----------------------------------------------------------------

@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int
    baselined: int
    stale_baseline: List[dict]
    files_scanned: int
    wall_ms: float
    per_rule_ms: Dict[str, float]
    #: shared-parse-cache hits/misses attributable to this run
    parse_cache: Dict[str, int] = dataclasses.field(default_factory=dict)

    @property
    def clean(self) -> bool:
        return not self.findings

    def to_dict(self) -> dict:
        return {
            "clean": self.clean,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": self.suppressed,
            "baselined": self.baselined,
            "stale_baseline": self.stale_baseline,
            "files_scanned": self.files_scanned,
            "wall_ms": round(self.wall_ms, 3),
            "per_rule_ms": {
                k: round(v, 3) for k, v in sorted(self.per_rule_ms.items())
            },
            "parse_cache": dict(self.parse_cache),
        }


def discover_files(root: str, paths: Optional[Sequence[str]] = None
                   ) -> List[str]:
    """Repo-relative paths (forward slashes) to scan.  Explicit ``paths``
    (files or directories, relative to root or absolute) override the
    default scan set."""
    rels: List[str] = []
    if paths:
        for p in paths:
            full = p if os.path.isabs(p) else os.path.join(root, p)
            if os.path.isdir(full):
                for dirpath, _dirs, files in os.walk(full):
                    rels += [
                        os.path.join(dirpath, f)
                        for f in files if f.endswith(".py")
                    ]
            elif os.path.exists(full):
                rels.append(full)
            else:
                raise FileNotFoundError(p)
        return sorted(
            os.path.relpath(r, root).replace(os.sep, "/") for r in rels
        )
    for d in SCAN_DIRS:
        base = os.path.join(root, d)
        for dirpath, _dirs, files in os.walk(base):
            rels += [
                os.path.join(dirpath, f) for f in files if f.endswith(".py")
            ]
    rels += [
        os.path.join(root, f) for f in SCAN_FILES
        if os.path.exists(os.path.join(root, f))
    ]
    return sorted(
        os.path.relpath(r, root).replace(os.sep, "/") for r in rels
    )


def run_lint(
    root: Optional[str] = None,
    rules: Optional[Sequence[str]] = None,
    baseline_path: Optional[str] = None,
    paths: Optional[Sequence[str]] = None,
    use_baseline: bool = True,
) -> LintResult:
    """Run the selected rules over the repo (or ``paths``) and fold in
    suppressions + baseline.  Pure function of the tree on disk."""
    t0 = time.perf_counter()
    root = root or repo_root()
    registry = all_rules()
    if rules:
        unknown = set(rules) - set(registry)
        if unknown:
            raise KeyError(
                f"unknown rule(s): {', '.join(sorted(unknown))} "
                f"(have: {', '.join(registry)})"
            )
        selected = [registry[r] for r in rules]
    else:
        selected = list(registry.values())

    raw: List[Finding] = []
    suppressed = 0
    per_rule_ms: Dict[str, float] = {r.name: 0.0 for r in selected}
    pc0 = parse_cache_stats()
    files = discover_files(root, paths)
    for rel in files:
        full = os.path.join(root, rel)
        applicable = [r for r in selected if r.applies_to(rel)]
        if not applicable:
            continue
        try:
            source, tree = parse_file(full)
        except (SyntaxError, OSError) as exc:
            lineno = getattr(exc, "lineno", 0) or 0
            raw.append(Finding(
                rule="parse-error", path=rel, line=lineno,
                message=f"{type(exc).__name__}: {exc}",
            ))
            continue
        ctx = FileContext(rel, source, tree, root=root)
        file_off = ctx.file_suppressed()
        for rule in applicable:
            if rule.name in file_off or "all" in file_off:
                continue
            rt0 = time.perf_counter()
            for finding in rule.scan(ctx):
                allowed_funcs = rule.allow.get(rel, set())
                line_off = ctx.line_suppressed(finding.line)
                if finding.func in allowed_funcs:
                    continue
                if rule.name in line_off or "all" in line_off:
                    suppressed += 1
                    continue
                raw.append(finding)
            per_rule_ms[rule.name] += (time.perf_counter() - rt0) * 1e3

    # baseline filter: consume entries as a multiset so N identical
    # baselined lines absorb exactly N findings, not unlimited ones
    baselined = 0
    stale: List[dict] = []
    findings = raw
    if use_baseline:
        bpath = baseline_path or default_baseline_path(root)
        entries = load_baseline(bpath)
        budget = collections.Counter(
            (e["rule"], e["path"], e["fingerprint"]) for e in entries
        )
        findings = []
        for f in raw:
            key = (f.rule, f.path, f.fingerprint())
            if budget.get(key, 0) > 0:
                budget[key] -= 1
                baselined += 1
            else:
                findings.append(f)
        ran = {r.name for r in selected} | {"parse-error"}
        scanned = set(files)
        stale = [
            {"rule": rule, "path": path, "fingerprint": fp, "count": n}
            for (rule, path, fp), n in sorted(budget.items()) if n > 0
            # only entries this run could have matched count as stale
            if rule in ran and path in scanned
        ]

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    pc1 = parse_cache_stats()
    return LintResult(
        findings=findings, suppressed=suppressed, baselined=baselined,
        stale_baseline=stale, files_scanned=len(files),
        wall_ms=(time.perf_counter() - t0) * 1e3, per_rule_ms=per_rule_ms,
        parse_cache={k: pc1[k] - pc0[k] for k in ("hits", "misses")},
    )
