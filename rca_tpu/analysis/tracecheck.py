"""Dynamic recompile gate: the public engine entry points compile once.

The static rules (tracer-leak, retrace-hazard) catch the *code shapes*
that cause silent recompilation; this companion closes the gap they
cannot see by actually running each public entry point twice with
identically-shaped inputs under ``jax_log_compiles`` and failing if the
second call compiles anything.  A recompile on call two means some cache
key changed between bit-identical calls — a fresh ``jnp`` constant, an
unhashable static, a shape that escaped bucketing — exactly the
regression class that lands with every test green and shows up weeks
later as a 30 s stall on the first production tick of a new pod.

Run via ``python -m rca_tpu.analysis --tracecheck`` (or ``rca lint
--tracecheck``); tests/test_analysis.py gates it under tier-1.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

N_SERVICES = 24  # small synthetic graph: compile cost, not engine scale


@contextlib.contextmanager
def compile_log_capture(records: List[str]):
    """Collect XLA "Compiling <name>" log lines emitted inside the block.

    ``jax_log_compiles`` promotes the compile-path logs to WARNING on the
    ``jax.*`` loggers; a handler on the package root sees them all.  The
    logger's propagation is suspended so enabling the flag does not spray
    compile chatter onto the caller's stderr."""
    import jax

    logger = logging.getLogger("jax")

    class _Handler(logging.Handler):
        def emit(self, record: logging.LogRecord) -> None:
            msg = record.getMessage()
            if msg.startswith("Compiling "):
                records.append(msg)

    handler = _Handler(level=logging.WARNING)
    prev_level = logger.level
    prev_propagate = logger.propagate
    prev_handlers = list(logger.handlers)
    prev_flag = jax.config.jax_log_compiles
    # ours is the ONLY handler for the duration: jax installs its own
    # stderr StreamHandler on the package logger, which would otherwise
    # spray every promoted compile log onto the operator's terminal
    logger.handlers = [handler]
    if logger.level > logging.WARNING or logger.level == logging.NOTSET:
        logger.setLevel(logging.WARNING)
    logger.propagate = False
    jax.config.update("jax_log_compiles", True)
    try:
        yield
    finally:
        jax.config.update("jax_log_compiles", prev_flag)
        logger.handlers = prev_handlers
        logger.setLevel(prev_level)
        logger.propagate = prev_propagate


def _case():
    from rca_tpu.cluster.generator import synthetic_cascade_arrays

    return synthetic_cascade_arrays(N_SERVICES, n_roots=1, seed=0)


def _entry_analyze() -> Callable[[], None]:
    from rca_tpu.engine.runner import GraphEngine

    engine = GraphEngine()
    case = _case()

    def call() -> None:
        engine.analyze_case(case, k=5)

    return call


def _entry_analyze_batch() -> Callable[[], None]:
    import numpy as np

    from rca_tpu.engine.sharded_runner import make_engine

    engine = make_engine()
    case = _case()
    batch = np.repeat(np.asarray(case.features, np.float32)[None], 4, axis=0)

    def call() -> None:
        engine.analyze_batch(batch, case.dep_src, case.dep_dst,
                             names=case.names, k=5)

    return call


def _entry_streaming_tick() -> Callable[[], None]:
    import numpy as np

    from rca_tpu.engine.streaming import StreamingSession

    case = _case()
    session = StreamingSession(
        case.names, case.dep_src, case.dep_dst,
        num_features=case.features.shape[1], k=5,
    )
    session.set_all(np.asarray(case.features, np.float32))
    row = np.asarray(case.features[0], np.float32)

    def call() -> None:
        # one changed row per tick: the steady-state hot path
        session.update(0, row)
        session.tick()

    return call


def _entry_propagate() -> Callable[[], None]:
    import jax.numpy as jnp
    import numpy as np

    from rca_tpu.config import RCAConfig, bucket_for
    from rca_tpu.engine.propagate import default_params, propagate_jit

    case = _case()
    cfg = RCAConfig()
    n_pad = bucket_for(N_SERVICES + 1, cfg.shape_buckets)
    e_pad = bucket_for(max(len(case.dep_src), 1), cfg.shape_buckets)
    dummy = n_pad - 1
    f = np.zeros((n_pad, case.features.shape[1]), np.float32)
    f[:N_SERVICES] = case.features
    s = np.full(e_pad, dummy, np.int32)
    d = np.full(e_pad, dummy, np.int32)
    s[: len(case.dep_src)] = case.dep_src
    d[: len(case.dep_dst)] = case.dep_dst
    features = jnp.asarray(f)
    src = jnp.asarray(s)
    dst = jnp.asarray(d)
    p = default_params(cfg.propagation_steps)
    aw, hw = p.weight_arrays()

    def call() -> None:
        propagate_jit(
            features, src, dst, aw, hw, steps=p.steps, decay=p.decay,
            explain_strength=p.explain_strength,
            impact_bonus=p.impact_bonus,
        )

    return call


ENTRY_POINTS: Dict[str, Callable[[], Callable[[], None]]] = {
    "engine.analyze_case": _entry_analyze,
    "engine.analyze_batch": _entry_analyze_batch,
    "streaming.tick": _entry_streaming_tick,
    "propagate_jit": _entry_propagate,
}


def run_tracecheck(
    entries: Optional[List[str]] = None,
) -> dict:
    """Each entry point: warm-up call (compiles expected), then a second
    bit-identical call that must be compile-free.  Returns a summary dict
    with ``ok`` plus per-entry compile counts."""
    selected: List[Tuple[str, Callable]] = [
        (name, builder) for name, builder in ENTRY_POINTS.items()
        if entries is None or name in entries
    ]
    if entries:
        unknown = set(entries) - {n for n, _ in selected}
        if unknown:
            raise KeyError(f"unknown tracecheck entries: {sorted(unknown)}")
    results = []
    for name, builder in selected:
        t0 = time.perf_counter()
        call = builder()
        warm: List[str] = []
        second: List[str] = []
        with compile_log_capture(warm):
            call()
        with compile_log_capture(second):
            call()
        results.append({
            "entry": name,
            "warmup_compiles": len(warm),
            "recompiles": len(second),
            "recompiled": sorted({m.split()[1] for m in second}),
            "ok": not second,
            "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
        })
    return {
        "ok": all(r["ok"] for r in results),
        "entries": results,
    }
