"""graftlint: JAX/TPU-aware static analysis for this codebase (ANALYSIS.md).

Usage::

    python -m rca_tpu.analysis            # or: python -m rca_tpu lint
    python -m rca_tpu.analysis --json
    python -m rca_tpu.analysis --tracecheck

Programmatic surface: :func:`run_lint` (static rules),
:func:`run_tracecheck` (dynamic recompile gate), :func:`all_rules`.
"""

from rca_tpu.analysis.core import (
    Finding,
    LintResult,
    Rule,
    all_rules,
    default_baseline_path,
    load_baseline,
    register,
    repo_root,
    run_lint,
    write_baseline,
)
from rca_tpu.analysis.tracecheck import run_tracecheck

__all__ = [
    "Finding",
    "LintResult",
    "Rule",
    "all_rules",
    "default_baseline_path",
    "load_baseline",
    "register",
    "repo_root",
    "run_lint",
    "run_tracecheck",
    "write_baseline",
]
