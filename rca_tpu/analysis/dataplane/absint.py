"""graftspec abstract interpreter: symbolic (shape, dtype) facts over
jnp/lax expressions (ANALYSIS.md §graftspec).

A deliberately honest interpreter: every construct it does not model
evaluates to :data:`~rca_tpu.analysis.dataplane.contracts.UNKNOWN`, and
checks downstream only ever fire on KNOWN facts — so a gap in the op
table costs coverage, never a false positive.  Dims are ints (exact) or
symbol names (``"n_pad"``); ``None`` dims are wildcards.

The op table covers exactly the vocabulary the ranked executables use:
``propagate_auto`` and friends via :data:`SEMANTICS` (signature-level
summaries — the propagation core itself is covered by its own tests),
``jnp.stack`` / ``lax.top_k`` / ``topk_diag`` / ``.at[].set`` /
indexing / elementwise arithmetic with broadcast + dtype promotion.
Promotions between a low-precision dtype and float32 are recorded as
events for the ``dtype-discipline`` rule; casts likewise.
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Tuple, Union

from rca_tpu.analysis.dataplane.contracts import (
    Fact,
    LOW_PRECISION_DTYPES,
    UNKNOWN,
)

Dims = Tuple[Optional[Union[int, str]], ...]

_DTYPE_NAMES = frozenset({
    "float32", "float64", "float16", "bfloat16", "int8", "int16",
    "int32", "int64", "uint8", "uint32", "bool_",
} | LOW_PRECISION_DTYPES)

_ELEMENTWISE = frozenset({
    "maximum", "minimum", "where", "abs", "exp", "log", "log1p", "clip",
    "nan_to_num", "sqrt", "square", "tanh", "sigmoid", "relu", "add",
    "subtract", "multiply", "divide", "power", "logical_and",
    "logical_or", "logical_not", "isfinite", "isnan",
})

_REDUCTIONS = frozenset({"sum", "prod", "max", "min", "mean", "all", "any"})


def dtype_of_node(node: ast.expr) -> Optional[str]:
    """The dtype a ``jnp.float32`` / ``np.int8`` style reference names,
    else None."""
    if isinstance(node, ast.Attribute) and node.attr in _DTYPE_NAMES:
        return "bool" if node.attr == "bool_" else node.attr
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value if node.value in _DTYPE_NAMES else None
    return None


def promote(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a is None:
        return b
    if b is None or a == b:
        return a
    order = ("bool", "int8", "uint8", "int16", "int32", "int64",
             "bfloat16", "float16", "float32", "float64")
    if a in order and b in order:
        return max(a, b, key=order.index)
    return None


def broadcast(a: Optional[Dims], b: Optional[Dims]) -> Optional[Dims]:
    if a is None or b is None:
        return None
    if len(a) < len(b):
        a, b = b, a
    pad: Dims = (1,) * (len(a) - len(b)) + tuple(b)
    out = []
    for da, db in zip(a, pad):
        if da == 1:
            out.append(db)
        elif db == 1 or db == da or db is None:
            out.append(da)
        elif da is None:
            out.append(db)
        else:
            return None  # statically incompatible; stay silent here
    return tuple(out)


class Events:
    """What the walk observed, for the dtype/shape rules to judge."""

    def __init__(self) -> None:
        #: (lineno, to_dtype) for every explicit cast/typed constructor
        self.casts: List[Tuple[int, str]] = []
        #: (lineno, dtype_a, dtype_b) for every mixed-precision binop
        self.promotions: List[Tuple[int, str, str]] = []


FactLike = Union[Fact, Tuple["FactLike", ...]]

#: name -> summary(arg_facts) for the engine functions the executables
#: call: propagate_* return five [n_pad] float32 vectors (n_pad = the
#: feature buffer's leading dim), finite_mask_rows passes its input
#: through plus a scalar count, topk_diag gathers [lead, *idx.shape]
SEMANTICS: Dict[str, Callable[[List[FactLike]], FactLike]] = {}


def _sem(name):
    def deco(fn):
        SEMANTICS[name] = fn
        return fn
    return deco


def _first_dim(fact: FactLike):
    return fact.shape[0] if isinstance(fact, Fact) and fact.shape else None


@_sem("propagate_auto")
@_sem("propagate")
@_sem("propagate_core")
@_sem("propagate_ell")
def _sem_propagate(args: List[FactLike]) -> FactLike:
    n = _first_dim(args[0]) if args else None
    vec = Fact((n,), "float32")
    return (vec, vec, vec, vec, vec)


@_sem("finite_mask_rows")
def _sem_finite_mask(args: List[FactLike]) -> FactLike:
    src = args[0] if args and isinstance(args[0], Fact) else UNKNOWN
    return (src, Fact((), "int32"))


@_sem("topk_diag")
def _sem_topk_diag(args: List[FactLike]) -> FactLike:
    if (len(args) >= 2 and isinstance(args[0], Fact) and args[0].shape
            and isinstance(args[1], Fact) and args[1].shape is not None):
        return Fact((args[0].shape[0],) + tuple(args[1].shape),
                    args[0].dtype)
    return UNKNOWN


class Interpreter(ast.NodeVisitor):
    """One forward pass over a function body with an initial symbolic
    environment; collects per-name facts, cast/promotion events, and the
    facts of every ``return`` expression."""

    def __init__(self, env: Optional[Dict[str, FactLike]] = None):
        self.env: Dict[str, FactLike] = dict(env or {})
        self.events = Events()
        self.returns: List[FactLike] = []
        self._local_defs: Dict[str, ast.FunctionDef] = {}

    # -- driving ------------------------------------------------------------

    def run(self, fn: ast.FunctionDef) -> None:
        for stmt in fn.body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, ast.FunctionDef):
            self._local_defs[stmt.name] = stmt
            return
        if isinstance(stmt, ast.Assign):
            fact = self.eval(stmt.value)
            for target in stmt.targets:
                self._bind(target, fact)
        elif isinstance(stmt, ast.AugAssign):
            left = self.eval(stmt.target)
            fact = self._binop(left, self.eval(stmt.value), stmt.lineno)
            self._bind(stmt.target, fact)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.returns.append(self.eval(stmt.value))
        elif isinstance(stmt, (ast.If, ast.For, ast.While, ast.With)):
            self.eval(getattr(stmt, "test", None)
                      or getattr(stmt, "iter", None) or ast.Constant(0))
            for s in stmt.body + getattr(stmt, "orelse", []):
                self._stmt(s)
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
        # everything else (imports, asserts, raises): no fact flow

    def _bind(self, target: ast.expr, fact: FactLike) -> None:
        if isinstance(target, ast.Name):
            self.env[target.id] = fact
        elif isinstance(target, (ast.Tuple, ast.List)):
            if isinstance(fact, tuple) and len(fact) == len(target.elts):
                for t, f in zip(target.elts, fact):
                    self._bind(t, f)
            else:
                for t in target.elts:
                    self._bind(t, UNKNOWN)

    # -- expressions --------------------------------------------------------

    def eval(self, node: Optional[ast.expr]) -> FactLike:
        if node is None:
            return UNKNOWN
        if isinstance(node, ast.Name):
            return self.env.get(node.id, UNKNOWN)
        if isinstance(node, ast.Constant):
            if isinstance(node.value, bool):
                return Fact((), "bool")
            if isinstance(node.value, (int, float)):
                return Fact((), None)  # weak-typed scalar: never promotes
            return UNKNOWN
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self.eval(e) for e in node.elts)
        if isinstance(node, ast.BinOp):
            return self._binop(self.eval(node.left), self.eval(node.right),
                               node.lineno)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand)
        if isinstance(node, ast.Compare):
            left = self.eval(node.left)
            shape = left.shape if isinstance(left, Fact) else None
            for c in node.comparators:
                right = self.eval(c)
                if isinstance(right, Fact):
                    shape = broadcast(shape, right.shape)
            return Fact(shape, "bool")
        if isinstance(node, ast.IfExp):
            self.eval(node.test)
            a, b = self.eval(node.body), self.eval(node.orelse)
            return a if a != UNKNOWN else b
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.Subscript):
            return self._subscript(node)
        if isinstance(node, ast.Attribute):
            return UNKNOWN  # x.shape / x.T etc: static under trace
        return UNKNOWN

    def _binop(self, a: FactLike, b: FactLike, lineno: int) -> FactLike:
        if not isinstance(a, Fact) or not isinstance(b, Fact):
            return UNKNOWN
        if (a.dtype and b.dtype and a.dtype != b.dtype
                and (a.dtype in LOW_PRECISION_DTYPES)
                != (b.dtype in LOW_PRECISION_DTYPES)):
            self.events.promotions.append((lineno, a.dtype, b.dtype))
        return Fact(broadcast(a.shape, b.shape), promote(a.dtype, b.dtype))

    def _dim(self, node: ast.expr) -> Optional[Union[int, str]]:
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return node.value
        if isinstance(node, ast.Name):
            return node.id
        return None

    def _call(self, node: ast.Call) -> FactLike:
        func = node.func
        args = [self.eval(a) for a in node.args]

        # explicit dtype anywhere in the call: a cast event
        to_dtype = None
        for kw in node.keywords:
            if kw.arg == "dtype":
                to_dtype = dtype_of_node(kw.value)
        for a in node.args:
            d = dtype_of_node(a)
            if d is not None:
                to_dtype = d

        if isinstance(func, ast.Attribute):
            # x.astype(dt)
            if func.attr == "astype" and node.args:
                dt = dtype_of_node(node.args[0]) or to_dtype
                base = self.eval(func.value)
                if dt:
                    self.events.casts.append((node.lineno, dt))
                shape = base.shape if isinstance(base, Fact) else None
                return Fact(shape, dt)
            # x.at[idx].set(rows) -> fact of x
            if (func.attr in ("set", "add", "multiply", "min", "max")
                    and isinstance(func.value, ast.Subscript)
                    and isinstance(func.value.value, ast.Attribute)
                    and func.value.value.attr == "at"):
                return self.eval(func.value.value.value)
            name = func.attr
        elif isinstance(func, ast.Name):
            name = func.id
        else:
            # jax.vmap(f)(args): prepend the batch dim to f's outputs
            if (isinstance(func, ast.Call)
                    and isinstance(func.func, ast.Attribute)
                    and func.func.attr == "vmap" and func.args):
                return self._vmap(func.args[0], node)
            return UNKNOWN

        if to_dtype is not None:
            self.events.casts.append((node.lineno, to_dtype))

        if name in SEMANTICS:
            return SEMANTICS[name](args)
        if name == "stack":
            if args and isinstance(args[0], tuple):
                elems = [f for f in args[0] if isinstance(f, Fact)]
                if len(elems) == len(args[0]):
                    shape = elems[0].shape
                    dtype = elems[0].dtype
                    for f in elems[1:]:
                        shape = shape if shape == f.shape else None
                        dtype = promote(dtype, f.dtype)
                    if shape is not None:
                        return Fact((len(elems),) + tuple(shape), dtype)
            return UNKNOWN
        if name == "top_k" and len(node.args) >= 2:
            base = args[0]
            k = self._dim(node.args[1])
            if isinstance(base, Fact) and base.shape and k is not None:
                lead = tuple(base.shape[:-1])
                return (Fact(lead + (k,), base.dtype),
                        Fact(lead + (k,), "int32"))
            return (UNKNOWN, UNKNOWN)
        if name in ("asarray", "array"):
            base = args[0] if args else UNKNOWN
            shape = base.shape if isinstance(base, Fact) else None
            if to_dtype:
                return Fact(shape, to_dtype)
            return base if isinstance(base, Fact) else UNKNOWN
        if name in ("zeros", "ones", "full", "empty"):
            shape_node = node.args[0] if node.args else None
            dims: Optional[Dims] = None
            if isinstance(shape_node, (ast.Tuple, ast.List)):
                dims = tuple(self._dim(e) for e in shape_node.elts)
            elif shape_node is not None:
                d = self._dim(shape_node)
                dims = (d,) if d is not None else None
            return Fact(dims, to_dtype)
        if name in ("zeros_like", "ones_like", "full_like"):
            base = args[0] if args else UNKNOWN
            if isinstance(base, Fact):
                return Fact(base.shape, to_dtype or base.dtype)
            return UNKNOWN
        if name in _ELEMENTWISE:
            facts = [a for a in args if isinstance(a, Fact)]
            if name == "where" and len(facts) == 3:
                facts = facts[1:]
            out = facts[0] if facts else UNKNOWN
            for f in facts[1:]:
                if isinstance(out, Fact):
                    out = Fact(broadcast(out.shape, f.shape),
                               promote(out.dtype, f.dtype))
            return out
        if name in _REDUCTIONS:
            base = args[0] if args and isinstance(args[0], Fact) else UNKNOWN
            axis = None
            for kw in node.keywords:
                if kw.arg == "axis" and isinstance(kw.value, ast.Constant):
                    axis = kw.value.value
            if not isinstance(base, Fact) or base.shape is None:
                return UNKNOWN
            if axis is None:
                return Fact((), base.dtype)
            if isinstance(axis, int) and -len(base.shape) <= axis:
                shape = list(base.shape)
                del shape[axis]
                return Fact(tuple(shape), base.dtype)
            return UNKNOWN
        if name in ("argmax", "argmin", "argsort"):
            return Fact((), "int32")
        if name in self._local_defs:
            return self._interp_local(self._local_defs[name], args)
        return UNKNOWN

    def _interp_local(self, fn: ast.FunctionDef,
                      args: List[FactLike]) -> FactLike:
        params = [a.arg for a in fn.args.args]
        env = dict(self.env)
        env.update(dict(zip(params, args)))
        sub = Interpreter(env)
        sub._local_defs = dict(self._local_defs)
        sub.run(fn)
        self.events.casts += sub.events.casts
        self.events.promotions += sub.events.promotions
        return sub.returns[-1] if sub.returns else UNKNOWN

    def _vmap(self, fn_node: ast.expr, call: ast.Call) -> FactLike:
        if not isinstance(fn_node, ast.Name):
            return UNKNOWN
        fn = self._local_defs.get(fn_node.id)
        if fn is None:
            return UNKNOWN
        batched = [self.eval(a) for a in call.args]
        lead = None
        sliced: List[FactLike] = []
        for f in batched:
            if isinstance(f, Fact) and f.shape:
                lead = lead if lead is not None else f.shape[0]
                sliced.append(Fact(tuple(f.shape[1:]), f.dtype))
            else:
                sliced.append(UNKNOWN)
        out = self._interp_local(fn, sliced)

        def add_lead(f: FactLike) -> FactLike:
            if isinstance(f, tuple):
                return tuple(add_lead(e) for e in f)
            if isinstance(f, Fact) and f.shape is not None:
                return Fact((lead,) + tuple(f.shape), f.dtype)
            return UNKNOWN

        return add_lead(out)

    def _subscript(self, node: ast.Subscript) -> FactLike:
        base = self.eval(node.value)
        sl = node.slice
        if isinstance(base, tuple):
            if isinstance(sl, ast.Constant) and isinstance(sl.value, int):
                if -len(base) <= sl.value < len(base):
                    return base[sl.value]
            if isinstance(sl, ast.Slice):
                lo = sl.lower.value if isinstance(sl.lower, ast.Constant) \
                    else None
                hi = sl.upper.value if isinstance(sl.upper, ast.Constant) \
                    else None
                return base[lo:hi]
            return UNKNOWN
        if not isinstance(base, Fact) or base.shape is None:
            return UNKNOWN
        # x[:, idx] — the diag gather
        if isinstance(sl, ast.Tuple) and len(sl.elts) == 2:
            first, second = sl.elts
            if (isinstance(first, ast.Slice) and first.lower is None
                    and first.upper is None):
                idx = self.eval(second)
                if isinstance(idx, Fact) and idx.shape is not None:
                    return Fact((base.shape[0],) + tuple(idx.shape),
                                base.dtype)
            return UNKNOWN
        if isinstance(sl, ast.Constant) and isinstance(sl.value, int):
            return Fact(tuple(base.shape[1:]), base.dtype)
        idx = self.eval(sl)
        if isinstance(idx, Fact) and idx.shape is not None:
            return Fact(tuple(idx.shape) + tuple(base.shape[1:]),
                        base.dtype)
        return UNKNOWN


def interpret_function(fn: ast.FunctionDef,
                       inputs: Dict[str, Fact]) -> Interpreter:
    """Seed the interpreter with ``inputs`` (missing params stay UNKNOWN)
    and run the body; returns the interpreter with env/events/returns."""
    interp = Interpreter(dict(inputs))
    interp.run(fn)
    return interp


def dims_conform(actual, declared) -> bool:
    """Declared dim vs interpreted dim: ints must match, symbols must
    match by name, None (unknown) conforms to anything."""
    if actual is None or declared is None:
        return True
    return actual == declared


def fact_conforms(actual: FactLike, declared) -> Optional[str]:
    """None when ``actual`` (a Fact) proves or is compatible with the
    declared Role; else a human-readable mismatch description."""
    if not isinstance(actual, Fact):
        return None  # tuple-vs-role confusion: stay silent
    if actual.shape is not None:
        if len(actual.shape) != len(declared.shape):
            return (f"rank {len(actual.shape)} != declared "
                    f"{len(declared.shape)} for `{declared.name}`")
        for a, d in zip(actual.shape, declared.shape):
            if not dims_conform(a, d):
                return (f"dim {a!r} != declared {d!r} for "
                        f"`{declared.name}`")
    if actual.dtype is not None and actual.dtype != declared.dtype:
        return (f"dtype {actual.dtype} != declared {declared.dtype} "
                f"for `{declared.name}`")
    return None
