"""graftspec contract tables: the declarative side of the dataplane
analyzer (ANALYSIS.md §graftspec).

Three tables, one discipline each:

- :data:`JIT_SIGNATURES` — the symbolic (shape, dtype) contract of every
  donated/ranked jit executable: what goes in, what must come out.  The
  abstract interpreter (:mod:`rca_tpu.analysis.dataplane.absint`) walks
  each executable's body with the declared input facts and proves the
  returned expressions match the declared outputs — a dtype or rank
  drift inside the traced body is a ``shape-contract`` finding, not a
  runtime recompile.
- :data:`DTYPE_RULES` — where low-precision dtypes are legal
  (``engine/quantized.py`` and nowhere else) and where float64 staging
  is forbidden (the device staging modules: a float64 buffer doubles
  the upload and silently de-optimizes every downstream op).
- :data:`FETCH_BUDGETS` — the quantitative generalization of the
  resident-fetch allowlist: every audited fetch surface declares the
  named result roles it may move (symbolic shapes + dtypes) and a
  per-``device_get``-call byte budget as an expression over the shape
  symbols.  :func:`budget_violations` proves, over the whole symbol
  grid, that the declared roles always fit the declared budget; specsan
  (:mod:`rca_tpu.analysis.dataplane.specsan`) proves the OBSERVED
  fetches unify with the declared roles at runtime.

Shape expressions are tuples of ints (exact dims) and symbol names:
``k`` top-k width, ``n_pad`` padded service count (pow2 by contract),
``B`` padded batch lanes, ``C`` feature channels, ``E`` padded edge
count, ``m`` counterfactual rows, ``P`` blame-path hops.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, NamedTuple, Optional, Tuple, Union

Dim = Union[int, str]

#: numpy/JAX itemsizes for the dtypes the contracts speak
ITEMSIZE = {
    "float32": 4, "int32": 4, "float64": 8, "int64": 8,
    "bfloat16": 2, "float16": 2, "int8": 1, "uint8": 1, "bool": 1,
}

#: dtypes legal only inside the quantized kernel module
LOW_PRECISION_DTYPES = frozenset({
    "bfloat16", "float16", "int8",
    "float8_e4m3fn", "float8_e5m2", "float8_e4m3b11_fnuz",
})

#: sample values per symbol for the static budget-domination proof;
#: every combination is checked, so a budget expression that ever under-
#: declares its roles fails loudly at lint time
SYMBOL_GRID: Dict[str, Tuple[int, ...]] = {
    "k": (1, 5, 64, 256),
    "n_pad": (8, 256, 4096),
    "B": (1, 8, 64),
    "C": (1, 8, 32),
    "E": (1, 64, 4096),
    "m": (1, 8, 64),
    "P": (1, 4, 16),
}


class Role(NamedTuple):
    """One named result a fetch surface may move host-ward."""

    name: str
    shape: Tuple[Dim, ...]
    dtype: str


class Fact(NamedTuple):
    """An abstract (shape, dtype) value: ``None`` means unknown — the
    interpreter only ever *proves* with known facts, never guesses."""

    shape: Optional[Tuple[Optional[Dim], ...]]
    dtype: Optional[str]


UNKNOWN = Fact(None, None)


class FetchBudget(NamedTuple):
    roles: Tuple[Role, ...]
    #: per-device_get-call byte budget, an expression over SYMBOL_GRID
    #: symbols (evaluated with no builtins)
    budget: str
    #: the one documented deferred bulk seam (full_diagnostics) — budget
    #: still holds, but it is O(n_pad) by design, off the latency path
    bulk: bool = False


def _r(name: str, shape: Tuple[Dim, ...], dtype: str = "float32") -> Role:
    return Role(name, shape, dtype)


_TOPK_ROLES = (
    _r("diag", (4, "k")), _r("vals", ("k",)),
    _r("idx", ("k",), "int32"), _r("n_bad", (), "int32"),
)
_BATCH_ROLES = (
    _r("diag", ("B", 4, "k")), _r("vals", ("B", "k")),
    _r("idx", ("B", "k"), "int32"), _r("n_bad", (), "int32"),
)

#: (repo-relative path, function) -> FetchBudget.  MUST cover every
#: allowlisted function in residentfetch.FETCH_SURFACES (asserted by
#: coverage() and tests/test_dataplane.py) — an audited surface without
#: a byte budget is an unquantified contract.
FETCH_BUDGETS: Dict[Tuple[str, str], FetchBudget] = {
    # one-shot + resident analyze path: the [4,k] diagnostic gather, the
    # top-k pair, and the sanitize count — O(k) by construction
    ("rca_tpu/engine/runner.py", "timed_fetch"): FetchBudget(
        _TOPK_ROLES, "24*k + 8"),
    ("rca_tpu/engine/runner.py", "analyze_batch"): FetchBudget(
        _BATCH_ROLES, "24*B*k + 8"),
    # THE deferred bulk seam: the parked [4, n_pad] stack, fetched
    # lazily on first diagnostics use — budgeted, but bulk by design
    ("rca_tpu/engine/runner.py", "full_diagnostics"): FetchBudget(
        (_r("stacked", (4, "n_pad")),), "16*n_pad", bulk=True),
    ("rca_tpu/engine/resident.py", "_fetch_topk"): FetchBudget(
        _TOPK_ROLES, "24*k + 8"),
    # causelens: [5,k] diag + [m,k] counterfactual deltas + five [k,P]
    # path arrays + [k,C] saliency + the top-m pair — top-k/m-sized
    ("rca_tpu/engine/attribution.py", "compute_attribution"): FetchBudget(
        (
            _r("diag", (5, "k")), _r("deltas", ("m", "k")),
            _r("path_edge", ("k", "P"), "int32"),
            _r("path_term", ("k", "P")),
            _r("path_dst", ("k", "P"), "int32"),
            _r("path_hard", ("k", "P")), _r("path_up", ("k", "P")),
            _r("sal_cand", ("k", "C")), _r("sal_vals", ("m",)),
            _r("sal_idx", ("m",), "int32"),
        ),
        "4*(5*k + m*k + 5*k*P + k*C + 2*m) + 64"),
    ("rca_tpu/engine/sharded_runner.py", "analyze_batch"): FetchBudget(
        _BATCH_ROLES, "24*B*k + 8"),
    # streaming tick + serve paths: top-k pair + sanitize count only
    ("rca_tpu/engine/streaming.py", "fetch"): FetchBudget(
        (_r("vals", ("k",)), _r("idx", ("k",), "int32"),
         _r("n_bad", (), "int32")),
        "8*k + 8"),
    ("rca_tpu/parallel/streaming.py", "fetch"): FetchBudget(
        (_r("vals", ("k",)), _r("idx", ("k",), "int32"),
         _r("n_bad", (), "int32")),
        "8*k + 8"),
    ("rca_tpu/parallel/sharded.py", "_fetch_topk"): FetchBudget(
        (_r("diag", (4, "k")), _r("vals", ("k",)),
         _r("idx", ("k",), "int32")),
        "24*k + 8"),
    ("rca_tpu/serve/dispatcher.py", "fetch"): FetchBudget(
        _BATCH_ROLES, "24*B*k + 8"),
}

#: the device staging modules: pow2 padding, explicit-dtype staging, and
#: dummy-row COO fill are enforced here.  The sharded/parallel layouts
#: pad to data-parallel multiples and per-shard maxima by design, so
#: they are deliberately NOT in this scope (their shape stability is
#: pinned per graph, not per bucket).
DATAPLANE_MODULES = frozenset({
    "rca_tpu/engine/runner.py",
    "rca_tpu/engine/resident.py",
    "rca_tpu/engine/streaming.py",
    "rca_tpu/serve/dispatcher.py",
    "rca_tpu/engine/ell.py",
})

DTYPE_RULES = {
    # bf16/int8/f8 live ONLY in the quantized kernel module — anywhere
    # else an implicit f32<->low-precision promotion silently changes
    # ranking arithmetic (SCORE_EPS calibration is per-dtype)
    "low_precision_ok": frozenset({"rca_tpu/engine/quantized.py"}),
    # float64 staging doubles upload bytes and de-optimizes every
    # downstream op on TPU; forbidden in the staging modules
    "no_float64_staging": DATAPLANE_MODULES,
}

#: attribute-spelled callables that donate their argument 0 — the jit
#: wrapper is built at runtime (jax.jit(fn, donate_argnums=(0,))), so
#: module-local decorator extraction cannot see it; the donation-guard
#: rule treats a call through these exactly like a decorated donor
DONATED_ATTR_CALLABLES: Dict[Tuple[str, str], Tuple[int, ...]] = {
    ("rca_tpu/parallel/streaming.py", "self._fn"): (0,),
}

#: symbolic signatures of the ranked jit executables: input facts the
#: interpreter seeds the body with, and the output facts the returned
#: expressions must prove equal to.  Order matters — outputs match the
#: returned tuple positionally.
JIT_SIGNATURES: Dict[Tuple[str, str], Dict[str, object]] = {
    ("rca_tpu/engine/runner.py", "_propagate_ranked"): {
        "inputs": {
            "features": Fact(("n_pad", "C"), "float32"),
            "edges": Fact((2, "E"), "int32"),
            "anomaly_w": Fact(("C",), "float32"),
            "hard_w": Fact(("C",), "float32"),
        },
        "outputs": (
            _r("stacked", (4, "n_pad")), _r("diag", (4, "k")),
            _r("vals", ("k",)), _r("idx", ("k",), "int32"),
            _r("n_bad", (), "int32"),
        ),
    },
    ("rca_tpu/engine/resident.py", "_resident_delta_ranked"): {
        "inputs": {
            "features": Fact(("n_pad", "C"), "float32"),
            "idx": Fact(("u",), "int32"),
            "rows": Fact(("u", "C"), "float32"),
            "edges": Fact((2, "E"), "int32"),
            "anomaly_w": Fact(("C",), "float32"),
            "hard_w": Fact(("C",), "float32"),
        },
        "outputs": (
            _r("features", ("n_pad", "C")), _r("stacked", (4, "n_pad")),
            _r("diag", (4, "k")), _r("vals", ("k",)),
            _r("idx", ("k",), "int32"), _r("n_bad", (), "int32"),
        ),
    },
    ("rca_tpu/engine/streaming.py", "_flush_propagate_ranked"): {
        "inputs": {
            "features": Fact(("n_pad", "C"), "float32"),
            "idx": Fact(("u",), "int32"),
            "rows": Fact(("u", "C"), "float32"),
            "edges": Fact((2, "E"), "int32"),
            "anomaly_w": Fact(("C",), "float32"),
            "hard_w": Fact(("C",), "float32"),
        },
        "outputs": (
            _r("features", ("n_pad", "C")), _r("vals", ("k",)),
            _r("idx", ("k",), "int32"), _r("n_bad", (), "int32"),
        ),
    },
}


def role_bytes(role: Role, binding: Dict[str, int]) -> int:
    n = ITEMSIZE[role.dtype]
    for d in role.shape:
        n *= d if isinstance(d, int) else binding[d]
    return n


def eval_budget(expr: str, binding: Dict[str, int]) -> int:
    return int(eval(expr, {"__builtins__": {}}, dict(binding)))


def _symbols(budget: FetchBudget) -> List[str]:
    syms = {d for r in budget.roles for d in r.shape if isinstance(d, str)}
    syms |= {s for s in SYMBOL_GRID if s in budget.budget}
    return sorted(syms)


def budget_violations() -> List[dict]:
    """The static domination proof: for every surface and every grid
    binding, the declared roles' total bytes must fit the declared
    budget.  Non-empty return = the contract table itself is unsound."""
    out: List[dict] = []
    for (path, func), budget in sorted(FETCH_BUDGETS.items()):
        syms = _symbols(budget)
        grids = [SYMBOL_GRID[s] for s in syms]
        for values in itertools.product(*grids):
            binding = dict(zip(syms, values))
            total = sum(role_bytes(r, binding) for r in budget.roles)
            cap = eval_budget(budget.budget, binding)
            if total > cap:
                out.append({
                    "surface": f"{path}::{func}", "binding": binding,
                    "roles_bytes": total, "budget_bytes": cap,
                })
                break  # one witness per surface is enough
    return out


def coverage() -> List[str]:
    """Allowlisted fetch functions missing a FETCH_BUDGETS row (must be
    empty: an audited surface without a byte budget is unquantified)."""
    from rca_tpu.analysis.rules.residentfetch import FETCH_SURFACES

    missing = []
    for path, funcs in sorted(FETCH_SURFACES.items()):
        for func in sorted(funcs):
            if (path, func) not in FETCH_BUDGETS:
                missing.append(f"{path}::{func}")
    return missing


def role_name(leaf_name: str) -> str:
    """Normalize a fetched expression's terminal name to its role name:
    ``self._stacked_dev`` -> ``stacked``, ``handle.vals`` -> ``vals``,
    ``topi`` -> ``idx``."""
    name = leaf_name.lstrip("_")
    for suffix in ("_dev", "_h", "_b"):
        if name.endswith(suffix):
            name = name[: -len(suffix)]
    return {"topi": "idx"}.get(name, name)
