"""specsan: the runtime half of graftspec (``rca lint --specsan``).

The contract tables are only trustworthy if real executions agree with
them — the same discipline rsan applies to the static lock model
(:mod:`rca_tpu.analysis.concurrency.crosscheck`).  This module runs real
engine + serve work with ``jax.device_get`` instrumented and diffs every
observed host-ward transfer against :data:`~rca_tpu.analysis.dataplane.
contracts.FETCH_BUDGETS`:

- **role unification**: the leaves of each fetched pytree must unify
  with the surface's declared roles — same dtype, literal dims equal,
  symbolic dims bound consistently within the call.  A leaf no declared
  role can absorb is an undeclared transfer (``unmatched_roles``);
- **byte budget**: the call's total bytes must fit the surface's budget
  expression evaluated at the unified symbol binding (symbols the call
  does not bind fall back to the surface's most recent binding, else
  the grid maximum — sound because the static domination proof already
  covers the whole grid) (``over_budget``);
- **audit scope**: a ``device_get`` reached from an audited hot-path
  module but OUTSIDE its allowlisted functions is a fetch the static
  allowlist never blessed (``unaudited``) — the runtime twin of the
  ``resident-fetch`` rule.

Workload: a seeded resident session (one-shot + delta analyze, deferred
bulk diagnostics, causelens attribution, the batched lane, a streaming
tick) plus the serve selftest (the dispatcher's batched fetch under
concurrent submitters) — every budgeted surface the CPU backend can
reach.  ``capture()`` is also reusable standalone, e.g. around a
flight-recorder replay in tests.
"""

from __future__ import annotations

import contextlib
import os
import sys
import time
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from rca_tpu.analysis.core import repo_root
from rca_tpu.analysis.dataplane.contracts import (
    FETCH_BUDGETS,
    ITEMSIZE,
    SYMBOL_GRID,
    FetchBudget,
    Role,
)

_SELF = os.path.join("analysis", "dataplane", "specsan.py")


def _leaf_meta(leaf: Any) -> Tuple[Tuple[int, ...], str, int]:
    """(shape, dtype, nbytes) of one fetched pytree leaf, pre-transfer."""
    shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
    dtype = str(getattr(leaf, "dtype", "")) or type(leaf).__name__
    n = ITEMSIZE.get(dtype, getattr(getattr(leaf, "dtype", None),
                                    "itemsize", 0) or 0)
    for d in shape:
        n *= d
    return shape, dtype, n


def unify_roles(
    leaves: Sequence[Tuple[Tuple[int, ...], str]],
    roles: Sequence[Role],
) -> Optional[Dict[str, int]]:
    """Assign each observed leaf to a DISTINCT declared role with one
    consistent symbol binding, or None.  Backtracking: the role lists
    are tiny (<= 10), ambiguity only arises when two symbols share a
    value — any consistent assignment proves conformance."""

    def match(leaf, role: Role, binding: Dict[str, int]):
        shape, dtype = leaf
        if dtype != role.dtype or len(shape) != len(role.shape):
            return None
        new = dict(binding)
        for actual, d in zip(shape, role.shape):
            if isinstance(d, int):
                if actual != d:
                    return None
            elif new.setdefault(d, actual) != actual:
                return None
        return new

    used = [False] * len(roles)

    def solve(i: int, binding: Dict[str, int]):
        if i == len(leaves):
            return binding
        for j, role in enumerate(roles):
            if used[j]:
                continue
            new = match(leaves[i], role, binding)
            if new is not None:
                used[j] = True
                out = solve(i + 1, new)
                if out is not None:
                    return out
                used[j] = False
        return None

    return solve(0, {})


class SpecsanRecorder:
    """Every intercepted ``device_get``, judged against the contracts."""

    def __init__(self, root: str) -> None:
        self.root = root
        self.events: List[Dict[str, Any]] = []
        self.violations: List[Dict[str, Any]] = []
        #: surface -> most recent symbol binding (budget fallback)
        self.bindings: Dict[str, Dict[str, int]] = {}
        self._audited_files = {path for path, _ in FETCH_BUDGETS}

    def _surface_for_frame(self) -> Tuple[Optional[str], Optional[str]]:
        """(relpath, func) of the nearest rca_tpu frame below the patched
        call, skipping this module's own frames."""
        frame = sys._getframe(2)
        while frame is not None:
            filename = frame.f_code.co_filename
            if not filename.endswith(_SELF):
                try:
                    rel = os.path.relpath(filename, self.root)
                except ValueError:  # pragma: no cover - windows drives
                    rel = filename
                rel = rel.replace(os.sep, "/")
                if rel.startswith("rca_tpu/"):
                    return rel, frame.f_code.co_name
            frame = frame.f_back
        return None, None

    def record(self, tree: Any) -> None:
        import jax

        rel, func = self._surface_for_frame()
        if rel is None:
            return  # not our code (test harness, tooling)
        # host-native leaves (a Python int already fetched upstream, e.g.
        # n_bad on the replay path) pass through device_get untouched —
        # they are not transfers, so they are not judged against roles
        leaves = [_leaf_meta(x) for x in jax.tree_util.tree_leaves(tree)
                  if hasattr(x, "dtype")]
        nbytes = sum(n for _, _, n in leaves)
        event: Dict[str, Any] = {
            "surface": f"{rel}::{func}",
            "shapes": [list(s) for s, _, _ in leaves],
            "dtypes": [d for _, d, _ in leaves],
            "nbytes": nbytes,
        }
        budget = FETCH_BUDGETS.get((rel, func))
        if budget is None:
            if rel in self._audited_files:
                event["verdict"] = "unaudited"
                self.violations.append({
                    "kind": "unaudited", **event,
                })
            else:
                event["verdict"] = "unscoped"
            self.events.append(event)
            return
        self._judge(event, budget, leaves, nbytes)
        self.events.append(event)

    def _judge(self, event: Dict[str, Any], budget: FetchBudget,
               leaves, nbytes: int) -> None:
        from rca_tpu.analysis.dataplane.contracts import eval_budget

        surface = event["surface"]
        binding = unify_roles([(s, d) for s, d, _ in leaves], budget.roles)
        if binding is None:
            event["verdict"] = "unmatched_roles"
            self.violations.append({
                "kind": "unmatched_roles",
                "declared": [
                    f"{r.name}{list(r.shape)}:{r.dtype}"
                    for r in budget.roles
                ],
                **event,
            })
            return
        # symbols this call left unbound: the surface's last observed
        # value, else the grid max (the static proof covers the grid)
        merged = {s: max(v) for s, v in SYMBOL_GRID.items()}
        merged.update(self.bindings.get(surface, {}))
        merged.update(binding)
        self.bindings[surface] = merged
        cap = eval_budget(budget.budget, merged)
        event["binding"] = {
            k: v for k, v in binding.items() if k in SYMBOL_GRID
        }
        event["budget_bytes"] = cap
        if nbytes > cap:
            event["verdict"] = "over_budget"
            self.violations.append({"kind": "over_budget", **event})
        else:
            event["verdict"] = "ok"


@contextlib.contextmanager
def capture(root: Optional[str] = None) -> Iterator[SpecsanRecorder]:
    """Patch ``jax.device_get`` with the recording wrapper for the
    duration of the block.  The wrapper records metadata from the
    pre-transfer leaves and delegates — observed values are untouched,
    so captured workloads stay bit-identical."""
    import jax

    rec = SpecsanRecorder(root or repo_root())
    original = jax.device_get

    def wrapper(tree, *args, **kwargs):
        rec.record(tree)
        return original(tree, *args, **kwargs)

    jax.device_get = wrapper
    try:
        yield rec
    finally:
        jax.device_get = original


def _session_leg(rec: SpecsanRecorder, seed: int) -> Dict[str, Any]:
    """Seeded resident-engine pass over every budgeted engine surface."""
    import numpy as np

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine.runner import GraphEngine
    from rca_tpu.engine.streaming import make_streaming_session

    arrays = synthetic_cascade_arrays(20, seed=seed)
    names = arrays.names or [f"svc-{i}" for i in range(arrays.n)]
    engine = GraphEngine(resident=True)
    # one-shot timed path (timed_fetch), then a delta re-analysis of the
    # same graph so the resident session's _fetch_topk fires too
    first = engine.analyze_case(arrays, k=5, timed=True)
    arrays.features[0, 0] += 0.25
    second = engine.analyze_arrays(
        arrays.features, arrays.dep_src, arrays.dep_dst, names, k=5
    )
    first.full_diagnostics()  # the deferred bulk seam
    attribution = second.attribution()
    batch = engine.analyze_batch(
        np.stack([arrays.features] * 3),
        arrays.dep_src, arrays.dep_dst, names, k=5,
    )
    session = make_streaming_session(
        names, arrays.dep_src, arrays.dep_dst,
        num_features=arrays.features.shape[1], engine=engine, k=5,
    )
    session.update_rows(
        np.arange(3, dtype=np.int32),
        np.asarray(arrays.features[:3], np.float32),
    )
    tick = session.tick()
    return {
        "services": int(arrays.n),
        "one_shot_top1": (first.ranked[0].get("component")
                          if first.ranked else None),
        "attribution_ok": attribution is not None,
        "batch_lanes": len(batch),
        "tick_latency_ms": tick.get("latency_ms"),
    }


def _serve_leg(seed: int, n_requests: int) -> Dict[str, Any]:
    """The dispatcher's batched fetch under concurrent submitters."""
    from rca_tpu.serve.client import serve_selftest

    out = serve_selftest(
        n_requests=n_requests, seed=seed, submitters=2,
    )
    return {
        "requests": out.get("requests", n_requests),
        "ok": bool(out.get("ok", False)),
    }


def run_specsan(
    root: Optional[str] = None,
    seed: int = 0,
    n_requests: int = 8,
) -> Dict[str, Any]:
    """Drive both workload legs under capture and report the diff
    against the static contract model (shape mirrors
    :func:`~rca_tpu.analysis.concurrency.crosscheck.run_rsan_crosscheck`:
    a dict with ``ok`` plus the evidence)."""
    t0 = time.perf_counter()
    root = root or repo_root()
    with capture(root) as rec:
        session = _session_leg(rec, seed)
        serve = _serve_leg(seed, n_requests)

    per_surface: Dict[str, Dict[str, Any]] = {}
    for e in rec.events:
        s = per_surface.setdefault(e["surface"], {
            "calls": 0, "max_nbytes": 0, "verdicts": {},
        })
        s["calls"] += 1
        s["max_nbytes"] = max(s["max_nbytes"], e["nbytes"])
        v = e.get("verdict", "ok")
        s["verdicts"][v] = s["verdicts"].get(v, 0) + 1
        if "budget_bytes" in e:
            s["budget_bytes"] = e["budget_bytes"]

    budgeted = {
        f"{p}::{f}" for p, f in FETCH_BUDGETS
    }
    confirmed = sorted(s for s in per_surface if s in budgeted)
    ok = (
        not rec.violations
        and serve["ok"]
        and len(confirmed) >= 2  # both legs actually fetched something
    )
    return {
        "ok": bool(ok),
        "fetches": len(rec.events),
        "surfaces": per_surface,
        "surfaces_confirmed": confirmed,
        "surfaces_unexercised": sorted(budgeted - set(confirmed)),
        "violations": rec.violations,
        "bindings": rec.bindings,
        "session": session,
        "serve": serve,
        "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
    }


def confirm_findings(
    findings: List[Dict[str, Any]], report: Dict[str, Any],
) -> int:
    """Stamp ``dynamically_confirmed: true`` onto static findings whose
    file a specsan violation also implicates (the static rules and the
    runtime check agreeing on a file is the strongest signal the lint
    can emit).  Returns the number of findings stamped."""
    implicated = {
        v["surface"].split("::", 1)[0]
        for v in report.get("violations", ())
        if "surface" in v
    }
    n = 0
    for f in findings:
        if f.get("rule") in (
            "shape-contract", "dtype-discipline", "donation-guard",
            "resident-fetch",
        ) and f.get("path") in implicated:
            f["dynamically_confirmed"] = True
            n += 1
    return n
