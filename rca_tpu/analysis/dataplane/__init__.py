"""graftspec: static shape/dtype/donation contracts for the jit seams,
plus the specsan runtime cross-check (ANALYSIS.md §graftspec).

- :mod:`~rca_tpu.analysis.dataplane.contracts` — the declarative tables
  (jit signatures, dtype scopes, quantitative fetch budgets);
- :mod:`~rca_tpu.analysis.dataplane.absint` — the symbolic (shape,
  dtype) abstract interpreter the rules prove against;
- :mod:`~rca_tpu.analysis.dataplane.specsan` — the runtime half: run
  real engine + serve work with every ``device_get`` instrumented and
  diff the observed transfers against the static contract model
  (``rca lint --specsan``).
"""

from rca_tpu.analysis.dataplane import absint, contracts  # noqa: F401
from rca_tpu.analysis.dataplane.specsan import (  # noqa: F401
    capture,
    confirm_findings,
    run_specsan,
)
