"""``python -m rca_tpu.analysis`` / ``rca lint``: the graftlint CLI.

Exit-code contract (stable for CI):

- **0** — no findings (suppressed/baselined hits do not count); with
  ``--tracecheck``, additionally no second-call recompilation; with
  ``--rsan``, additionally a clean runtime cross-check (no order
  contradictions, no observed races, stress totals exact); with
  ``--specsan``, additionally every observed device fetch unifies with
  the graftspec contract tables; ``--all`` = all of the above in one
  run with a single JSON summary;
- **1** — findings (or a tracecheck recompile, or an rsan/specsan
  failure);
- **2** — usage or internal error (unknown rule, malformed baseline,
  ``--changed`` mixed with explicit paths).

``--changed`` lints only git-dirty files plus files whose content
differs from the cached fingerprint index under ``.graftlint/``
(refreshed by every default-scan run); interprocedural rules still see
the whole package, so per-file findings match a full run.

``--json`` emits one machine-readable JSON object on stdout and nothing
else — the same stdout hygiene contract as bench.py.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from rca_tpu.analysis.core import (
    all_rules,
    changed_files,
    default_baseline_path,
    discover_files,
    repo_root,
    run_lint,
    update_index,
    write_baseline,
)

EPILOG = """\
suppressions:
  # graftlint: disable=<rule>[,<rule>]    on the flagged line
  # graftlint: disable-file=<rule>        anywhere in the file
  (the rule name `all` disables every rule)

baseline:
  accepted legacy hits live in rca_tpu/analysis/baseline.json as content
  fingerprints; --write-baseline regenerates it from the current findings
  (policy: new-rule violations get FIXED, not baselined — see ANALYSIS.md)
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rca lint",
        description=("graftlint: JAX/TPU-aware static analysis — tracer "
                     "leaks, retrace hazards, RNG key reuse, lock and env "
                     "discipline, tick-sync and swallowed-fault contracts "
                     "(ANALYSIS.md)"),
        epilog=EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: the repo scan set)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (see --list-rules)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable JSON on stdout (sole output)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: rca_tpu/analysis/"
                   "baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined hits too")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("--tracecheck", action="store_true",
                   help="also jit the public engine entry points twice "
                   "and fail on second-call recompilation")
    p.add_argument("--changed", action="store_true",
                   help="incremental: lint only git-dirty files and "
                   "files whose content differs from the cached "
                   ".graftlint/ fingerprint index (interprocedural "
                   "rules still see the whole package, so findings "
                   "match a full run on the same files)")
    p.add_argument("--rsan", action="store_true",
                   help="also run the gravelock runtime cross-check: a "
                   "sanitized multi-thread stress whose observed lock "
                   "orders and access pairs must agree with the static "
                   "concurrency model (ANALYSIS.md)")
    p.add_argument("--specsan", action="store_true",
                   help="also run the graftspec runtime cross-check: a "
                   "seeded engine session + serve selftest with every "
                   "device_get instrumented; observed transfer shapes/"
                   "dtypes/bytes must unify with the FETCH_BUDGETS "
                   "contract tables (ANALYSIS.md §graftspec)")
    p.add_argument("--all", action="store_true", dest="run_all",
                   help="the full gate: default rules + --tracecheck + "
                   "--rsan + --specsan in one run, one summary, one "
                   "exit code")
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    root = args.root or repo_root()
    if args.run_all:
        args.tracecheck = args.rsan = args.specsan = True

    if args.list_rules:
        rules = all_rules()
        if args.as_json:
            print(json.dumps({
                name: {"summary": r.summary, "why": r.why}
                for name, r in rules.items()
            }, indent=2))
        else:
            for name, r in rules.items():
                print(f"{name:18s} {r.summary}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    paths = args.paths or None
    changed: Optional[List[str]] = None
    if args.changed:
        if paths:
            print("graftlint: error: --changed takes no explicit paths",
                  file=sys.stderr)
            return 2
        changed = changed_files(root)
        paths = changed
    try:
        if changed is not None and not changed:
            # nothing changed: vacuously clean, no scan at all
            from rca_tpu.analysis.core import LintResult

            result = LintResult(
                findings=[], suppressed=0, baselined=0,
                stale_baseline=[], files_scanned=0, wall_ms=0.0,
                per_rule_ms={},
            )
        else:
            result = run_lint(
                root=root, rules=rules,
                baseline_path=args.baseline,
                paths=paths,
                use_baseline=not args.no_baseline,
            )
    except (KeyError, FileNotFoundError, ValueError) as exc:
        print(f"graftlint: error: {exc}", file=sys.stderr)
        return 2
    # refresh the fingerprint index for whatever this run scanned (the
    # default set on full runs, the changed subset on --changed)
    if not args.paths:
        update_index(root, changed if changed is not None
                     else discover_files(root))

    if args.write_baseline:
        bpath = args.baseline or default_baseline_path(root)
        write_baseline(bpath, result.findings)
        if not args.as_json:
            print(f"graftlint: wrote {len(result.findings)} entr"
                  f"{'y' if len(result.findings) == 1 else 'ies'} to "
                  f"{bpath}")
        return 0

    trace = None
    if args.tracecheck:
        from rca_tpu.analysis.tracecheck import run_tracecheck

        trace = run_tracecheck()

    rsan_report = None
    if args.rsan:
        from rca_tpu.analysis.concurrency.crosscheck import (
            run_rsan_crosscheck,
        )

        rsan_report = run_rsan_crosscheck(root=root)

    specsan_report = None
    if args.specsan:
        from rca_tpu.analysis.dataplane.specsan import run_specsan
        from rca_tpu.config import env_int

        specsan_report = run_specsan(
            root=root,
            seed=env_int("RCA_SPECSAN_SEED", 0, 0, 2**31 - 1),
            n_requests=env_int("RCA_SPECSAN_REQUESTS", 8, 1, 10_000),
        )

    if args.as_json:
        out = result.to_dict()
        if changed is not None:
            out["changed_files"] = changed
        if trace is not None:
            out["tracecheck"] = trace
            out["clean"] = out["clean"] and trace["ok"]
        if rsan_report is not None:
            out["rsan"] = rsan_report
            out["clean"] = out["clean"] and rsan_report["ok"]
        if specsan_report is not None:
            from rca_tpu.analysis.dataplane.specsan import confirm_findings

            confirm_findings(out["findings"], specsan_report)
            out["specsan"] = specsan_report
            out["clean"] = out["clean"] and specsan_report["ok"]
        print(json.dumps(out))
        return 0 if out["clean"] else 1

    for f in result.findings:
        print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
        if f.snippet:
            print(f"  | {f.snippet}")
    for e in result.stale_baseline:
        print(f"graftlint: stale baseline entry {e['rule']} @ {e['path']} "
              f"({e['fingerprint']}) — the code it excused is gone; "
              "remove it (or --write-baseline)")
    counts = (f"{len(result.findings)} finding(s), "
              f"{result.suppressed} suppressed, "
              f"{result.baselined} baselined, "
              f"{result.files_scanned} files in "
              f"{result.wall_ms:.0f} ms")
    if changed is not None:
        print(f"graftlint: --changed scanned {len(changed)} file(s)")
    if trace is not None:
        for e in trace["entries"]:
            status = "ok" if e["ok"] else (
                f"RECOMPILED {e['recompiles']}x ({', '.join(e['recompiled'])})"
            )
            print(f"tracecheck: {e['entry']}: {status} "
                  f"[warmup {e['warmup_compiles']} compiles, "
                  f"{e['wall_ms']:.0f} ms]")
    if rsan_report is not None:
        r = rsan_report
        print(f"rsan: {'ok' if r['ok'] else 'FAILED'} "
              f"[{r['acquires']} acquires over "
              f"{len(r['locks_observed'])} locks "
              f"({len(r['multi_thread_locks'])} multi-thread), "
              f"{len(r['observed_edges'])} order edges, "
              f"{len(r['contradictions'])} contradiction(s), "
              f"{len(r['races_observed'])} race(s) observed, "
              f"{r['wall_ms']:.0f} ms]")
        for c in r["contradictions"]:
            print(f"rsan: ORDER CONTRADICTION {c['edge'][0]} -> "
                  f"{c['edge'][1]} (threads {', '.join(c['threads'])}; "
                  f"chain {' -> '.join(c['chain'])})")
        for race in r["races_observed"]:
            predicted = ("statically predicted" if
                         race["statically_predicted"]
                         else "NOT statically predicted — model gap")
            print(f"rsan: OBSERVED RACE {race['owner']}.{race['attr']} "
                  f"between {', '.join(race['threads'])} ({predicted})")
    if specsan_report is not None:
        s = specsan_report
        print(f"specsan: {'ok' if s['ok'] else 'FAILED'} "
              f"[{s['fetches']} fetches over "
              f"{len(s['surfaces_confirmed'])} budgeted surface(s), "
              f"{len(s['violations'])} violation(s), "
              f"serve {'ok' if s['serve']['ok'] else 'FAILED'}, "
              f"{s['wall_ms']:.0f} ms]")
        for v in s["violations"]:
            detail = {
                "unmatched_roles": "leaves do not unify with declared "
                                   "roles",
                "over_budget": "transfer exceeds the declared byte "
                               "budget",
                "unaudited": "device_get outside the allowlisted "
                             "functions of an audited module",
            }.get(v["kind"], v["kind"])
            print(f"specsan: {v['kind'].upper()} at {v['surface']}: "
                  f"{detail} (shapes {v['shapes']}, dtypes "
                  f"{v['dtypes']}, {v['nbytes']} B)")
    clean = (result.clean and (trace is None or trace["ok"])
             and (rsan_report is None or rsan_report["ok"])
             and (specsan_report is None or specsan_report["ok"]))
    print(f"graftlint: {'clean' if clean else 'FAILED'} ({counts})")
    return 0 if clean else 1


if __name__ == "__main__":
    sys.exit(main())
