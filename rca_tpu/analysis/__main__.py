"""``python -m rca_tpu.analysis`` / ``rca lint``: the graftlint CLI.

Exit-code contract (stable for CI):

- **0** — no findings (suppressed/baselined hits do not count); with
  ``--tracecheck``, additionally no second-call recompilation;
- **1** — findings (or a tracecheck recompile);
- **2** — usage or internal error (unknown rule, malformed baseline).

``--json`` emits one machine-readable JSON object on stdout and nothing
else — the same stdout hygiene contract as bench.py.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from rca_tpu.analysis.core import (
    all_rules,
    default_baseline_path,
    repo_root,
    run_lint,
    write_baseline,
)

EPILOG = """\
suppressions:
  # graftlint: disable=<rule>[,<rule>]    on the flagged line
  # graftlint: disable-file=<rule>        anywhere in the file
  (the rule name `all` disables every rule)

baseline:
  accepted legacy hits live in rca_tpu/analysis/baseline.json as content
  fingerprints; --write-baseline regenerates it from the current findings
  (policy: new-rule violations get FIXED, not baselined — see ANALYSIS.md)
"""


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rca lint",
        description=("graftlint: JAX/TPU-aware static analysis — tracer "
                     "leaks, retrace hazards, RNG key reuse, lock and env "
                     "discipline, tick-sync and swallowed-fault contracts "
                     "(ANALYSIS.md)"),
        epilog=EPILOG,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: the repo scan set)")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset (see --list-rules)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable JSON on stdout (sole output)")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: rca_tpu/analysis/"
                   "baseline.json)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report baselined hits too")
    p.add_argument("--write-baseline", action="store_true",
                   help="accept all current findings into the baseline")
    p.add_argument("--list-rules", action="store_true",
                   help="print the registered rules and exit")
    p.add_argument("--tracecheck", action="store_true",
                   help="also jit the public engine entry points twice "
                   "and fail on second-call recompilation")
    p.add_argument("--root", default=None, help=argparse.SUPPRESS)
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    root = args.root or repo_root()

    if args.list_rules:
        rules = all_rules()
        if args.as_json:
            print(json.dumps({
                name: {"summary": r.summary, "why": r.why}
                for name, r in rules.items()
            }, indent=2))
        else:
            for name, r in rules.items():
                print(f"{name:18s} {r.summary}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    try:
        result = run_lint(
            root=root, rules=rules,
            baseline_path=args.baseline,
            paths=args.paths or None,
            use_baseline=not args.no_baseline,
        )
    except (KeyError, FileNotFoundError, ValueError) as exc:
        print(f"graftlint: error: {exc}", file=sys.stderr)
        return 2

    if args.write_baseline:
        bpath = args.baseline or default_baseline_path(root)
        write_baseline(bpath, result.findings)
        if not args.as_json:
            print(f"graftlint: wrote {len(result.findings)} entr"
                  f"{'y' if len(result.findings) == 1 else 'ies'} to "
                  f"{bpath}")
        return 0

    trace = None
    if args.tracecheck:
        from rca_tpu.analysis.tracecheck import run_tracecheck

        trace = run_tracecheck()

    if args.as_json:
        out = result.to_dict()
        if trace is not None:
            out["tracecheck"] = trace
            out["clean"] = out["clean"] and trace["ok"]
        print(json.dumps(out))
        return 0 if out["clean"] else 1

    for f in result.findings:
        print(f"{f.path}:{f.line}: [{f.rule}] {f.message}")
        if f.snippet:
            print(f"  | {f.snippet}")
    for e in result.stale_baseline:
        print(f"graftlint: stale baseline entry {e['rule']} @ {e['path']} "
              f"({e['fingerprint']}) — the code it excused is gone; "
              "remove it (or --write-baseline)")
    counts = (f"{len(result.findings)} finding(s), "
              f"{result.suppressed} suppressed, "
              f"{result.baselined} baselined, "
              f"{result.files_scanned} files in "
              f"{result.wall_ms:.0f} ms")
    if trace is not None:
        for e in trace["entries"]:
            status = "ok" if e["ok"] else (
                f"RECOMPILED {e['recompiles']}x ({', '.join(e['recompiled'])})"
            )
            print(f"tracecheck: {e['entry']}: {status} "
                  f"[warmup {e['warmup_compiles']} compiles, "
                  f"{e['wall_ms']:.0f} ms]")
    clean = result.clean and (trace is None or trace["ok"])
    print(f"graftlint: {'clean' if clean else 'FAILED'} ({counts})")
    return 0 if clean else 1


if __name__ == "__main__":
    sys.exit(main())
