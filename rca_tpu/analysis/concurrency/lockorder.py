"""Lock-order graph + deadlock-cycle findings.

Every traversal chain that enters lock B while (interprocedurally)
holding lock A contributes the directed edge ``A -> B``.  A cycle in
that graph is a potential deadlock: two threads walking the cycle from
different entry lock in a state where each holds what the other wants.
The finding carries the full acquire chains — for each edge, where the
outer lock was taken and where the inner acquisition nested under it
(function-qualified, so a cross-call inversion reads as the two call
paths that collide, not just two lock names).

Single-threaded cycles are still reported: a lock order is a global
invariant, and the chain that today only ever runs on one thread is one
``spawn()`` away from not being one (the serve-pool roadmap item is
exactly that change).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from rca_tpu.analysis.concurrency.model import (
    ConcurrencyModel,
    OrderEdge,
)


@dataclasses.dataclass
class CycleFinding:
    locks: Tuple[str, ...]            # cycle members, canonical rotation
    edges: List[OrderEdge]            # one representative edge per hop
    relpath: str                      # attribution: first edge's inner site
    lineno: int
    func: str

    def message(self) -> str:
        hops = []
        for e in self.edges:
            of, ol = e.outer_site
            inf, inl = e.inner_site
            hops.append(
                f"{e.outer} -> {e.inner} "
                f"(held at {_short(of)}:{ol}, nested at {_short(inf)}:{inl}"
                f", root {e.root})"
            )
        chain = "; ".join(hops)
        return (
            "lock-order cycle "
            + " -> ".join(self.locks + (self.locks[0],))
            + " — two threads entering from different edges deadlock; "
            + "acquire chains: " + chain
        )


def _short(qual: str) -> str:
    # "rca_tpu/serve/loop.py::ServeLoop._run" -> "loop.py::ServeLoop._run"
    path, _, fn = qual.partition("::")
    return f"{path.rsplit('/', 1)[-1]}::{fn}" if fn else path


def _cycles(graph: Dict[str, set]) -> List[Tuple[str, ...]]:
    """Elementary cycles via DFS from each node (graphs here are tiny —
    a handful of locks — so simplicity beats Johnson's algorithm)."""
    out: List[Tuple[str, ...]] = []
    seen: set = set()
    nodes = sorted(graph)
    for start in nodes:
        stack: List[Tuple[str, Tuple[str, ...]]] = [(start, (start,))]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) >= 1:
                    # canonical rotation: start from the smallest member
                    i = path.index(min(path))
                    canon = path[i:] + path[:i]
                    if canon not in seen:
                        seen.add(canon)
                        out.append(canon)
                elif nxt not in path and nxt > start:
                    # only explore nodes > start: each cycle found once,
                    # from its smallest member
                    if len(path) < 8:
                        stack.append((nxt, path + (nxt,)))
    return sorted(out)


def analyze_lock_order(model: ConcurrencyModel) -> List[CycleFinding]:
    cached = getattr(model, "_order_findings", None)
    if cached is not None:
        return cached
    graph: Dict[str, set] = {}
    best_edge: Dict[Tuple[str, str], OrderEdge] = {}
    for e in model.order_edges:
        graph.setdefault(e.outer, set()).add(e.inner)
        graph.setdefault(e.inner, set())
        best_edge.setdefault((e.outer, e.inner), e)
    findings: List[CycleFinding] = []
    for cyc in _cycles(graph):
        edges = [
            best_edge[(cyc[i], cyc[(i + 1) % len(cyc)])]
            for i in range(len(cyc))
        ]
        first = edges[0]
        findings.append(CycleFinding(
            locks=cyc, edges=edges,
            relpath=first.inner_site[0].split("::")[0],
            lineno=first.inner_site[1],
            func=first.inner_site[0].split("::")[-1].split(".")[-1],
        ))
    model._order_findings = findings  # one analysis per model build
    return findings
