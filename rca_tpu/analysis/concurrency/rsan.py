"""rsan: the runtime lock sanitizer (gravelock's dynamic half).

When enabled (``RCA_RSAN=1`` or :func:`enable`), the constructors in
:mod:`rca_tpu.util.threads` return :class:`SanitizedLock` /
:class:`SanitizedCondition` shims instead of bare ``threading``
primitives.  A shim behaves exactly like the primitive it wraps and
additionally records, into one bounded process-wide :class:`RsanRecorder`:

- **acquisition-order edges**: acquiring lock B while holding lock A
  records the edge ``A -> B`` (per thread, via a thread-local held
  stack).  Locks are identified by the ``"Class.attr"`` names their
  construction sites pass, which are the SAME identities the static
  model uses — so :mod:`crosscheck` can diff observed orders against the
  static lock-order graph directly;
- **same-attribute access pairs**: :func:`note_access` stamps an access
  to ``owner.attr`` with the caller's thread and currently-held lock
  set.  Two writes from different threads with disjoint held sets are an
  *observed* race (the Eraser lockset discipline, run live) — the
  concurrency stress tests and the chaos soak run with rsan on so the
  static findings are validated against real executions.

Zero-cost when off: ``util.threads`` returns bare primitives, nothing
here is imported, and no per-acquire work exists anywhere.  The recorder
itself uses a raw ``threading.Lock`` — the sanitizer cannot sanitize
itself (``thread-discipline`` exempts this module).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, FrozenSet, List, Optional, Set, Tuple

#: bounded-state caps: rsan runs inside stress tests and soaks, never
#: accumulates beyond these whatever the workload does
MAX_EDGES = 4096
MAX_ACCESS_KEYS = 1024
MAX_SAMPLES_PER_KEY = 128

_ENABLED: Optional[bool] = None
_STATE_LOCK = threading.Lock()


def enabled() -> bool:
    """Is the sanitizer on?  Lazily seeded from ``RCA_RSAN`` on first
    ask; :func:`enable`/:func:`disable` override for tests."""
    global _ENABLED
    if _ENABLED is None:
        with _STATE_LOCK:
            if _ENABLED is None:
                from rca_tpu.config import rsan_enabled

                _ENABLED = rsan_enabled()
    return _ENABLED


def enable() -> None:
    global _ENABLED
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


class _Held(threading.local):
    def __init__(self) -> None:
        self.stack: List[str] = []


_HELD = _Held()


def held_locks() -> Tuple[str, ...]:
    """Names of the sanitized locks the CURRENT thread holds, outermost
    first (other threads' holds are invisible by design)."""
    return tuple(_HELD.stack)


class RsanRecorder:
    """Bounded process-wide record of observed orders and access pairs."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (outer, inner) -> {count, threads, chain} ; chain is the held
        # stack at first observation (the acquire chain evidence)
        self._edges: Dict[Tuple[str, str], Dict[str, Any]] = {}
        # lock name -> thread names that ever acquired it
        self._lock_threads: Dict[str, Set[str]] = {}
        # (owner, attr) -> [(thread, kind, frozenset(held))]
        self._accesses: Dict[
            Tuple[str, str], List[Tuple[str, str, FrozenSet[str]]]
        ] = {}
        self.acquires = 0

    # -- recording (called from the shims) ----------------------------------
    def note_acquire(self, name: str, held: List[str]) -> None:
        thread = threading.current_thread().name
        with self._lock:
            self.acquires += 1
            self._lock_threads.setdefault(name, set()).add(thread)
            for outer in held:
                if outer == name:
                    continue  # reentrant re-acquire, not an order edge
                key = (outer, name)
                rec = self._edges.get(key)
                if rec is not None:
                    rec["count"] += 1
                    rec["threads"].add(thread)
                elif len(self._edges) < MAX_EDGES:
                    self._edges[key] = {
                        "count": 1,
                        "threads": {thread},
                        "chain": list(held) + [name],
                    }

    def note_access(self, owner: str, attr: str, kind: str,
                    held: List[str]) -> None:
        thread = threading.current_thread().name
        key = (owner, attr)
        with self._lock:
            samples = self._accesses.get(key)
            if samples is None:
                if len(self._accesses) >= MAX_ACCESS_KEYS:
                    return
                samples = self._accesses[key] = []
            if len(samples) < MAX_SAMPLES_PER_KEY:
                samples.append((thread, kind, frozenset(held)))

    # -- analysis ------------------------------------------------------------
    def order_edges(self) -> Dict[Tuple[str, str], Dict[str, Any]]:
        with self._lock:
            return {
                k: {"count": v["count"], "threads": sorted(v["threads"]),
                    "chain": list(v["chain"])}
                for k, v in self._edges.items()
            }

    def lock_threads(self) -> Dict[str, List[str]]:
        with self._lock:
            return {k: sorted(v) for k, v in self._lock_threads.items()}

    def races_observed(self) -> List[Dict[str, Any]]:
        """Eraser over the recorded access pairs: two accesses to the
        same ``owner.attr`` from different threads, at least one a write,
        with DISJOINT held-lock sets."""
        with self._lock:
            items = {k: list(v) for k, v in self._accesses.items()}
        out: List[Dict[str, Any]] = []
        for (owner, attr), samples in sorted(items.items()):
            for i, (t1, k1, h1) in enumerate(samples):
                hit = None
                for t2, k2, h2 in samples[i + 1:]:
                    if t1 == t2:
                        continue
                    if "write" not in (k1, k2):
                        continue
                    if h1 & h2:
                        continue
                    hit = {
                        "owner": owner, "attr": attr,
                        "threads": sorted((t1, t2)),
                        "locksets": [sorted(h1), sorted(h2)],
                    }
                    break
                if hit is not None:
                    out.append(hit)
                    break
        return out

    def reset(self) -> None:
        with self._lock:
            self._edges.clear()
            self._lock_threads.clear()
            self._accesses.clear()
            self.acquires = 0


RSAN = RsanRecorder()


def note_access(owner: str, attr: str, kind: str = "write") -> None:
    """Stamp one shared-state access with the caller's thread + held
    sanitized locks.  No-op when the sanitizer is off — safe to call from
    stress harnesses unconditionally."""
    if enabled():
        RSAN.note_access(owner, attr, kind, _HELD.stack)


class SanitizedLock:
    """Drop-in ``threading.Lock``/``RLock`` that records acquisitions."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self._reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            if enabled():
                RSAN.note_acquire(self.name, _HELD.stack)
            _HELD.stack.append(self.name)
        return ok

    def release(self) -> None:
        # pop the innermost matching hold (reentrant locks stack dupes)
        stack = _HELD.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"SanitizedLock({self.name!r})"


class SanitizedCondition:
    """Drop-in ``threading.Condition`` over a :class:`SanitizedLock`.

    ``wait()`` releases the lock for the duration of the park and
    re-records the re-acquisition — exactly the window where a second
    thread's acquires interleave, which is what the order record needs to
    see."""

    def __init__(self, name: str, lock: Optional[Any] = None):
        self.name = name
        self._cond = threading.Condition(
            getattr(lock, "_lock", lock)  # unwrap a SanitizedLock mutex
        )

    def acquire(self, *args: Any) -> bool:
        ok = self._cond.acquire(*args)
        if ok:
            if enabled():
                RSAN.note_acquire(self.name, _HELD.stack)
            _HELD.stack.append(self.name)
        return ok

    def release(self) -> None:
        stack = _HELD.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._cond.release()

    def __enter__(self) -> "SanitizedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        stack = _HELD.stack
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        try:
            return self._cond.wait(timeout)
        finally:
            if enabled():
                RSAN.note_acquire(self.name, _HELD.stack)
            _HELD.stack.append(self.name)

    def wait_for(self, predicate: Any, timeout: Optional[float] = None):
        # mirrors threading.Condition.wait_for over OUR wait (so the
        # held-stack bookkeeping stays balanced)
        import time as _time

        endtime = None
        result = predicate()
        while not result:
            if timeout is not None:
                if endtime is None:
                    endtime = _time.monotonic() + timeout
                waittime = endtime - _time.monotonic()
                if waittime <= 0:
                    break
                self.wait(waittime)
            else:
                self.wait(None)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()

    def __repr__(self) -> str:
        return f"SanitizedCondition({self.name!r})"
