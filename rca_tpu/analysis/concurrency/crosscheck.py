"""The rsan <-> static-model cross-check (``rca lint --rsan``).

A static concurrency model is only trustworthy if real executions agree
with it — the same discipline the flight recorder applies to the engine
(REPLAY.md: recorded, checkable execution).  This module drives real
multi-threaded work with the sanitizer on and fails the lint when the
two halves disagree:

- **order contradiction**: an observed acquisition edge ``A -> B`` such
  that ``B`` can already reach ``A`` through the combined (static +
  observed) order graph — the runtime just walked one half of a
  deadlock cycle the static graph didn't bless;
- **observed race**: two same-attribute writes from different threads
  with disjoint held-lock sets (:meth:`RsanRecorder.races_observed`).
  Each is matched against the static race findings: a predicted one
  confirms the model, an unpredicted one means the model missed a root
  or an alias — both fail the check, with the attribution in the
  report;
- **coverage floor**: the stress must actually exercise concurrency —
  every hot lock it touches must be acquired from >=2 distinct threads,
  otherwise the "clean" verdict would be vacuous.

The built-in workload (:func:`queue_metrics_stress`) is the serve
scheduler's admission path under an 8-thread barrage — the same shape
tier-1's ``RCA_RSAN=1`` stress test runs — plus, when ``soak_ticks`` is
set, a short seeded chaos soak so the watch/streaming lock family gets
exercised too.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Set, Tuple

from rca_tpu.analysis.concurrency import model_for
from rca_tpu.analysis.concurrency import rsan
from rca_tpu.analysis.concurrency.races import analyze_races
from rca_tpu.analysis.core import repo_root


def _reaches(graph: Dict[str, Set[str]], src: str, dst: str) -> bool:
    seen: Set[str] = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(graph.get(n, ()))
    return False


def order_contradictions(
    static_edges: Set[Tuple[str, str]],
    observed: Dict[Tuple[str, str], Dict[str, Any]],
) -> List[Dict[str, Any]]:
    """Observed edges that close a cycle in the combined order graph."""
    graph: Dict[str, Set[str]] = {}
    for a, b in set(static_edges) | set(observed):
        graph.setdefault(a, set()).add(b)
    out = []
    for (a, b), rec in sorted(observed.items()):
        if _reaches(graph, b, a):
            out.append({
                "edge": [a, b],
                "chain": rec["chain"],
                "threads": rec["threads"],
                "count": rec["count"],
            })
    return out


def queue_metrics_stress(
    seed: int = 0,
    threads: int = 8,
    requests_per_thread: int = 24,
) -> Dict[str, Any]:
    """Seeded multi-thread barrage over the serve admission path:
    ``threads`` submitters race a drainer on one :class:`RequestQueue`
    (submit / pop / shed / kick) while every completion path hammers one
    :class:`ServeMetrics`.  Constructed AFTER the sanitizer is enabled,
    so every lock involved is a recording shim.  Returns exact expected
    vs. observed counter totals — a lost update is a hard failure, not a
    flake."""
    import numpy as np

    from rca_tpu.serve.metrics import ServeMetrics
    from rca_tpu.serve.queue import RequestQueue
    from rca_tpu.serve.request import ServeRequest
    from rca_tpu.util.threads import make_lock, spawn

    rng = np.random.default_rng(seed)
    feats = rng.random((4, 3)).astype(np.float32)
    src = np.array([0, 1, 2], np.int32)
    dst = np.array([1, 2, 3], np.int32)
    queue = RequestQueue(cap=threads * requests_per_thread + 8)
    metrics = ServeMetrics()
    total = threads * requests_per_thread
    # a harness-owned guarded counter exercises the access-pair record
    # the honest way: every access holds the lock, so the Eraser check
    # sees a non-empty lockset intersection and stays quiet
    counter_lock = make_lock("StressCounter._lock")
    counter = {"submitted": 0}

    def submitter(w: int) -> None:
        for i in range(requests_per_thread):
            req = ServeRequest(
                tenant=f"t{w % 3}", features=feats, dep_src=src,
                dep_dst=dst,
                # a sprinkle of already-expired deadlines exercises the
                # shed path under contention
                deadline_s=-1.0 if (w + i) % 7 == 0 else None,
            )
            queue.submit(req)
            metrics.submitted(req.tenant, len(queue))
            with counter_lock:
                rsan.note_access("StressCounter", "submitted")
                counter["submitted"] += 1

    drained = []
    stop = []

    def drainer() -> None:
        while not stop or len(drained) < total:
            for req in queue.shed_expired(time.monotonic()):
                metrics.shed(req.tenant)
                drained.append(req)
            req = queue.pop()
            if req is None:
                if stop:
                    break
                queue.wait_for_work(0.001)
                continue
            metrics.answered(req.tenant, 0.1)
            drained.append(req)
        # shutdown drain: everything still queued errors out, nothing
        # is left parked (the ServeLoop._shutdown_drain contract)
        while True:
            req = queue.pop()
            if req is None:
                break
            metrics.errors(req.tenant)
            drained.append(req)

    workers = [
        spawn(submitter, name=f"rsan-stress-{w}", args=(w,))
        for w in range(threads)
    ]
    drain_thread = spawn(drainer, name="rsan-stress-drain")
    for t in workers:
        t.join(30.0)
    stop.append(True)
    queue.kick()
    drain_thread.join(30.0)

    summary = metrics.summary()
    counted = min(
        sum(t["submitted"] for t in summary["tenants"].values()),
        counter["submitted"],
    )
    completed = sum(
        t["answered"] + t["shed"] + t["errors"]
        for t in summary["tenants"].values()
    )
    return {
        "requests": total,
        "submitted_counted": counted,
        "completed_counted": completed,
        "drained": len(drained),
        "queue_leftover": len(queue),
        "ok": (
            counted == total and len(drained) == total
            and completed == total and len(queue) == 0
        ),
    }


def run_rsan_crosscheck(
    root: Optional[str] = None,
    seed: int = 0,
    soak_ticks: int = 0,
) -> Dict[str, Any]:
    """Run the sanitized workload and diff it against the static model.
    ``soak_ticks > 0`` adds a seeded chaos soak (imports the engine —
    noticeably heavier than the pure-scheduler stress)."""
    t0 = time.perf_counter()
    root = root or repo_root()
    model = model_for(root)
    static_edges = model.static_order_edges()
    static_race_keys = {
        (f.cls, f.attr) for f in analyze_races(model)
    }

    was_enabled = rsan.enabled()
    rsan.enable()
    rsan.RSAN.reset()
    try:
        stress = queue_metrics_stress(seed=seed)
        soak = None
        if soak_ticks > 0:
            from rca_tpu.cluster.generator import synthetic_cascade_world
            from rca_tpu.resilience.chaos import run_chaos_soak

            soak_summary = run_chaos_soak(
                lambda: synthetic_cascade_world(
                    20, n_roots=1, seed=seed + 1,
                ),
                "synthetic", seed=seed + 1, ticks=soak_ticks,
                replay_check=False,
            )
            soak = {
                "ticks": soak_summary["ticks"],
                "uncaught_exceptions":
                    soak_summary["uncaught_exceptions"],
                "ok": soak_summary["uncaught_exceptions"] == 0,
            }
    finally:
        if not was_enabled:
            rsan.disable()

    observed = rsan.RSAN.order_edges()
    lock_threads = rsan.RSAN.lock_threads()
    contradictions = order_contradictions(static_edges, observed)
    races = rsan.RSAN.races_observed()
    for r in races:
        r["statically_predicted"] = (
            (r["owner"], r["attr"]) in static_race_keys
        )
    multi_thread_locks = [
        k for k, v in lock_threads.items() if len(v) >= 2
    ]
    coverage_ok = len(multi_thread_locks) >= 1
    ok = (
        stress["ok"]
        and coverage_ok
        and not contradictions
        and not races
        and (soak is None or soak["ok"])
    )
    return {
        "ok": bool(ok),
        "acquires": rsan.RSAN.acquires,
        "locks_observed": sorted(lock_threads),
        "multi_thread_locks": sorted(multi_thread_locks),
        "observed_edges": [
            list(k) for k in sorted(observed)
        ],
        "static_edges": sorted(list(e) for e in static_edges),
        "contradictions": contradictions,
        "races_observed": races,
        "static_race_findings": sorted(
            f"{c}.{a}" for c, a in static_race_keys
        ),
        "stress": stress,
        "soak": soak,
        "wall_ms": round((time.perf_counter() - t0) * 1e3, 1),
    }
