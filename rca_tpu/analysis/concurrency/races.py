"""Guarded-by inference + race findings over the concurrency model.

The discipline is Eraser's lockset algorithm run statically over the
model's traversal output: every write to ``Class.attr`` is observed as
(thread root, receiver context, locks held on every path).  Two
observations RACE when

- they come from different thread roots (or the same root spawned more
  than once — two copies of one entry point are just as concurrent),
- their receiver contexts can name the same object
  (:meth:`model.Context.pairs_with` — the instance-identity
  approximation), and
- their lock sets are DISJOINT: no common lock orders the two writes.

For attributes that are locked *somewhere*, the **dominant guard** (the
most frequently held lock across that attribute's write sites) names the
convention the offending site broke; attributes never locked anywhere
are flagged only on read-modify-write shapes (``+=``, container
mutation) — an unshared-lock plain assignment is publication, not a lost
update.  A separate deterministic check flags **class attributes**
mutated inside methods: a per-instance lock cannot guard class-shared
state, whatever the roots (the shape behind the watch-pump token
counter bug this analyzer's first run over the repo surfaced).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Dict, List, Optional, Tuple

from rca_tpu.analysis.concurrency.model import (
    ConcurrencyModel,
    Observation,
)


@dataclasses.dataclass
class RaceFinding:
    relpath: str
    lineno: int
    func: str             # enclosing function qual (for allowlists)
    cls: str
    attr: str
    roots: Tuple[str, ...]
    dominant: Optional[str]
    held: Tuple[str, ...]  # locks held at the flagged site

    def message(self) -> str:
        roots = ", ".join(self.roots)
        held = (" while holding only {" + ", ".join(self.held) + "}"
                if self.held else " with no lock held")
        if self.dominant:
            return (
                f"`self.{self.attr}` is written from threads [{roots}]"
                f"{held}, but its dominant guard is `{self.dominant}` — "
                "racing the locked writers loses updates silently"
            )
        return (
            f"read-modify-write of `self.{self.attr}` from threads "
            f"[{roots}] with no common lock — concurrent `+=`/mutation "
            "interleaves and drops updates"
        )


@dataclasses.dataclass
class ClassAttrFinding:
    relpath: str
    lineno: int
    func: str
    cls: str
    attr: str
    under_lock: bool

    def message(self) -> str:
        tail = (
            "a per-instance lock cannot guard class-shared state"
            if self.under_lock else
            "class-shared state mutated with no guard at all"
        )
        return (
            f"`{self.cls}.{self.attr}` (a CLASS attribute) is mutated "
            f"inside a method — {tail}; use a module-level lock or an "
            "atomic counter (itertools.count)"
        )


def _conflicts(a: Observation, b: Observation) -> bool:
    if a.root.root_id == b.root.root_id and not a.root.multi:
        return False
    if not a.ctx.pairs_with(b.ctx):
        return False
    if a.locks & b.locks:
        return False
    return True


def analyze_races(model: ConcurrencyModel) -> List[RaceFinding]:
    cached = getattr(model, "_race_findings", None)
    if cached is not None:
        return cached
    findings: List[RaceFinding] = []
    for (cls, attr), obs in sorted(model.observations.items()):
        # dominant guard: the most frequently held lock across this
        # attribute's distinct write SITES (not chains, so a hot path
        # does not outvote the convention)
        site_locks: Dict[Tuple[str, int], set] = {}
        for o in obs:
            key = (o.site.func, o.site.lineno)
            cur = site_locks.get(key)
            site_locks[key] = (set(o.locks) if cur is None
                               else cur & set(o.locks))
        counts = collections.Counter()
        for locks in site_locks.values():
            counts.update(locks)
        dominant = counts.most_common(1)[0][0] if counts else None

        # conflicting observation pairs -> flag the unguarded side(s)
        flagged: Dict[Tuple[str, int], RaceFinding] = {}
        for i, a in enumerate(obs):
            for b in obs[i:]:
                if a is b and not a.root.multi:
                    continue
                if not _conflicts(a, b):
                    continue
                pair_roots = tuple(sorted(
                    {a.root.root_id, b.root.root_id}
                ))
                for o in (a, b):
                    unguarded = (
                        dominant is not None and dominant not in o.locks
                    ) or (
                        dominant is None
                        and o.site.kind in ("augassign", "mutcall")
                    )
                    if not unguarded:
                        continue
                    key = (o.site.func, o.site.lineno)
                    if key in flagged:
                        flagged[key].roots = tuple(sorted(
                            set(flagged[key].roots) | set(pair_roots)
                        ))
                        continue
                    flagged[key] = RaceFinding(
                        relpath=o.site.func.split("::")[0],
                        lineno=o.site.lineno,
                        func=o.site.func.split("::")[-1].split(".")[-1],
                        cls=cls, attr=attr, roots=pair_roots,
                        dominant=dominant,
                        held=tuple(sorted(o.locks)),
                    )
        findings.extend(flagged.values())
    findings.sort(key=lambda f: (f.relpath, f.lineno, f.attr))
    model._race_findings = findings  # one analysis per model build
    return findings


def analyze_class_attrs(model: ConcurrencyModel) -> List[ClassAttrFinding]:
    out = []
    for w in model.class_attr_writes:
        out.append(ClassAttrFinding(
            relpath=w.func.split("::")[0], lineno=w.lineno,
            func=w.func.split("::")[-1].split(".")[-1],
            cls=w.cls, attr=w.attr, under_lock=bool(w.locks),
        ))
    out.sort(key=lambda f: (f.relpath, f.lineno))
    return out
