"""gravelock: interprocedural race & deadlock analysis + runtime rsan.

The static half builds one whole-package concurrency model per lint run
(:mod:`model`): thread roots and their reachable functions, a call graph
with held-lock propagation, per-class guarded-by inference (:mod:`races`)
and the interprocedural lock-order graph (:mod:`lockorder`).  Findings
surface through the graftlint rules ``race-guard`` and ``lock-order``
(rca_tpu/analysis/rules/gravelock.py) with the normal suppression /
baseline / exit-code contract.

The dynamic half (:mod:`rsan`) is a lock sanitizer the
:mod:`rca_tpu.util.threads` constructors route through when enabled
(``RCA_RSAN=1``): it records real acquisition orders and same-attribute
access pairs, and :mod:`crosscheck` fails the lint when an observed
order edge contradicts the static graph or an observed unguarded access
pair matches (or should have matched) a static finding.

Import discipline: this package must stay import-light — ``util.threads``
pulls :mod:`rsan` inside every lock construction when the sanitizer is
on, and the model modules are pure-AST (no jax).
"""

from rca_tpu.analysis.concurrency.model import (  # noqa: F401
    ConcurrencyModel,
    model_for,
)
