"""The whole-package static concurrency model gravelock analyzes.

One :class:`ConcurrencyModel` per lint run (cached by file mtimes)
indexes every ``rca_tpu/`` module under the linted root — pure AST, no
imports executed — and computes:

- **thread roots** (:attr:`ConcurrencyModel.roots`): every
  ``threading.Thread(target=...)`` / ``util.threads.spawn`` /
  ``make_thread`` call site, every in-package ``threading.Thread``
  subclass (its ``run`` is the root), and executor-style ``.submit(fn)``
  hand-offs.  A root spawned inside a loop or comprehension is marked
  **multi-instance**: two copies of the same entry point are as
  concurrent as two different ones.  The implicit ``main`` root covers
  every chain that starts outside spawned code;
- a **call graph** with best-effort receiver typing (self-attribute
  types from ``__init__`` assignments and parameter annotations, local
  constructor bindings, imported module functions), over which the
  traversal (:meth:`ConcurrencyModel.traverse`) propagates, per
  (root, receiver-context) pair, the set of locks **held on every path**
  to each function — the interprocedural half of both analyses;
- per-class **write sites** of ``self.<attr>`` (plain assign, augmented
  read-modify-write, mutating container calls) with the locks held
  locally at each site, feeding guarded-by inference (:mod:`races`);
- **nested-acquire events** feeding the lock-order graph
  (:mod:`lockorder`).

Receiver contexts are how the model distinguishes *instances* without a
points-to analysis: a chain that reaches ``PhaseStats.record`` through
``ServeMetrics._queue_ms`` and one that reaches it through a streaming
session's own accumulator touch DIFFERENT objects, so their write
observations never pair; chains that converge on the same
``Owner.attr`` hop (or on the spawning object itself) do.  Locks carry
the same ``"Class.attr"`` identities :mod:`rca_tpu.util.threads` stamps
at construction, so the rsan cross-check compares like with like.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

#: constructors whose result counts as a lock (raw + the util.threads seam)
LOCK_FACTORIES = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "make_lock", "make_rlock", "make_condition",
}
#: thread constructors (raw + seam); subclassing threading.Thread also roots
THREAD_FACTORIES = {"Thread", "make_thread", "spawn"}

#: constructor-family methods whose writes are pre-sharing by definition
INIT_METHODS = {"__init__", "__post_init__", "__new__"}

MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort", "reverse", "move_to_end",
}

#: traversal bounds: states are (context, lockset) pairs per (func, root);
#: past the cap further states are dropped (loses observations — safe in
#: the false-negative direction, never invents a finding)
MAX_STATES_PER_FUNC = 24

MAIN_ROOT = "main"


# ---------------------------------------------------------------------------
# index records
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CallSite:
    callee: str                   # resolved function qual
    kind: str                     # self | attr | local | unknown | plain
    owner: str = ""               # attr hop: class owning the attribute
    attr: str = ""                # attr hop: attribute name
    locks: Tuple[Tuple[str, Tuple[str, int]], ...] = ()  # held at site
    lineno: int = 0


@dataclasses.dataclass
class WriteSite:
    cls: str
    attr: str
    kind: str                     # assign | augassign | mutcall
    locks: Tuple[Tuple[str, Tuple[str, int]], ...] = ()
    lineno: int = 0
    func: str = ""                # enclosing function qual


@dataclasses.dataclass
class AcquireSite:
    lock: str
    outer: Tuple[Tuple[str, Tuple[str, int]], ...]  # held when entering
    lineno: int = 0
    func: str = ""


@dataclasses.dataclass
class SpawnSite:
    target: str                   # resolved root function qual ("" = unknown)
    name_hint: str
    multi: bool
    lineno: int = 0
    func: str = ""                # where the spawn happens


@dataclasses.dataclass
class FuncInfo:
    qual: str                     # "<relpath>::Cls.meth" / "<relpath>::fn"
    relpath: str
    cls: str                      # "" for plain functions
    name: str
    lineno: int
    calls: List[CallSite] = dataclasses.field(default_factory=list)
    writes: List[WriteSite] = dataclasses.field(default_factory=list)
    acquires: List[AcquireSite] = dataclasses.field(default_factory=list)
    spawns: List[SpawnSite] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ClassInfo:
    name: str
    relpath: str
    lineno: int
    bases: List[str]
    lock_attrs: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: self.<attr> -> candidate class names (best-effort typing)
    attr_types: Dict[str, Set[str]] = dataclasses.field(default_factory=dict)
    methods: Dict[str, str] = dataclasses.field(default_factory=dict)

    def is_thread(self, classes: Dict[str, "ClassInfo"]) -> bool:
        seen: Set[str] = set()
        stack = list(self.bases)
        while stack:
            b = stack.pop()
            if b in seen:
                continue
            seen.add(b)
            if b == "Thread":
                return True
            info = classes.get(b)
            if info is not None:
                stack.extend(info.bases)
        return False


@dataclasses.dataclass(frozen=True)
class Context:
    """Receiver identity approximation for one call chain.

    kind ``inst``: the receiver was reached as ``<owner>.<attr>`` — two
    chains through the same hop touch the same object.  ``root``: the
    receiver hosts a spawned entry point; it pairs with ANY chain whose
    receiver class matches (``inst`` hops and external ``ext`` entries
    alike) — you start the worker on the same object you keep calling.
    ``local``/``ext`` against anything else: never pairs (distinct or
    unknowable instances).  ``-``: no receiver (plain function)."""

    kind: str
    detail: str
    recv_class: str

    def pairs_with(self, other: "Context") -> bool:
        for a, b in ((self, other), (other, self)):
            if a.kind == "inst" and b.kind == "inst":
                return a.detail == b.detail
            if a.kind == "root" and b.kind in ("inst", "root", "ext"):
                return a.recv_class == b.recv_class \
                    and bool(a.recv_class)
        return False


NO_CTX = Context("-", "", "")


@dataclasses.dataclass(frozen=True)
class RootInfo:
    root_id: str                  # display name ("main", "rca-serve", ...)
    entry: str                    # function qual ("" for main)
    multi: bool                   # >1 concurrent instances of this entry


@dataclasses.dataclass
class Observation:
    """One write site as seen from one traversal chain."""

    site: WriteSite
    root: RootInfo
    ctx: Context
    locks: FrozenSet[str]


@dataclasses.dataclass
class OrderEdge:
    outer: str
    inner: str
    root: str
    outer_site: Tuple[str, int]   # (func qual, line) where outer acquired
    inner_site: Tuple[str, int]


# ---------------------------------------------------------------------------
# per-file extraction
# ---------------------------------------------------------------------------


def _dotted(relpath: str) -> str:
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = p.split("/")
    if parts and parts[0] == "rca_tpu":
        parts = parts[1:]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "rca_tpu"


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_factory_name(call: ast.Call) -> Optional[str]:
    """The bare factory name of a constructor call (``threading.Lock`` ->
    ``Lock``, ``make_lock`` -> ``make_lock``), or None."""
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _ann_names(ann: Optional[ast.AST]) -> Set[str]:
    """Class names referenced by an annotation (handles Optional[...],
    string annotations, unions)."""
    out: Set[str] = set()
    if ann is None:
        return out
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return out
    for node in ast.walk(ann):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    out -= {"Optional", "Union", "List", "Dict", "Tuple", "Sequence",
            "Callable", "Any", "None", "Set", "FrozenSet", "Iterable",
            "Type", "str", "int", "float", "bool", "bytes", "object"}
    return out


class _FileIndexer(ast.NodeVisitor):
    """Extract classes/functions of one module (structure pass)."""

    def __init__(self, relpath: str, tree: ast.AST):
        self.relpath = relpath
        self.tree = tree
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        #: imported name -> source module dotted path (package-internal)
        self.imports: Dict[str, str] = {}
        #: module-level lock names -> lock id
        self.module_locks: Dict[str, str] = {}
        self._collect()

    def _collect(self) -> None:
        for node in self.tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._collect_import(node)
            elif isinstance(node, ast.Assign):
                self._collect_module_lock(node)
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._collect_import(node)

    def _collect_import(self, node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = alias.asname or alias.name.split(".")[0]
                self.imports[name] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                self.imports[alias.asname or alias.name] = (
                    f"{node.module}.{alias.name}"
                )

    def _collect_module_lock(self, node: ast.Assign) -> None:
        if not isinstance(node.value, ast.Call):
            return
        fac = _call_factory_name(node.value)
        if fac not in LOCK_FACTORIES:
            return
        for t in node.targets:
            if isinstance(t, ast.Name):
                explicit = (
                    _const_str(node.value.args[0]) if node.value.args
                    else None
                )
                self.module_locks[t.id] = (
                    explicit or f"{_dotted(self.relpath)}.{t.id}"
                )


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------


class ConcurrencyModel:
    def __init__(self, root: str, files: Sequence[Tuple[str, ast.AST]]):
        self.root = root
        self.indexers: Dict[str, _FileIndexer] = {
            rel: _FileIndexer(rel, tree) for rel, tree in files
        }
        #: bare class name -> ClassInfo (package-unique in practice)
        self.classes: Dict[str, ClassInfo] = {}
        self.functions: Dict[str, FuncInfo] = {}
        #: dotted module -> {func name -> qual}
        self.module_funcs: Dict[str, Dict[str, str]] = {}
        self.roots: List[RootInfo] = []
        self.observations: Dict[Tuple[str, str], List[Observation]] = {}
        self.order_edges: List[OrderEdge] = []
        self.class_attr_writes: List[WriteSite] = []
        self.functions_traversed = 0
        self._build_structure()
        self._build_bodies()
        self._discover_roots()
        self.traverse()

    # -- structure: classes, methods, typing --------------------------------
    def _build_structure(self) -> None:
        for rel, idx in self.indexers.items():
            dotted = _dotted(rel)
            self.module_funcs.setdefault(dotted, {})
            for node in idx.tree.body:
                if isinstance(node, ast.ClassDef):
                    self._index_class(rel, idx, node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    qual = f"{rel}::{node.name}"
                    self.functions[qual] = FuncInfo(
                        qual=qual, relpath=rel, cls="", name=node.name,
                        lineno=node.lineno,
                    )
                    self.module_funcs[dotted][node.name] = qual

    def _index_class(self, rel: str, idx: _FileIndexer,
                     node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        info = ClassInfo(name=node.name, relpath=rel, lineno=node.lineno,
                         bases=bases)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{rel}::{node.name}.{item.name}"
                info.methods[item.name] = qual
                self.functions[qual] = FuncInfo(
                    qual=qual, relpath=rel, cls=node.name, name=item.name,
                    lineno=item.lineno,
                )
                self._harvest_types(info, item)
            elif isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name):
                # dataclass-style field annotation
                for t in _ann_names(item.annotation):
                    info.attr_types.setdefault(item.target.id, set()).add(t)
        self.classes.setdefault(node.name, info)
        idx.classes[node.name] = info

    def _harvest_types(self, info: ClassInfo,
                       fn: ast.FunctionDef) -> None:
        """Attribute typing + lock-attr discovery from one method."""
        ann_by_param = {
            a.arg: _ann_names(a.annotation) for a in fn.args.args
        }
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            targets = [
                t for t in node.targets
                if isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name) and t.value.id == "self"
            ]
            if not targets:
                continue
            # lock attrs: self._x = Lock()/make_lock("...")-family
            calls = [
                n for n in ast.walk(node.value) if isinstance(n, ast.Call)
            ]
            for c in calls:
                fac = _call_factory_name(c)
                if fac in LOCK_FACTORIES:
                    explicit = _const_str(c.args[0]) if c.args else None
                    for t in targets:
                        info.lock_attrs[t.attr] = (
                            explicit or f"{info.name}.{t.attr}"
                        )
                elif fac is not None and fac[0].isupper():
                    for t in targets:
                        info.attr_types.setdefault(t.attr, set()).add(fac)
            # self.x = <param> carries the param's annotation
            if isinstance(node.value, ast.Name) \
                    and node.value.id in ann_by_param:
                for t in targets:
                    info.attr_types.setdefault(t.attr, set()).update(
                        ann_by_param[node.value.id]
                    )
            # self.x = a or B(...): BoolOp branches both contribute (the
            # Call branch was picked up above; a Name branch may be an
            # annotated param too)
            if isinstance(node.value, ast.BoolOp):
                for v in node.value.values:
                    if isinstance(v, ast.Name) and v.id in ann_by_param:
                        for t in targets:
                            info.attr_types.setdefault(t.attr, set()).update(
                                ann_by_param[v.id]
                            )

    # -- bodies: calls, writes, acquires, spawns ----------------------------
    def _build_bodies(self) -> None:
        for rel, idx in self.indexers.items():
            for node in idx.tree.body:
                if isinstance(node, ast.ClassDef):
                    cls = idx.classes.get(node.name)
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            self._scan_function(rel, idx, cls, item,
                                                f"{node.name}.{item.name}")
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    self._scan_function(rel, idx, None, node, node.name)

    def _lock_id_for_expr(self, idx: _FileIndexer,
                          cls: Optional[ClassInfo],
                          expr: ast.AST) -> Optional[str]:
        """The lock a ``with <expr>:`` enters, if recognizable."""
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and cls is not None:
            if expr.attr in cls.lock_attrs:
                return cls.lock_attrs[expr.attr]
            if "lock" in expr.attr.lower() or "cond" in expr.attr.lower():
                return f"{cls.name}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name):
            return idx.module_locks.get(expr.id)
        return None

    def _scan_function(self, rel: str, idx: _FileIndexer,
                       cls: Optional[ClassInfo],
                       fn: ast.FunctionDef, label: str,
                       outer_types: Optional[Dict] = None,
                       outer_hops: Optional[Dict] = None) -> None:
        qual = f"{rel}::{label}"
        fi = self.functions.get(qual)
        if fi is None:   # nested function discovered below gets its own
            fi = self.functions[qual] = FuncInfo(
                qual=qual, relpath=rel, cls=cls.name if cls else "",
                name=fn.name, lineno=fn.lineno,
            )
        # closures inherit the enclosing scope's variable typing (a spawn
        # target like a submitter closure calls through captured locals)
        local_types: Dict[str, Tuple[str, Set[str]]] = dict(
            outer_types or {}
        )
        local_hops: Dict[str, Tuple[str, str]] = dict(outer_hops or {})
        # param annotations type the locals they name
        for a in fn.args.args:
            names = _ann_names(a.annotation)
            if names:
                local_types[a.arg] = ("unknown", names)

        def infer(expr: ast.AST) -> Tuple[str, str, str, Set[str]]:
            """(kind, owner, attr, classes) of a receiver expression."""
            if isinstance(expr, ast.Name):
                if expr.id == "self" and cls is not None:
                    return ("self", "", "", {cls.name})
                if expr.id in local_types:
                    kind, classes = local_types[expr.id]
                    owner, attr = local_hops.get(expr.id, ("", ""))
                    return (kind, owner, attr, classes)
                return ("unknown", "", "", set())
            if isinstance(expr, ast.Attribute):
                base_kind, _o, _a, base_classes = infer(expr.value)
                owners = set()
                types: Set[str] = set()
                for bc in base_classes:
                    binfo = self.classes.get(bc)
                    if binfo is None:
                        continue
                    if expr.attr in binfo.attr_types:
                        owners.add(bc)
                        types |= binfo.attr_types[expr.attr]
                if owners:
                    owner = sorted(owners)[0]
                    return ("attr", owner, expr.attr, types)
                return ("unknown", "", "", set())
            if isinstance(expr, ast.Call):
                fac = _call_factory_name(expr)
                if fac in self.classes:
                    return ("local", "", "", {fac})
                return ("unknown", "", "", set())
            return ("unknown", "", "", set())

        def resolve_callee(call: ast.Call) -> List[Tuple[str, str, str, str]]:
            """[(callee_qual, kind, owner, attr)] for one call node."""
            f = call.func
            out: List[Tuple[str, str, str, str]] = []
            if isinstance(f, ast.Name):
                name = f.id
                nested = f"{rel}::{label}.{name}"
                if nested in self.functions:
                    return [(nested, "self" if cls else "plain", "", "")]
                dotted = _dotted(rel)
                if name in self.module_funcs.get(dotted, {}):
                    return [(self.module_funcs[dotted][name], "plain",
                             "", "")]
                if name in self.classes:
                    init = self.classes[name].methods.get("__init__")
                    if init:
                        out.append((init, "local", "", ""))
                    return out
                src = idx.imports.get(name)
                if src and src.startswith("rca_tpu."):
                    mod, _, fname = src.rpartition(".")
                    mod = mod[len("rca_tpu."):]
                    target = self.module_funcs.get(mod, {}).get(fname)
                    if target:
                        return [(target, "plain", "", "")]
                    if fname in self.classes:
                        init = self.classes[fname].methods.get("__init__")
                        if init:
                            return [(init, "local", "", "")]
                return out
            if isinstance(f, ast.Attribute):
                meth = f.attr
                kind, owner, attr, classes = infer(f.value)
                for c in sorted(classes):
                    target = self._lookup_method(c, meth)
                    if target:
                        out.append((target, kind, owner, attr))
                return out
            return out

        def spawn_target(call: ast.Call) -> Tuple[str, str]:
            """(root function qual, name hint) for a thread spawn."""
            target_expr = None
            name_hint = ""
            for kw in call.keywords:
                if kw.arg == "target":
                    target_expr = kw.value
                if kw.arg == "name":
                    name_hint = _const_str(kw.value) or ""
            if target_expr is None and call.args:
                target_expr = call.args[0]
            if target_expr is None:
                return ("", name_hint)
            if isinstance(target_expr, ast.Name):
                nested = f"{rel}::{label}.{target_expr.id}"
                if nested in self.functions:
                    return (nested, name_hint or target_expr.id)
                dotted = _dotted(rel)
                q = self.module_funcs.get(dotted, {}).get(target_expr.id)
                return (q or "", name_hint or target_expr.id)
            if isinstance(target_expr, ast.Attribute):
                kind, _o, _a, classes = infer(target_expr.value)
                for c in sorted(classes):
                    q = self._lookup_method(c, target_expr.attr)
                    if q:
                        return (q, name_hint or target_expr.attr)
            return ("", name_hint)

        # local variable typing pass (simple forward scan)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                var = node.targets[0].id
                kind, owner, attr, classes = infer(node.value)
                if classes:
                    local_types[var] = (kind, classes)
                    if kind == "attr":
                        local_hops[var] = (owner, attr)
                elif isinstance(node.value, ast.BoolOp):
                    for v in node.value.values:
                        k2, o2, a2, c2 = infer(v)
                        if c2:
                            local_types[var] = (k2, c2)
                            if k2 == "attr":
                                local_hops[var] = (o2, a2)
                            break

        # body walk with a with-lock stack
        multi_depth = 0

        def walk(node: ast.AST, held: List[Tuple[str, Tuple[str, int]]],
                 in_loop: bool) -> None:
            nonlocal multi_depth
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn:
                # nested defs scanned separately (with their own label),
                # inheriting this scope's typing for captured variables
                self._scan_function(rel, idx, cls, node,
                                    f"{label}.{node.name}",
                                    outer_types=local_types,
                                    outer_hops=local_hops)
                return
            if isinstance(node, ast.With):
                entered: List[Tuple[str, Tuple[str, int]]] = []
                for item in node.items:
                    lid = self._lock_id_for_expr(idx, cls,
                                                 item.context_expr)
                    if lid is not None:
                        fi.acquires.append(AcquireSite(
                            lock=lid, outer=tuple(held),
                            lineno=node.lineno, func=qual,
                        ))
                        entered.append((lid, (qual, node.lineno)))
                for child in node.body:
                    walk(child, held + entered, in_loop)
                return
            loop_here = in_loop or isinstance(
                node, (ast.For, ast.While, ast.ListComp, ast.SetComp,
                       ast.GeneratorExp, ast.DictComp)
            )
            if isinstance(node, ast.Call):
                fac = _call_factory_name(node)
                if fac in THREAD_FACTORIES or (
                    fac in self.classes
                    and self.classes[fac].is_thread(self.classes)
                ):
                    if fac in THREAD_FACTORIES:
                        tgt, hint = spawn_target(node)
                    else:
                        tgt = self._lookup_method(fac, "run") or ""
                        hint = fac
                    if tgt:
                        fi.spawns.append(SpawnSite(
                            target=tgt, name_hint=hint, multi=loop_here,
                            lineno=node.lineno, func=qual,
                        ))
                elif fac == "submit" and node.args:
                    # executor-style hand-off: first arg is callable
                    a0 = node.args[0]
                    ref = ""
                    if isinstance(a0, ast.Name):
                        dotted = _dotted(rel)
                        nested = f"{rel}::{label}.{a0.id}"
                        ref = (nested if nested in self.functions else
                               self.module_funcs.get(dotted, {})
                               .get(a0.id, ""))
                    if ref:
                        fi.spawns.append(SpawnSite(
                            target=ref, name_hint=a0.id, multi=loop_here,
                            lineno=node.lineno, func=qual,
                        ))
                for callee, kind, owner, attr in resolve_callee(node):
                    fi.calls.append(CallSite(
                        callee=callee, kind=kind, owner=owner, attr=attr,
                        locks=tuple(held), lineno=node.lineno,
                    ))
                # mutating container call on self.<attr>
                w = self._mutcall_write(node, cls)
                if w is not None:
                    fi.writes.append(WriteSite(
                        cls=cls.name if cls else "", attr=w,
                        kind="mutcall", locks=tuple(held),
                        lineno=node.lineno, func=qual,
                    ))
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                self._record_assign_writes(node, fi, cls, held, qual)
            for child in ast.iter_child_nodes(node):
                walk(child, held, loop_here)

        for stmt in fn.body:
            walk(stmt, [], False)

    @staticmethod
    def _base_of(target: ast.AST) -> ast.AST:
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        return base

    def _record_assign_writes(self, node: ast.AST, fi: FuncInfo,
                              cls: Optional[ClassInfo],
                              held: List[Tuple[str, Tuple[str, int]]],
                              qual: str) -> None:
        kind = "augassign" if isinstance(node, ast.AugAssign) else "assign"
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            base = self._base_of(t)
            if not isinstance(base, ast.Attribute):
                continue
            # subscripted plain assigns (self.d[k] = v) mutate the
            # container in place — treat like a mutating call
            wkind = kind
            if base is not t and kind == "assign":
                wkind = "mutcall"
            if isinstance(base.value, ast.Name) and base.value.id == "self" \
                    and cls is not None:
                if base.attr in cls.lock_attrs:
                    continue
                if fi.name in INIT_METHODS:
                    continue
                fi.writes.append(WriteSite(
                    cls=cls.name, attr=base.attr, kind=wkind,
                    locks=tuple(held), lineno=node.lineno, func=qual,
                ))
            elif isinstance(base.value, ast.Name) and cls is not None \
                    and base.value.id == cls.name:
                # ClassName.attr mutated inside a method: class-shared
                # state behind (at best) a per-instance lock
                if wkind in ("augassign", "mutcall"):
                    self.class_attr_writes.append(WriteSite(
                        cls=cls.name, attr=base.attr, kind=wkind,
                        locks=tuple(held), lineno=node.lineno, func=qual,
                    ))

    def _mutcall_write(self, call: ast.Call,
                       cls: Optional[ClassInfo]) -> Optional[str]:
        if cls is None or not isinstance(call.func, ast.Attribute):
            return None
        if call.func.attr not in MUTATING_METHODS:
            return None
        base = self._base_of(call.func.value)
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" \
                and base.attr not in cls.lock_attrs:
            return base.attr
        return None

    def _lookup_method(self, cls_name: str, meth: str) -> Optional[str]:
        seen: Set[str] = set()
        stack = [cls_name]
        while stack:
            c = stack.pop(0)
            if c in seen:
                continue
            seen.add(c)
            info = self.classes.get(c)
            if info is None:
                continue
            if meth in info.methods:
                return info.methods[meth]
            stack.extend(info.bases)
        return None

    # -- roots ---------------------------------------------------------------
    def _discover_roots(self) -> None:
        by_entry: Dict[str, RootInfo] = {}
        for fi in self.functions.values():
            for sp in fi.spawns:
                if not sp.target:
                    continue
                prev = by_entry.get(sp.target)
                multi = sp.multi or (prev.multi if prev else False) or (
                    prev is not None  # spawned from 2+ sites = concurrent
                )
                tgt = self.functions.get(sp.target)
                name = sp.name_hint or (tgt.name if tgt else sp.target)
                by_entry[sp.target] = RootInfo(
                    root_id=name, entry=sp.target, multi=multi,
                )
        # Thread subclasses instantiated nowhere statically still root
        # their run(): the class exists to be started
        for cls in self.classes.values():
            if cls.is_thread(self.classes) and "run" in cls.methods:
                entry = cls.methods["run"]
                if entry not in by_entry:
                    by_entry[entry] = RootInfo(
                        root_id=cls.name, entry=entry, multi=True,
                    )
        self.roots = sorted(by_entry.values(), key=lambda r: r.entry)

    # -- traversal -----------------------------------------------------------
    def _spawn_reachable(self) -> Set[str]:
        out: Set[str] = set()
        stack = [r.entry for r in self.roots]
        while stack:
            q = stack.pop()
            if q in out:
                continue
            out.add(q)
            fi = self.functions.get(q)
            if fi is None:
                continue
            for c in fi.calls:
                if c.callee not in out:
                    stack.append(c.callee)
        return out

    def traverse(self) -> None:
        """Propagate (root, context, lockset) triples over the call graph,
        collecting write observations and nested-acquire edges."""
        spawn_reach = self._spawn_reachable()
        main = RootInfo(root_id=MAIN_ROOT, entry="", multi=False)
        seeds: List[Tuple[str, RootInfo, Context,
                          Tuple[Tuple[str, Tuple[str, int]], ...]]] = []
        for r in self.roots:
            fi = self.functions.get(r.entry)
            ctx = (Context("root", "", fi.cls) if fi is not None and fi.cls
                   else NO_CTX)
            seeds.append((r.entry, r, ctx, ()))
        for qual, fi in self.functions.items():
            if qual in spawn_reach:
                continue
            ctx = Context("ext", qual, fi.cls) if fi.cls else NO_CTX
            seeds.append((qual, main, ctx, ()))

        visited: Set[Tuple[str, str, Context, FrozenSet[str]]] = set()
        states_per_func: Dict[Tuple[str, str], int] = {}
        stack = list(seeds)
        touched: Set[str] = set()
        while stack:
            qual, root, ctx, held = stack.pop()
            lockset = frozenset(l for l, _site in held)
            key = (qual, root.root_id, ctx, lockset)
            if key in visited:
                continue
            cap_key = (qual, root.root_id)
            if states_per_func.get(cap_key, 0) >= MAX_STATES_PER_FUNC:
                continue
            states_per_func[cap_key] = states_per_func.get(cap_key, 0) + 1
            visited.add(key)
            touched.add(qual)
            fi = self.functions.get(qual)
            if fi is None:
                continue
            held_map = dict(held)
            # observations: every write in this function, with inherited +
            # local locks
            for w in fi.writes:
                locks = frozenset(held_map) | frozenset(
                    l for l, _s in w.locks
                )
                self.observations.setdefault(
                    (w.cls, w.attr), []
                ).append(Observation(site=w, root=root, ctx=ctx,
                                     locks=locks))
            # lock-order edges: local acquires nest under inherited locks
            # AND under locally-outer with-blocks (recorded in .outer)
            for a in fi.acquires:
                outer_map = dict(held)
                outer_map.update(dict(a.outer))
                for outer_lock, outer_site in outer_map.items():
                    if outer_lock == a.lock:
                        continue
                    self.order_edges.append(OrderEdge(
                        outer=outer_lock, inner=a.lock,
                        root=root.root_id, outer_site=outer_site,
                        inner_site=(qual, a.lineno),
                    ))
            # propagate over calls
            for c in fi.calls:
                callee = self.functions.get(c.callee)
                if callee is None:
                    continue
                new_held = dict(held)
                new_held.update(dict(c.locks))
                if not callee.cls:
                    new_ctx = NO_CTX
                elif c.kind == "self":
                    new_ctx = ctx
                elif c.kind == "attr":
                    new_ctx = Context("inst", f"{c.owner}.{c.attr}",
                                      callee.cls)
                elif c.kind == "local":
                    new_ctx = Context("local", f"{qual}:{c.lineno}",
                                      callee.cls)
                else:
                    new_ctx = Context("ext", f"{qual}:{c.lineno}",
                                      callee.cls)
                stack.append((
                    c.callee, root, new_ctx,
                    tuple(sorted(new_held.items())),
                ))
        self.functions_traversed = len(touched)

    # -- reporting helpers ---------------------------------------------------
    def stats(self) -> Dict[str, object]:
        locks: Set[str] = set()
        for cls in self.classes.values():
            locks.update(cls.lock_attrs.values())
        for idx in self.indexers.values():
            locks.update(idx.module_locks.values())
        edge_keys = {(e.outer, e.inner) for e in self.order_edges}
        return {
            "files": len(self.indexers),
            "functions": len(self.functions),
            "functions_traversed": self.functions_traversed,
            "thread_roots": [r.root_id for r in self.roots],
            "locks": len(locks),
            "lock_graph_nodes": len(
                {l for e in edge_keys for l in e}
            ),
            "lock_graph_edges": len(edge_keys),
        }

    def static_order_edges(self) -> Set[Tuple[str, str]]:
        return {(e.outer, e.inner) for e in self.order_edges}


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

_CACHE: Dict[str, Tuple[Tuple[Tuple[str, int, int], ...],
                        ConcurrencyModel]] = {}


def _package_files(root: str) -> List[str]:
    base = os.path.join(root, "rca_tpu")
    out: List[str] = []
    for dirpath, _dirs, files in os.walk(base):
        out += [
            os.path.join(dirpath, f) for f in files if f.endswith(".py")
        ]
    return sorted(out)


def model_for(root: str) -> ConcurrencyModel:
    """The (cached) concurrency model of the ``rca_tpu/`` package under
    ``root``.  Rebuilt whenever any package file's (mtime, size)
    changes — cheap enough that repeated ``run_lint`` calls in one
    process do not re-parse the world."""
    files = _package_files(root)
    key = tuple(
        (f, int(os.stat(f).st_mtime_ns), os.path.getsize(f))
        for f in files
    )
    cached = _CACHE.get(root)
    if cached is not None and cached[0] == key:
        return cached[1]
    from rca_tpu.analysis.core import parse_file

    parsed: List[Tuple[str, ast.AST]] = []
    for f in files:
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        try:
            # shared parse cache: one ast.parse per file per lint run,
            # even though graftlint's runner walks the same trees
            parsed.append((rel, parse_file(f)[1]))
        except (SyntaxError, OSError):
            continue  # the core runner reports parse errors itself
    model = ConcurrencyModel(root, parsed)
    if len(_CACHE) > 4:
        _CACHE.clear()
    _CACHE[root] = (key, model)
    return model
