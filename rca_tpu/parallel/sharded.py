"""Node-sharded sparse propagation via shard_map + XLA collectives.

The scaling analog of ring attention for this workload (SURVEY.md §5): the
service graph's node arrays are sharded across the 'sp' mesh axis, each
device owns a contiguous node block plus the edge partition whose *sources*
live in its block, and every propagation step exchanges cross-shard state
with collectives riding ICI:

- upstream explain-away (segment-max):  ``all_gather`` the per-block signal,
  gather per-edge values locally, scatter-max into the local block;
- downstream impact (segment-sum): compute full-length contributions
  locally, ``psum_scatter`` so each device receives exactly its reduced
  block (reduce-scatter, no full materialization on any hop).

Hypothesis batches shard over 'dp' (the BASELINE.json "pmap over fault
candidates" config) — 2-axis mesh, one jit.

Padded edges carry mask 0 and contribute exactly 0 to both max and sum (all
signals are nonnegative), so no special dummy nodes are needed per shard.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from rca_tpu.engine.propagate import (
    PropagationParams,
    _noisy_or,
    background_excess,
    combine_score,
)
from rca_tpu.parallel.rules import (
    GRAPH_RULES,
    make_shard_and_gather_fns,
    match_partition_rules,
)


def shard_map_compat(fn, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` where it exists (jax ≥ 0.5), else the
    ``jax.experimental.shard_map`` spelling with its ``check_rep`` kwarg —
    the same primitive under an older name.  Without this shim every
    sharded dispatch dies with AttributeError on a jax 0.4.x install,
    which is exactly the class of environment skew the engine degradation
    ladder exists for; prefer not entering the ladder at all."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm_legacy

    return sm_legacy(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                     check_rep=check_vma)


@dataclasses.dataclass(frozen=True)
class ShardedGraph:
    """Edge partition for an sp-way node sharding."""

    n_pad: int                 # padded node count (multiple of sp)
    n: int                     # real node count (slots n..n_pad-1 are pad)
    block: int                 # nodes per shard = n_pad // sp
    sp: int
    src_local: np.ndarray      # int32 [sp, e_pad] — src index within block
    src_global: np.ndarray     # int32 [sp, e_pad]
    dst_global: np.ndarray     # int32 [sp, e_pad]
    mask: np.ndarray           # float32 [sp, e_pad] — 1 real, 0 padding


def shard_graph(
    n: int, src: np.ndarray, dst: np.ndarray, sp: int,
    *, n_pad_to: int = 0, e_pad_fn=None,
) -> ShardedGraph:
    """Partition edges by source-node shard; pad shards to equal length.

    ``n_pad_to``: pad the node axis to at least this many slots (rounded up
    to a multiple of ``sp``) — lets :class:`ShardedGraphEngine` reuse the
    dense engine's shape buckets so jit compiles once per tier, not per
    graph.  ``e_pad_fn``: optional bucketing function applied to the
    per-shard edge row length (same recompilation control for the edge
    axis)."""
    block = -(-max(n, 1, n_pad_to) // sp)  # ceil
    n_pad = block * sp
    shard_of = (src // block).astype(np.int64) if len(src) else np.zeros(0, np.int64)
    per_shard = [np.nonzero(shard_of == k)[0] for k in range(sp)]
    e_pad = max(1, max((len(ix) for ix in per_shard), default=1))
    if e_pad_fn is not None:
        e_pad = max(e_pad, int(e_pad_fn(e_pad)))
    src_local = np.zeros((sp, e_pad), dtype=np.int32)
    src_global = np.zeros((sp, e_pad), dtype=np.int32)
    dst_global = np.zeros((sp, e_pad), dtype=np.int32)
    mask = np.zeros((sp, e_pad), dtype=np.float32)
    for k, ix in enumerate(per_shard):
        m = len(ix)
        if m:
            src_global[k, :m] = src[ix]
            src_local[k, :m] = src[ix] - k * block
            dst_global[k, :m] = dst[ix]
            mask[k, :m] = 1.0
    return ShardedGraph(
        n_pad=n_pad, n=n, block=block, sp=sp,
        src_local=src_local, src_global=src_global,
        dst_global=dst_global, mask=mask,
    )


class ShardedSegLayouts(NamedTuple):
    """Per-shard segmented-scan layouts (round 5): the round-4 Pallas
    segscan win (``rca_tpu.engine.segscan``, 2.5x at 50k single-device)
    ported into the per-device block kernel.  Segments are LOCAL to each
    shard's own edge partition — the down-scan's segment totals form this
    shard's full-length contribution vector, and cross-shard reduction
    still rides the existing ``psum_scatter``; the up-scan's segments are
    source nodes, which by construction live inside this shard's block, so
    its totals apply locally with no extra collective.  Comm volume is
    therefore IDENTICAL to the scatter kernel — only the on-device
    scatter/gather primitives change.

    All arrays are stacked ``[sp, ...]`` host-side and enter ``shard_map``
    under a ``P("sp", None)`` prefix spec.  Sort order differs per shard,
    so each shard carries its own flags/ends/mask permutation."""

    dn_other: np.ndarray   # int32 [sp, e_pad] — src, dst-sorted
    dn_mask: np.ndarray    # f32 [sp, e_pad] — edge mask, dst-sorted
    dn_flags: np.ndarray   # f32 [sp, e_pad] — 1 at each dst-run start
    dn_ends: np.ndarray    # int32 [sp, n_pad] — last edge pos per dst
    dn_has: np.ndarray     # f32 [sp, n_pad] — dst has local edges
    up_other: np.ndarray   # int32 [sp, e_pad] — dst, src-local-sorted
    up_mask: np.ndarray    # f32 [sp, e_pad]
    up_flags: np.ndarray   # f32 [sp, e_pad]
    up_ends: np.ndarray    # int32 [sp, block] — last edge pos per src
    up_has: np.ndarray     # f32 [sp, block]


def _seg_direction(seg, other, mask, n_seg: int):
    """One shard, one scan direction: dst- (or src-) sorted edge layout.
    Padded slots (mask 0) sort into the dummy segment ``n_seg - 1``; their
    values are masked to the combine identity 0 in the kernel, so they are
    harmless wherever they land (matches engine.segscan's convention)."""
    seg = np.where(mask > 0, seg, n_seg - 1).astype(np.int64)
    order = np.argsort(seg, kind="stable")
    seg_s = seg[order]
    counts = np.bincount(seg_s, minlength=n_seg)
    ends = np.cumsum(counts)
    starts = ends - counts
    flags = np.zeros(len(seg), np.float32)
    flags[starts[counts > 0]] = 1.0
    return (
        other[order].astype(np.int32),
        mask[order].astype(np.float32),
        flags,
        (ends - 1).clip(0).astype(np.int32),
        (counts > 0).astype(np.float32),
    )


# built layouts keyed on the graph's edge digest (same rationale as
# engine.segscan._LAYOUT_CACHE: the per-shard argsort+bincount is host
# milliseconds at the 50k tier, paid once per pinned edge set)
_SHARD_LAYOUT_CACHE: dict = {}


def build_sharded_seg_layouts(graph: ShardedGraph) -> ShardedSegLayouts:
    """Host-side per-shard layouts for :class:`ShardedSegLayouts`."""
    from rca_tpu.engine.segscan import arrays_digest, cache_insert

    key = arrays_digest(
        (graph.n_pad, graph.sp, graph.src_local.shape[1]),
        (graph.src_global, graph.dst_global, graph.mask),
    )
    hit = _SHARD_LAYOUT_CACHE.get(key)
    if hit is not None:
        return hit
    cols = [[] for _ in range(10)]
    for k in range(graph.sp):
        dn = _seg_direction(
            graph.dst_global[k], graph.src_global[k], graph.mask[k],
            graph.n_pad,
        )
        up = _seg_direction(
            graph.src_local[k], graph.dst_global[k], graph.mask[k],
            graph.block,
        )
        for i, arr in enumerate(dn + up):
            cols[i].append(arr)
    layouts = ShardedSegLayouts(*(np.stack(c) for c in cols))
    cache_insert(_SHARD_LAYOUT_CACHE, key, layouts, maxsize=16)
    return layouts


def sharded_seg_layouts_for(graph: ShardedGraph) -> Optional[ShardedSegLayouts]:
    """Engagement gate + builder: the sharded twin of
    :func:`rca_tpu.engine.segscan.seg_layouts_for`.  The decision lives
    in the per-shape kernel registry's SHARDED row (ISSUE 13 — backend,
    ``RCA_SEGSCAN``/``RCA_KERNEL`` forcing, per-shard edge tier
    divisible by 128), so ``rca kernels`` and bench show the sharded
    engagement like any dense row."""
    from rca_tpu.engine.registry import engaged_kernel

    if engaged_kernel(graph.n_pad, graph.src_local.shape[1],
                      sharded=True) != "segscan":
        return None
    return build_sharded_seg_layouts(graph)


def _propagate_block(
    f_blk, src_local, src_global, dst_global, mask, n_live,
    aw, hw, steps: int, decay: float, mu: float, beta: float, seg=None,
    error_contrast: float = 0.0,
):
    """Per-device kernel for ONE graph: f_blk is this shard's node block.
    ``seg`` (this shard's :class:`ShardedSegLayouts` slices) swaps the
    scatter primitives for the Pallas segmented scans; collectives and
    semantics are unchanged (sum order differs within a segment, so parity
    is allclose ~1e-6 like the dense segscan; max is order-invariant)."""
    from rca_tpu.features.schema import SvcF

    a_blk = _noisy_or(f_blk, aw)
    h_blk = _noisy_or(f_blk, hw)
    if error_contrast:
        # error-source contrast (round 5): one extra one-time [block]
        # all_gather; edges are partitioned by source shard, so the
        # scatter-max of dependency error rates is block-local
        from rca_tpu.engine.propagate import fold_error_contrast

        e_blk = jnp.clip(f_blk[:, SvcF.ERROR_RATE], 0.0, 1.0)
        e_full = jax.lax.all_gather(e_blk, "sp", tiled=True)
        dep_max = jnp.zeros_like(e_blk).at[src_local].max(
            mask * e_full[dst_global]
        )
        a_blk = fold_error_contrast(
            a_blk, jnp.maximum(e_blk - dep_max, 0.0), error_contrast
        )
    h_full = jax.lax.all_gather(h_blk, "sp", tiled=True)
    a_full = jax.lax.all_gather(a_blk, "sp", tiled=True)

    if seg is not None:
        from rca_tpu.engine.segscan import pallas_segscan, pallas_segscan_max

        def up_step(u_blk, _):
            u_full = jax.lax.all_gather(u_blk, "sp", tiled=True)
            # per-node signal computed DENSE once, then ONE e_pad-gather
            w_full = jnp.maximum(h_full, decay * u_full)
            vals = seg.up_mask * w_full[seg.up_other]
            s = pallas_segscan_max(vals, seg.up_flags)
            upd = jnp.where(seg.up_has > 0, s[seg.up_ends], 0.0)
            return jnp.maximum(u_blk, upd), None
    else:

        def up_step(u_blk, _):
            u_full = jax.lax.all_gather(u_blk, "sp", tiled=True)
            vals = mask * jnp.maximum(h_full[dst_global], decay * u_full[dst_global])
            scattered = jnp.zeros_like(u_blk).at[src_local].max(vals)
            return jnp.maximum(u_blk, scattered), None

    u_blk, _ = jax.lax.scan(up_step, jnp.zeros_like(a_blk), None, length=steps)

    # background excess over the FULL (all-gathered) anomaly vector so every
    # shard subtracts the same global background as the dense path
    a_ex_full = background_excess(a_full, n_live)

    # dependent count per node in THIS shard's block, for the impact mean:
    # local masked counts reduce-scattered exactly like the contributions
    # (one-time cost outside the step loop — stays a scatter either way)
    deg_blk = jax.lax.psum_scatter(
        jnp.zeros_like(a_full).at[dst_global].add(mask),
        "sp", scatter_dimension=0, tiled=True,
    )
    inv_deg_blk = 1.0 / jnp.maximum(deg_blk, 1.0)

    if seg is not None:

        def imp_step(m_blk, _):
            m_full = jax.lax.all_gather(m_blk, "sp", tiled=True)
            vals = seg.dn_mask * (
                a_ex_full[seg.dn_other] + decay * m_full[seg.dn_other]
            )
            s = pallas_segscan(vals, seg.dn_flags)
            contrib_full = jnp.where(seg.dn_has > 0, s[seg.dn_ends], 0.0)
            return jax.lax.psum_scatter(
                contrib_full, "sp", scatter_dimension=0, tiled=True
            ) * inv_deg_blk, None
    else:

        def imp_step(m_blk, _):
            m_full = jax.lax.all_gather(m_blk, "sp", tiled=True)
            vals = mask * (a_ex_full[src_global] + decay * m_full[src_global])
            contrib_full = jnp.zeros_like(m_full).at[dst_global].add(vals)
            # reduce-scatter: every shard receives its reduced block only
            return jax.lax.psum_scatter(
                contrib_full, "sp", scatter_dimension=0, tiled=True
            ) * inv_deg_blk, None

    m_blk, _ = jax.lax.scan(imp_step, jnp.zeros_like(a_blk), None, length=steps)
    # same hard-evidence-damped suppression + multiplicative impact as
    # engine.propagate.combine_score; return the full diagnostic stack in
    # the dense engine's [a, u, m, score] order so the analyze path can
    # render identical per-service evidence from either engine
    score = combine_score(a_blk, h_blk, u_blk, m_blk, mu, beta)
    return jnp.stack([a_blk, u_blk, m_blk, score])


@functools.lru_cache(maxsize=32)
def _jitted_shard_fn(
    mesh: Mesh, steps: int, decay: float, mu: float, beta: float,
    batch_axes: tuple = ("dp",), use_segscan: bool = False,
    error_contrast: float = 0.0,
):
    """One traced+compiled shard_map per (mesh, scalar-params); weight
    vectors are runtime args so repeated calls hit jit's shape cache
    instead of re-tracing (jit is keyed on function identity).

    ``batch_axes`` names the mesh axes the hypothesis batch shards over —
    ``("dp",)`` single-slice, ``("slice", "dp")`` multi-slice (hypotheses
    spread over DCN, node shards over ICI; no cross-slice collective is
    ever issued inside the propagation).  ``use_segscan`` appends the ten
    :class:`ShardedSegLayouts` arrays as trailing runtime args."""

    def per_device(f_loc, src_l, src_g, dst_g, mask, n_live, aw, hw,
                   *seg_flat):
        # f_loc: [B/dp, block, C]; edge arrays arrive [1, e_pad] — drop the
        # collapsed shard axis, then vmap the block kernel over the local batch
        src_l, src_g = src_l[0], src_g[0]
        dst_g, mask = dst_g[0], mask[0]
        seg = (
            ShardedSegLayouts(*(x[0] for x in seg_flat))
            if seg_flat else None
        )
        kernel = functools.partial(
            _propagate_block,
            steps=steps, decay=decay, mu=mu, beta=beta, seg=seg,
            error_contrast=error_contrast,
        )
        return jax.vmap(
            lambda f: kernel(f, src_l, src_g, dst_g, mask, n_live, aw=aw, hw=hw)
        )(f_loc)

    # arg layout from the ONE rule table (rules.GRAPH_RULES) — the same
    # source stage_sharded derives its upload shardings and the serve
    # pool derives its replica meshes from
    arg_names = (
        "f_loc", "src_local", "src_global", "dst_global", "mask",
        "n_live", "aw", "hw",
        *(ShardedSegLayouts._fields if use_segscan else ()),
    )
    shard_fn = shard_map_compat(
        per_device,
        mesh=mesh,
        in_specs=tuple(
            GRAPH_RULES.spec_for(name, batch_axes) for name in arg_names
        ),
        # [B, 4, n_pad]: diagnostic axis replicated, nodes sharded
        out_specs=GRAPH_RULES.spec_for("stack", batch_axes),
        check_vma=False,
    )
    return jax.jit(shard_fn)


@functools.lru_cache(maxsize=32)
def _jitted_topk_fn(mesh: Mesh, k: int, batch_axes: tuple = ("dp",)):
    """Distributed top-k: each node shard reduces its block to k local
    candidates, the k·sp candidate set rides ONE small all_gather over the
    'sp' axis (ICI), and every device merges on-device — the full [S]
    score vector never leaves its shard and nothing is argmaxed on host."""

    def per_device(s_blk):
        # s_blk: [B/dp, block] — this shard's slice of the score vector
        block = s_blk.shape[1]
        sp = mesh.shape["sp"]
        if k > block * sp:
            raise ValueError(
                f"sharded_topk: k={k} exceeds the sharded vector length "
                f"{block * sp} (block {block} x sp {sp})"
            )
        # a shard can contribute at most `block` candidates; sp*k_local
        # candidates still cover any global top-k with k <= block*sp
        k_local = min(k, block)
        v, i = jax.lax.top_k(s_blk, k_local)
        gi = i + jax.lax.axis_index("sp") * block
        # [B/dp, sp*k_local] candidate values/indices on every device
        vg = jax.lax.all_gather(v, "sp", axis=1, tiled=True)
        ig = jax.lax.all_gather(gi, "sp", axis=1, tiled=True)
        vv, pos = jax.lax.top_k(vg, k)
        return vv, jnp.take_along_axis(ig, pos, axis=1)

    shard_fn = shard_map_compat(
        per_device,
        mesh=mesh,
        in_specs=(GRAPH_RULES.spec_for("scores", batch_axes),),
        # merged results are replicated across 'sp' (every shard holds the
        # same k winners after the gather+merge)
        out_specs=(
            GRAPH_RULES.spec_for("topk_vals", batch_axes),
            GRAPH_RULES.spec_for("topk_idx", batch_axes),
        ),
        check_vma=False,
    )
    return jax.jit(shard_fn)


def sharded_topk(
    mesh: Mesh,
    scores: jax.Array,           # [B, n_pad] as returned by sharded_propagate
    k: int,
    batch_axes: Tuple[str, ...] = ("dp",),
):
    """On-device cross-shard top-k merge; returns (values [B, k],
    global indices [B, k])."""
    fn = _jitted_topk_fn(mesh, k, tuple(batch_axes))
    with mesh:
        return fn(scores)


def stage_sharded(
    mesh: Mesh,
    features_batch: np.ndarray,  # [B, n_pad, C] hypothesis batch, same graph
    graph: ShardedGraph,
    params: PropagationParams,
    batch_axes: Tuple[str, ...] = ("dp",),
):
    """Upload the batch + edge partition to their mesh shardings ONCE and
    return a zero-argument callable that runs the jitted shard fn on the
    staged device buffers — so repeated invocations (the engine's timed
    reps, streaming-style reruns) pay dispatch only, the same methodology
    the dense engine times."""
    aw, hw = params.weight_arrays()
    seg = sharded_seg_layouts_for(graph)
    fn = _jitted_shard_fn(
        mesh, params.steps, params.decay,
        params.explain_strength, params.impact_bonus, tuple(batch_axes),
        use_segscan=seg is not None,
        error_contrast=params.error_contrast,
    )
    # upload shardings from the SAME rule table the shard_map's in_specs
    # derive from — one source of truth for the whole staged layout
    edge_names = ("src_local", "src_global", "dst_global", "mask")
    seg_names = ShardedSegLayouts._fields if seg is not None else ()
    shard_fns, _ = make_shard_and_gather_fns(
        match_partition_rules(
            GRAPH_RULES, ("features_batch", *edge_names, *seg_names),
            batch_axes,
        ),
        mesh,
    )
    fb = shard_fns["features_batch"](features_batch)
    args = tuple(
        shard_fns[name](getattr(graph, name)) for name in edge_names
    )
    seg_args = tuple(
        shard_fns[name](x) for name, x in zip(seg_names, seg)
    ) if seg is not None else ()
    n_live = jnp.asarray(graph.n, jnp.int32)
    awj, hwj = jnp.asarray(aw), jnp.asarray(hw)

    def invoke() -> jax.Array:
        with mesh:
            return fn(fb, *args, n_live, awj, hwj, *seg_args)

    return invoke


def sharded_propagate_full(
    mesh: Mesh,
    features_batch: np.ndarray,  # [B, n_pad, C] hypothesis batch, same graph
    graph: ShardedGraph,
    params: PropagationParams,
    batch_axes: Tuple[str, ...] = ("dp",),
) -> jax.Array:
    """Diagnostic stack [B, 4, n_pad] in the dense engine's
    [anomaly, upstream, impact, score] order: batch sharded over
    ``batch_axes``, nodes over 'sp'.

    Pass ``batch_axes=("slice", "dp")`` with a
    :func:`rca_tpu.parallel.mesh.make_multislice_mesh` mesh for the
    multi-slice configs — hypothesis parallelism rides DCN, node-shard
    collectives stay on ICI."""
    return stage_sharded(mesh, features_batch, graph, params, batch_axes)()


def sharded_propagate(
    mesh: Mesh,
    features_batch: np.ndarray,  # [B, n_pad, C] hypothesis batch, same graph
    graph: ShardedGraph,
    params: PropagationParams,
    batch_axes: Tuple[str, ...] = ("dp",),
) -> jax.Array:
    """Scores [B, n_pad] (the last row of the diagnostic stack; same
    compiled executable as :func:`sharded_propagate_full`)."""
    return sharded_propagate_full(
        mesh, features_batch, graph, params, batch_axes
    )[:, 3]


def batch_topk_diag(stack: jax.Array, idx: jax.Array) -> jax.Array:
    """On-device per-lane gather of the top-k diagnostic rows:
    ``out[b, :, j] = stack[b, :, idx[b, j]]`` — the [B, 4, kk] slice is
    everything the ranked rendering needs, so fetch surfaces move THIS
    instead of the full [B, 4, n_pad] stack (ISSUE 6).  Works on sharded
    stacks too: GSPMD inserts the cross-shard gather, which is exactly
    the transfer the fetch used to pay anyway."""
    B, four, _ = stack.shape
    kk = idx.shape[-1]
    return jnp.take_along_axis(
        stack, jnp.broadcast_to(idx[:, None, :], (B, four, kk)), axis=2
    )


def stage_batch_ranked(
    mesh: Mesh,
    features_batch: np.ndarray,  # [B, n_pad, C] hypothesis batch, same graph
    graph: ShardedGraph,
    params: PropagationParams,
    kk: int,
    batch_axes: Tuple[str, ...] = ("dp",),
):
    """Enqueue the sharded hypothesis batch, its cross-shard top-k merge,
    AND the [B, 4, kk] top-k diagnostic gather, returning
    ``(stack, diag, vals, idx)`` as in-flight DEVICE values — this
    function never synchronizes (JAX dispatch is async), so a caller can
    overlap host work with the mesh execution and fetch later.  Callers
    fetch only the top-k-sized values (``diag``/``vals``/``idx``); the
    full ``stack`` stays on device for lazy diagnostics.  The engine's
    ``analyze_batch`` fetches immediately; the serving dispatcher
    (rca_tpu/serve) parks the values in a batch handle and fetches one
    batch behind."""
    stack = stage_sharded(mesh, features_batch, graph, params, batch_axes)()
    vals, idx = sharded_topk(mesh, stack[:, 3], kk, batch_axes)
    return stack, batch_topk_diag(stack, idx), vals, idx


# ---------------------------------------------------------------------------
# Sharded one-shot resident session (ISSUE 8 satellite: close PR 6's
# named leftover — the sharded analyze path got the top-k fetch treatment
# in round 7 but still restaged the full feature batch per call)
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_lane0(fb, idx, rows):
    """Donated in-place row scatter into lane 0 of the sharded resident
    feature batch: the [U] index block and [U, C] row block are tiny
    replicated uploads (GRAPH_RULES ``delta_idx``/``delta_rows``); GSPMD
    routes each row to the shard that owns it.  Pad slots aim at the
    dummy node row with zero rows — already zero, so a no-op at any pad
    width (same convention as the dense resident scatter)."""
    return fb.at[0, idx].set(rows)


class ShardedResidentSession:
    """One graph's device-resident SHARDED analyze state: the multi-device
    twin of :class:`rca_tpu.engine.resident.ResidentSession`, pluggable
    into the same :class:`rca_tpu.engine.resident.ResidentCache` (the
    cache's lock serializes access; the donated buffer swap must not
    race).

    The session pins the sharded edge partition, the segscan layouts, and
    the [1, n_pad, C] feature batch on the mesh (shardings from
    :data:`rca_tpu.parallel.rules.GRAPH_RULES`); a repeat request diffs
    against a raw host mirror and ships O(changed rows) through the
    donated scatter instead of restaging the batch.

    Bit-parity contract: the resident buffer holds the SANITIZED features
    (the sharded kernel has no fused finite-mask pass, so the host-side
    ``finite_mask_rows_np`` guard runs per request over the raw input —
    the same values the restaged path would upload, row for row; NaN rows
    always diff as changed and re-scatter their zeroed form), so scores,
    rankings, and sanitized-row counts are bit-identical to restaging —
    property-tested in tests/test_resident.py.
    """

    def __init__(self, engine, key, dep_src, dep_dst):
        import numpy as _np

        n, num_features, n_edges, _ = key
        self.engine = engine
        self.key = key
        self._n = n
        self._num_features = num_features
        self._n_edges = n_edges
        # raw edges retained for the lazy causelens context (ISSUE 14)
        self._dep_src = _np.asarray(dep_src, _np.int32)
        self._dep_dst = _np.asarray(dep_dst, _np.int32)
        self._graph = engine._shard(n, dep_src, dep_dst)
        self._n_pad = self._graph.n_pad
        self._mesh = engine._exec_mesh
        p = engine.params
        seg = sharded_seg_layouts_for(self._graph)
        self._fn = _jitted_shard_fn(
            self._mesh, p.steps, p.decay, p.explain_strength,
            p.impact_bonus, ("dp",),
            use_segscan=seg is not None,
            error_contrast=p.error_contrast,
        )
        edge_names = ("src_local", "src_global", "dst_global", "mask")
        seg_names = ShardedSegLayouts._fields if seg is not None else ()
        shard_fns, _ = make_shard_and_gather_fns(
            match_partition_rules(
                GRAPH_RULES, ("features_batch", *edge_names, *seg_names),
            ),
            self._mesh,
        )
        self._shard_fb = shard_fns["features_batch"]
        self._args = tuple(
            shard_fns[name](getattr(self._graph, name))
            for name in edge_names
        )
        self._seg_args = tuple(
            shard_fns[name](x) for name, x in zip(seg_names, seg)
        ) if seg is not None else ()
        self._n_live = jnp.asarray(n, jnp.int32)
        aw, hw = p.weight_arrays()
        self._aw, self._hw = jnp.asarray(aw), jnp.asarray(hw)
        self._fb = None              # device [1, n_pad, C], sharded
        self._mirror = None          # np [n, C] RAW request mirror (diff base)
        # accounting (ResidentCache.stats + bench read these)
        self.requests = 0
        self.delta_requests = 0
        self.last_upload_rows = 0
        self.upload_bytes = 0
        self.fetch_bytes = 0

    def _fetch_topk(self, diag, vals, idx):
        """THE session's device-sync point: moves only the [4, kk]
        diagnostic gather and the top-k pair (resident-fetch lint — no
        full-[n_pad] fetch on this path)."""
        diag, vals, idx = jax.device_get((diag, vals, idx))
        self.fetch_bytes += diag.nbytes + vals.nbytes + idx.nbytes
        return diag, vals, idx

    def analyze(self, features, names, k: int):
        import time as _time

        from rca_tpu.engine.runner import finite_mask_rows_np, render_result

        t0 = _time.perf_counter()
        features = np.asarray(features, np.float32)
        clean, n_bad = finite_mask_rows_np(features)
        kk = min(k + 8, self._n_pad)
        changed = (
            None if self._mirror is None
            else np.flatnonzero(np.any(features != self._mirror, axis=1))
        )
        if changed is None or 2 * len(changed) >= self._n_pad:
            # first request over this graph — or the delta stopped paying:
            # stage the full sanitized batch once and pin it on the mesh
            fb_host = np.zeros(
                (1, self._n_pad, self._num_features), np.float32
            )
            fb_host[0, : self._n] = clean
            self._fb = self._shard_fb(fb_host)
            self._mirror = features.copy()
            self.last_upload_rows = self._n_pad
            self.upload_bytes += fb_host.nbytes
        elif len(changed):
            # delta request: O(changed rows) up, donated sharded scatter.
            # NaN rows diff as changed every time (NaN != NaN) and
            # re-ship their sanitized (zeroed) form — parity holds
            u = len(changed)
            u_pad = 1 << max(0, (u - 1).bit_length())
            idx_h = np.full(u_pad, self._n_pad - 1, np.int32)
            rows_h = np.zeros((u_pad, self._num_features), np.float32)
            idx_h[:u] = changed
            rows_h[:u] = clean[changed]
            with self._mesh:
                self._fb = _scatter_lane0(
                    self._fb, jnp.asarray(idx_h), jnp.asarray(rows_h)
                )
            # mirror updates only once the dispatch is accepted — a raise
            # above leaves the old mirror, so the next request re-diffs
            self._mirror[changed] = features[changed]
            self.delta_requests += 1
            self.last_upload_rows = u_pad
            self.upload_bytes += idx_h.nbytes + rows_h.nbytes
        else:
            # identical request (retry, hypothesis re-rank): zero upload
            self.delta_requests += 1
            self.last_upload_rows = 0
        self.requests += 1
        with self._mesh:
            stack = self._fn(
                self._fb, *self._args, self._n_live, self._aw, self._hw,
                *self._seg_args,
            )
        vals, idx = sharded_topk(self._mesh, stack[:, 3], kk)
        diag = batch_topk_diag(stack, idx)
        diag, vals, idx = self._fetch_topk(diag[0], vals[0], idx[0])
        latency_ms = (_time.perf_counter() - t0) * 1e3
        from rca_tpu.engine.runner import make_attribution_ctx

        return render_result(
            diag, vals, idx, names, self._n, k, latency_ms,
            self._n_edges, engine=self.engine.engine_tag,
            sanitized_rows=int(n_bad), stacked_dev=stack[0],
            attribution_ctx=make_attribution_ctx(
                features, self._dep_src, self._dep_dst,
                self.engine.params, names,
                self.engine.config.shape_buckets,
            ),
        )
