"""Device-mesh parallelism: sharded propagation, mesh helpers.

The reference has no distributed backend at all (SURVEY.md §2.9); this
package is the TPU-native scaling layer: node-sharded sparse propagation via
``shard_map`` with XLA collectives (all_gather / psum_scatter) riding ICI,
data-parallel hypothesis batching over the 'dp' axis, and mesh construction
helpers shared by the engine, the trainer, and the driver's multi-chip dry
run.
"""

from rca_tpu.parallel.distributed import initialize_distributed
from rca_tpu.parallel.mesh import make_mesh, make_multislice_mesh
from rca_tpu.parallel.sharded import (
    ShardedGraph,
    ShardedSegLayouts,
    shard_graph,
    sharded_propagate,
    sharded_propagate_full,
    sharded_seg_layouts_for,
    sharded_topk,
)

__all__ = [
    "initialize_distributed",
    "make_mesh",
    "make_multislice_mesh",
    "ShardedGraph",
    "ShardedSegLayouts",
    "shard_graph",
    "sharded_propagate",
    "sharded_propagate_full",
    "sharded_seg_layouts_for",
    "sharded_topk",
]
