"""Sharded streaming session: delta ticks over an sp-sharded resident buffer.

Round-3's :class:`rca_tpu.engine.streaming.StreamingSession` pinned the
feature matrix on ONE device and admitted it had no sharded twin
(VERDICT r3 item 3) — so 50k live ticks could not use the sharded engine
and the streaming row of BASELINE stopped at 10k single-chip.  This class
is that twin:

- the feature buffer lives sharded ``P("sp", None)`` across the mesh — no
  device ever holds the full [n_pad, C] matrix;
- each tick ships the (tiny, power-of-two-padded) delta rows replicated to
  every device; each shard applies the subset landing in its node block
  with a donated in-place scatter (out-of-block rows drop);
- propagation runs the same per-block kernel as the sharded analyze path
  (:func:`rca_tpu.parallel.sharded._propagate_block` — all_gather +
  psum_scatter over ICI), so streaming and one-shot scores cannot drift;
- the top-k is merged ON DEVICE: each shard reduces its block to k local
  candidates, one small all_gather over 'sp' carries k·sp candidates, and
  every device merges — the full score vector never leaves its shard;
- scatter + propagate + top-k run as ONE jitted dispatch per tick, same
  as the dense session (on tunneled TPUs each dispatch pays a host RTT).

Tick results are parity-locked to the dense session by
tests/test_parallel.py (same deltas → same ranking at 10k on the virtual
8-device mesh) and exercised by ``__graft_entry__.dryrun_multichip``.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rca_tpu.config import bucket_for
from rca_tpu.parallel.sharded import (
    ShardedGraph,
    ShardedSegLayouts,
    _propagate_block,
    shard_map_compat,
    sharded_seg_layouts_for,
)


@functools.lru_cache(maxsize=32)
def _jitted_tick_fn(
    mesh: Mesh, steps: int, decay: float, mu: float, beta: float,
    kk: int, block: int, use_segscan: bool = False,
    error_contrast: float = 0.0,
):
    """One compiled scatter+propagate+top-k per (mesh, params, k, block);
    delta width and edge shapes key jit's shape cache underneath.
    ``use_segscan`` appends the ten :class:`ShardedSegLayouts` arrays as
    trailing args (built ONCE at session init — the streaming path never
    pays the host-side layout sort per tick)."""

    def per_device(f_blk, idx, rows, src_l, src_g, dst_g, mask, n_live,
                   aw, hw, *seg_flat):
        # f_blk: [block, C] this shard's node rows (donated).
        # idx/rows: [U] / [U, C], replicated; rows outside this shard's
        # block are redirected to an out-of-bounds index and dropped.
        src_l, src_g = src_l[0], src_g[0]
        dst_g, mask = dst_g[0], mask[0]
        seg = (
            ShardedSegLayouts(*(x[0] for x in seg_flat))
            if seg_flat else None
        )
        blk = jax.lax.axis_index("sp")
        local = idx - blk * block
        inside = (local >= 0) & (local < block)
        safe = jnp.where(inside, local, block)       # block == OOB
        f_blk = f_blk.at[safe].set(rows, mode="drop")
        stack = _propagate_block(
            f_blk, src_l, src_g, dst_g, mask, n_live, aw, hw,
            steps=steps, decay=decay, mu=mu, beta=beta, seg=seg,
            error_contrast=error_contrast,
        )
        score_blk = stack[3]
        # distributed top-k merge (same shape as sharded.sharded_topk,
        # inlined so the whole tick is one dispatch)
        k_local = min(kk, block)
        v, i = jax.lax.top_k(score_blk, k_local)
        gi = i + blk * block
        vg = jax.lax.all_gather(v, "sp", tiled=True)     # [sp * k_local]
        ig = jax.lax.all_gather(gi, "sp", tiled=True)
        vv, pos = jax.lax.top_k(vg, kk)
        return f_blk, vv, jnp.take(ig, pos)

    n_seg = len(ShardedSegLayouts._fields) if use_segscan else 0
    shard_fn = shard_map_compat(
        per_device,
        mesh=mesh,
        in_specs=(
            P("sp", None),               # resident features
            P(), P(),                    # delta idx / rows (replicated)
            P("sp", None), P("sp", None), P("sp", None), P("sp", None),
            P(), P(), P(),
            *([P("sp", None)] * n_seg),
        ),
        out_specs=(P("sp", None), P(), P()),
        check_vma=False,
    )
    return jax.jit(shard_fn, donate_argnums=(0,))


from rca_tpu.engine.streaming import StreamingHostState


class ShardedStreamingSession(StreamingHostState):
    """Drop-in twin of :class:`rca_tpu.engine.streaming.StreamingSession`
    running on a :class:`rca_tpu.engine.sharded_runner.ShardedGraphEngine`
    mesh."""

    def __init__(
        self,
        names: Sequence[str],
        dep_src: np.ndarray,
        dep_dst: np.ndarray,
        num_features: int,
        engine=None,
        k: int = 5,
        clock=None,
    ):
        from rca_tpu.engine.sharded_runner import ShardedGraphEngine

        self.engine = engine or ShardedGraphEngine()
        self.names = list(names)
        self.k = k
        n = len(self.names)
        self._n = n
        self._num_features = num_features
        self.mesh = self.engine._exec_mesh
        graph: ShardedGraph = self.engine._shard(
            n, np.asarray(dep_src, np.int32), np.asarray(dep_dst, np.int32)
        )
        self._graph = graph
        self._n_pad = graph.n_pad
        self._block = graph.block
        self._n_live = jnp.asarray(n, jnp.int32)
        self._kk = min(k + 8, graph.n_pad)
        edge_sharding = NamedSharding(self.mesh, P("sp", None))
        self._edge_args = tuple(
            jax.device_put(jnp.asarray(x), edge_sharding)
            for x in (graph.src_local, graph.src_global,
                      graph.dst_global, graph.mask)
        )
        # segscan layouts built ONCE per pinned edge set (round 5: the
        # sharded tick inherits the round-4 segmented-scan kernels)
        seg = sharded_seg_layouts_for(graph)
        self._seg_args = tuple(
            jax.device_put(jnp.asarray(x), edge_sharding) for x in seg
        ) if seg is not None else ()
        p = self.engine.params
        self._aw, self._hw = (jnp.asarray(w) for w in p.weight_arrays())
        self._fn = _jitted_tick_fn(
            self.mesh, p.steps, p.decay, p.explain_strength, p.impact_bonus,
            self._kk, self._block, use_segscan=seg is not None,
            error_contrast=p.error_contrast,
        )
        # the sharded per-block kernel keeps XLA's fused noisy-OR (the
        # Pallas pair kernel has no shard_map twin); the registry's
        # sharded row records xla — or segscan, when the per-block twin
        # engaged above — so the kernel table shows the shape ran
        from rca_tpu.engine.registry import engaged_kernel

        self.kernel_path = engaged_kernel(
            self._n_pad, graph.src_local.shape[1], sharded=True,
        )
        self._feat_sharding = NamedSharding(self.mesh, P("sp", None))
        self._features = jax.device_put(
            jnp.zeros((self._n_pad, num_features), jnp.float32),
            self._feat_sharding,
        )
        self._init_host_state(clock)

    def set_all(self, features: np.ndarray) -> None:
        from rca_tpu.engine.runner import finite_mask_rows_np

        # finite-mask guard, host-side: this path stages from host anyway,
        # so zeroing poisoned rows before the upload matches the dense
        # session's fused on-device sanitize (same zeroed-row semantics)
        features, n_bad = finite_mask_rows_np(features)
        self._san_pending += n_bad
        f = np.zeros((self._n_pad, self._num_features), np.float32)
        f[: len(features)] = features
        self._features = jax.device_put(
            jnp.asarray(f), self._feat_sharding
        )
        self._pending.clear()
        self._pending_blocks.clear()
        self._bulk_upload = self._n_pad

    # -- tick ---------------------------------------------------------------
    def dispatch(self):
        """Enqueue one fused sharded tick; same dispatch/fetch contract as
        the dense session (``StreamingHostState.fetch`` renders the handle,
        ``tick()`` runs the two serially).  The sanitized-row count is
        host-side here (the delta rows stage from host anyway) so the
        handle carries a plain int."""
        from rca_tpu.engine.runner import finite_mask_rows_np
        from rca_tpu.engine.streaming import TickHandle

        t0 = self._clock()
        # pad slots target index n_pad: out of range for EVERY shard, so
        # the scatter drops them (quiet ticks run the same executable)
        u, u_pad, idx_h, rows_h = self._pack_pending(self._n_pad)
        # host-side twin of the dense session's fused sanitize: delta rows
        # carrying NaN/Inf zero out before the scatter ships them
        rows_h, n_bad = finite_mask_rows_np(rows_h)
        sanitized = n_bad + self._san_pending
        self._san_pending = 0
        with self.mesh:
            self._features, vals, idx = self._fn(
                self._features, jnp.asarray(idx_h), jnp.asarray(rows_h),
                *self._edge_args, self._n_live, self._aw, self._hw,
                *self._seg_args,
            )
        # deltas drop only once the dispatch is accepted (retryable on a
        # compile failure), matching the dense session's contract
        upload = self._account_upload(u_pad if u else 0)
        now = self._clock()
        return TickHandle(
            session=self, vals=vals, idx=idx, n_bad=sanitized,
            upload_rows=upload, dispatch_ms=(now - t0) * 1e3,
            dispatched_at=t0,
        )
