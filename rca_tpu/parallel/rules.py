"""Declarative partition rules: ONE table names how every graph tensor
shards (ISSUE 8 tentpole, the ``match_partition_rules`` /
``make_shard_and_gather_fns`` pattern from SNIPPETS.md [2][3]).

Before this module, :mod:`rca_tpu.parallel.sharded` hand-built its
``PartitionSpec`` tuples at three independent call sites (the shard_map
``in_specs``/``out_specs``, the distributed top-k, and the
``stage_sharded`` uploads) — adding one staged array meant editing all
of them in lockstep, and the serve pool's replica construction would
have added a fourth copy.  Here the layout lives in one rule table:

- :data:`GRAPH_RULES` maps tensor NAMES (regex) to partition specs, with
  the :data:`BATCH` placeholder standing for whatever axes the caller
  batches over (``("dp",)`` single-slice, ``("slice", "dp")``
  multi-slice);
- :func:`match_partition_rules` resolves a set of names against the
  table (the fmengine/EasyDeL shape: regex lookup, loud failure on an
  unmatched name, scalars never partitioned);
- :func:`make_shard_and_gather_fns` turns resolved specs into per-name
  device_put shard closures and host gather closures for one mesh;
- the serve pool derives replica device groups from the SAME table:
  :meth:`PartitionRuleSet.mesh_axes` names the axes a replica's
  sub-mesh is built over (``rca_tpu.serve.replica`` — replica
  construction, graph-tensor sharding, and device-group assignment all
  read one source of truth).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterable, Optional, Sequence, Tuple

#: placeholder axis name: substituted with the caller's batch axes when a
#: rule is resolved (one table serves single- and multi-slice meshes)
BATCH = "__batch__"


def resolve_batch_axes(
    spec: Tuple, batch_axes: Sequence[str] = ("dp",)
):
    """A rule's spec with :data:`BATCH` replaced by the actual batch axes
    (a tuple of axis names collapses to one mesh dimension — hypotheses
    spread over ``("slice", "dp")`` shard a single array axis)."""
    from jax.sharding import PartitionSpec as P

    batch = tuple(batch_axes) if len(batch_axes) > 1 else batch_axes[0]
    return P(*(batch if part == BATCH else part for part in spec))


@dataclasses.dataclass(frozen=True)
class PartitionRuleSet:
    """An ordered (regex, spec) table plus the mesh axes it talks about."""

    axes: Tuple[str, ...]                  # canonical axis order (dp, sp)
    rules: Tuple[Tuple[str, Tuple], ...]   # (pattern, spec parts)

    def spec_for(
        self, name: str, batch_axes: Sequence[str] = ("dp",)
    ):
        """The first matching rule's spec for ``name`` (loud failure on
        no match — a silently-replicated tensor is a perf bug that no
        test catches)."""
        for pattern, parts in self.rules:
            if re.search(pattern, name) is not None:
                return resolve_batch_axes(parts, batch_axes)
        raise ValueError(
            f"no partition rule matches tensor {name!r} "
            f"(rule table axes {self.axes}); add it to GRAPH_RULES"
        )

    def mesh_axes(self) -> Tuple[str, ...]:
        """The mesh axis names the table's specs place tensors over —
        the axes a replica's sub-mesh must expose (serve-pool replica
        construction reads these instead of hard-coding 'dp'/'sp')."""
        return self.axes


#: the graph-propagation layout (was: hand-built specs in sharded.py):
#: hypothesis batches over the batch axes, node blocks + per-shard edge
#: partitions + segscan layouts over 'sp', weights/scalars replicated.
GRAPH_RULES = PartitionRuleSet(
    axes=("dp", "sp"),
    rules=(
        # hypothesis feature batches: [B, n_pad, C] — batch over BATCH,
        # nodes over sp, channels replicated
        (r"(^|\.)(features_batch|fb|f_loc)$", (BATCH, "sp", None)),
        # per-shard edge partition rows: [sp, e_pad]
        (r"(^|\.)(src_local|src_global|dst_global|mask)$", ("sp", None)),
        # segscan layouts (ShardedSegLayouts fields): [sp, ...]
        (r"(^|\.)(dn|up)_(other|mask|flags|ends|has)$", ("sp", None)),
        # replicated scalars + weight vectors
        (r"(^|\.)(n_live|aw|hw|anomaly_w|hard_w)$", ()),
        # delta-scatter staging (sharded resident session): tiny [U]/[U, C]
        # blocks, replicated — the scatter itself lands them in the right
        # shard
        (r"(^|\.)(delta_idx|delta_rows)$", ()),
        # outputs: the [B, 4, n_pad] diagnostic stack (diag axis
        # replicated, nodes sharded), score vectors, merged top-k
        (r"(^|\.)stack$", (BATCH, None, "sp")),
        (r"(^|\.)scores$", (BATCH, "sp")),
        (r"(^|\.)topk_(vals|idx)$", (BATCH, None)),
    ),
)


def match_partition_rules(
    rules: PartitionRuleSet,
    names: Iterable[str],
    batch_axes: Sequence[str] = ("dp",),
) -> Dict[str, object]:
    """Resolve ``names`` against the rule table → {name: PartitionSpec}.

    The dict shape (rather than a pytree walk) fits this codebase: staged
    graph tensors are a flat named set, not a Flax parameter tree."""
    return {
        name: rules.spec_for(name, batch_axes) for name in names
    }


def make_shard_and_gather_fns(
    partition_specs: Dict[str, object],
    mesh,
) -> Tuple[Dict[str, object], Dict[str, object]]:
    """Per-name shard/gather closures for one mesh (SNIPPETS.md [3]).

    ``shard_fns[name](array)`` device_puts the array to its
    :class:`NamedSharding`; ``gather_fns[name](array)`` pulls a sharded
    device value back to one host ndarray (checkpoint/debug seam — the
    hot paths never gather full tensors, see the resident-fetch rule)."""
    import jax
    import numpy as np
    from jax.sharding import NamedSharding

    shard_fns: Dict[str, object] = {}
    gather_fns: Dict[str, object] = {}
    for name, spec in partition_specs.items():
        sharding = NamedSharding(mesh, spec)

        def shard_fn(x, _s=sharding):
            import jax.numpy as jnp

            return jax.device_put(jnp.asarray(x), _s)

        def gather_fn(x):
            return np.asarray(x)

        shard_fns[name] = shard_fn
        gather_fns[name] = gather_fn
    return shard_fns, gather_fns
