"""Multi-host bootstrap: one call before building a cross-host mesh.

The reference's "distributed backend" is HTTPS to the K8s API plus kubectl
subprocesses (SURVEY.md §2.9 — no NCCL/MPI anywhere); the TPU-native
equivalent is jax.distributed + XLA collectives, where every host runs the
same program and the runtime wires ICI (intra-slice) and DCN (cross-slice /
cross-host) underneath the mesh axes.  This module owns the one impure
step — process bootstrap — so the rest of :mod:`rca_tpu.parallel` stays
pure mesh/shard_map code.

Usage on a TPU pod (each host)::

    from rca_tpu.parallel import initialize_distributed, make_mesh
    info = initialize_distributed()          # auto-detects on TPU pods
    mesh = make_mesh([("dp", 4), ("sp", 2)]) # jax.devices() is now global

On CPU/GPU clusters pass coordinator_address/num_processes/process_id
explicitly (or set JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
JAX_PROCESS_ID).  Single-process runs are a no-op: the helper never makes
a laptop run worse.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import jax

from rca_tpu.config import env_int_opt, env_raw

_initialized = False


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> Dict[str, Any]:
    """Idempotently initialize jax.distributed and report the topology.

    Returns ``{process_index, process_count, local_device_count,
    global_device_count, initialized}`` — ``initialized`` is False when the
    run is single-process and no coordinator was configured (nothing to
    do), True when the distributed runtime is (or already was) up.
    """
    global _initialized

    coordinator_address = coordinator_address or env_raw(
        "JAX_COORDINATOR_ADDRESS"
    )
    if num_processes is None:
        num_processes = env_int_opt("JAX_NUM_PROCESSES", 1, 2**31 - 1)
    if process_id is None:
        process_id = env_int_opt("JAX_PROCESS_ID", 0, 2**31 - 1)

    # TPU pods auto-detect all three through the TPU metadata server; only
    # skip when nothing indicates a multi-process run at all.
    on_tpu_pod = bool(
        env_raw("TPU_WORKER_HOSTNAMES")
        or env_raw("MEGASCALE_COORDINATOR_ADDRESS")
    )

    # recognize a runtime someone else already brought up, so a second
    # bootstrap (ours or theirs) never re-initializes and raises
    try:
        from jax._src import distributed as _jdist

        runtime_up = _jdist.global_state.client is not None
    except Exception:  # pragma: no cover - private-API drift
        runtime_up = False
    _initialized = _initialized or runtime_up

    if not _initialized and (coordinator_address is not None or on_tpu_pod):
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
        _initialized = True

    if not _initialized:
        # STRICT no-op: querying jax.process_index() here would initialize
        # the backend and permanently foreclose a later real
        # jax.distributed.initialize in this process.  Device counts are
        # filled only when the backend is already up (then querying is
        # harmless), else left None.
        try:
            from jax._src import xla_bridge as _xb

            backend_up = bool(getattr(_xb, "_backends", None))
        except Exception:  # pragma: no cover - private-API drift
            backend_up = False
        return {
            "initialized": False,
            "process_index": 0,
            "process_count": 1,
            "local_device_count": (
                jax.local_device_count() if backend_up else None
            ),
            "global_device_count": (
                jax.device_count() if backend_up else None
            ),
        }

    return {
        "initialized": True,
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }
