"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axis_sizes: Sequence[Tuple[str, int]],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh from (axis, size) pairs, e.g. [("dp", 2), ("sp", 4)].

    Sizes must multiply to the device count used.  Axis order follows the
    argument order; lay fast-communicating axes (sp) innermost so their
    collectives ride ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(a for a, _ in axis_sizes)
    sizes = tuple(s for _, s in axis_sizes)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh needs {total} devices for axes {axis_sizes}, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(grid, names)
