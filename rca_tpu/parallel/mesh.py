"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    axis_sizes: Sequence[Tuple[str, int]],
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a mesh from (axis, size) pairs, e.g. [("dp", 2), ("sp", 4)].

    Sizes must multiply to the device count used.  Axis order follows the
    argument order; lay fast-communicating axes (sp) innermost so their
    collectives ride ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = tuple(a for a, _ in axis_sizes)
    sizes = tuple(s for _, s in axis_sizes)
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(
            f"mesh needs {total} devices for axes {axis_sizes}, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(grid, names)


def carve_device_groups(
    sizes: Sequence[int],
    devices: Optional[Sequence] = None,
) -> list:
    """Contiguous device groups for a serve-pool replica set.

    ``sizes[i]`` devices go to replica ``i``, carved in order so a
    sharded replica's group stays ICI-adjacent (same reasoning as
    :func:`make_mesh`'s innermost-axis rule).  When the host exposes
    fewer devices than the replica set asks for, groups WRAP AROUND and
    share devices — replicas then oversubscribe hardware (still correct;
    the serve pool's occupancy metrics make the sharing visible) instead
    of refusing to start, which is the right degradation for the
    single-device laptop running an 8-replica config.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        raise ValueError("carve_device_groups: no devices visible")
    groups = []
    cursor = 0
    for size in sizes:
        size = max(1, int(size))
        groups.append(
            [devices[(cursor + j) % len(devices)] for j in range(size)]
        )
        cursor = (cursor + size) % len(devices)
    return groups


def make_multislice_mesh(
    n_slices: int,
    per_slice_axes: Sequence[Tuple[str, int]],
    devices: Optional[Sequence] = None,
    slice_axis: str = "slice",
) -> Mesh:
    """Multi-slice mesh: an outer DCN axis over intra-slice ICI axes.

    For the 50k multi-cluster config (BASELINE.md) the service graph shards
    node-wise over the intra-slice 'sp' axis (collectives ride ICI) while
    independent hypothesis batches / cluster partitions spread across
    ``slice_axis`` (collectives ride DCN — keep cross-slice communication to
    the final top-k merge, never per propagation step).

    On real multi-slice hardware, group devices by ``device.slice_index``
    when available; on single-slice or CPU-virtual device sets, fall back to
    contiguous partitioning (the layout the driver's virtual-device dry run
    exercises).
    """
    devices = list(devices if devices is not None else jax.devices())
    per_slice = int(np.prod([s for _, s in per_slice_axes]))
    need = n_slices * per_slice
    if need > len(devices):
        raise ValueError(
            f"multislice mesh needs {need} devices "
            f"({n_slices} slices x {per_slice}), have {len(devices)}"
        )
    devices = devices[:need]
    slice_ids = {getattr(d, "slice_index", None) for d in devices}
    if None not in slice_ids and len(slice_ids) >= n_slices:
        by_slice: dict = {}
        for d in devices:
            by_slice.setdefault(d.slice_index, []).append(d)
        # keep every slice that can fill per_slice, then take the first
        # n_slices qualifying ones (an undersized early slice must not
        # abandon slice-aware grouping when later slices qualify)
        groups = [
            group[:per_slice]
            for _, group in sorted(by_slice.items())
            if len(group) >= per_slice
        ][:n_slices]
        if len(groups) == n_slices:
            devices = [d for group in groups for d in group]
    sizes = (n_slices, *(s for _, s in per_slice_axes))
    names = (slice_axis, *(a for a, _ in per_slice_axes))
    grid = np.asarray(devices).reshape(sizes)
    return Mesh(grid, names)
