"""Evidence-conditioned follow-up suggestions (VERDICT r2 item 5).

The reference regenerates 3-5 prioritized suggestions by LLM-analyzing the
evidence each suggestion-action just gathered (reference:
agents/mcp_coordinator.py:3370-3505 — though its `_generate_suggestions_
from_analysis` references an undefined variable at :3450 and always falls
back to generics).  This module does that flow right, in two tiers:

1. **Deterministic tier** — rule tables from evidence to targeted next
   actions, naming the objects the evidence implicates: log-pattern hits
   map to the K8s object that explains them (OOM kills → describe the pod
   + pull previous logs; connection refusals → topology agent on the
   callee), event reasons map to their diagnostic next hop (BackOff →
   previous logs of the pod; FailedScheduling → resource pressure),
   resource details map to state-specific checks (CrashLoopBackOff →
   previous logs; OOMKilled last state → memory limits), findings map to
   per-component checks.
2. **LLM tier** — when a capable (non-offline) provider is configured, it
   is asked for up to two ADDITIONAL suggestions conditioned on the same
   evidence, merged behind the deterministic ones (hermetic paths never
   need the network).

Different evidence therefore yields different, targeted suggestion lists;
the generic counts-derived list (structured.build_suggestions) remains only
as the final fallback when the evidence is unremarkable.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from rca_tpu.coordinator.structured import (
    build_suggestions,
    cluster_state_counts,
)
from rca_tpu.features.logscan import LOG_PATTERN_NAMES

_IDX = {name: i for i, name in enumerate(LOG_PATTERN_NAMES)}


def _sugg(text: str, priority: str, reasoning: str,
          action: Dict[str, Any]) -> Dict[str, Any]:
    return {
        "text": text, "priority": priority,
        "reasoning": reasoning, "action": action,
    }


def _dedupe_cap(tiers: List[List[Dict[str, Any]]],
                cap: int = 5) -> List[Dict[str, Any]]:
    """Merge suggestion tiers: TIER FIRST (specific > LLM > generic), then
    priority within a tier — a generic high-priority count-derived action
    must never outrank the targeted suggestion the evidence produced (that
    ordering was the round-2 failure mode).  Duplicate actions drop (first
    tier wins); capped."""
    rank = {"high": 0, "medium": 1, "low": 2}
    seen = set()
    out = []
    for tier in tiers:
        for s in sorted(tier, key=lambda s: rank.get(s.get("priority"), 3)):
            key = json.dumps(s.get("action", {}), sort_keys=True, default=str)
            if key in seen:
                continue
            seen.add(key)
            out.append(s)
    return out[:cap]


# -- deterministic tier: one rule table per evidence kind -------------------

def _from_log_patterns(pod: str, counts: np.ndarray,
                       was_previous: bool) -> List[Dict[str, Any]]:
    """Log-pattern hits → the object that explains them (reference rule
    intent: agents/logs_agent.py:451-477 recommendation table, turned into
    next ACTIONS instead of prose)."""
    c = np.asarray(counts)
    hit = lambda name: c[_IDX[name]] > 0  # noqa: E731
    out: List[Dict[str, Any]] = []
    if hit("oom_kill"):
        out.append(_sugg(
            f"Describe {pod} — check memory limits",
            "high",
            f"{int(c[_IDX['oom_kill']])} OOM-kill log hits: the container "
            "is being killed at its memory limit",
            {"type": "check_resource", "kind": "Pod", "name": pod},
        ))
    if hit("crash_loop") and not was_previous:
        out.append(_sugg(
            f"Check previous logs of {pod}",
            "high",
            "crash-loop hits — the failure reason is in the LAST "
            "container's output, not the current one",
            {"type": "check_logs", "pod_name": pod, "previous": True},
        ))
    if hit("connection_refused") or hit("timeout") or hit("dns_resolution"):
        names = [n for n in ("connection_refused", "timeout",
                             "dns_resolution") if hit(n)]
        out.append(_sugg(
            "Trace the failing dependency (topology analysis)",
            "high",
            f"{', '.join(names)} hits in {pod}: an upstream service is "
            "unreachable — the dependency graph localizes which",
            {"type": "run_agent", "agent_type": "topology"},
        ))
    if hit("image_pull"):
        out.append(_sugg(
            f"Inspect events of {pod}",
            "high",
            "image-pull errors carry the registry message in events",
            {"type": "check_events", "kind": "Pod", "name": pod},
        ))
    if hit("permission_denied") or hit("authentication"):
        out.append(_sugg(
            f"Describe {pod} — check service account / RBAC",
            "medium",
            "auth/permission errors in logs point at the pod's identity "
            "configuration",
            {"type": "check_resource", "kind": "Pod", "name": pod},
        ))
    if hit("volume_mount"):
        out.append(_sugg(
            f"Inspect events of {pod}",
            "medium",
            "volume-mount errors name the PVC/secret in events",
            {"type": "check_events", "kind": "Pod", "name": pod},
        ))
    if hit("config_error"):
        out.append(_sugg(
            "Run the resource analyzer (config references)",
            "medium",
            "config errors in logs — the resource sweep validates "
            "ConfigMap/Secret references",
            {"type": "run_agent", "agent_type": "resources"},
        ))
    return out


_EVENT_REASON_RULES = {
    # reason (substring, lowercase) → (action builder, priority, why)
    "oomkill": ("check_resource", "high",
                "OOM kills: the pod is over its memory limit"),
    "backoff": ("check_logs_previous", "high",
                "restart back-off: the crash reason is in the previous "
                "container's logs"),
    "unhealthy": ("check_logs", "high",
                  "failing probes: the probe failure detail is in the "
                  "pod's logs"),
    "failedscheduling": ("run_agent_resources", "high",
                         "unschedulable: check cluster resource pressure "
                         "and requests"),
    "failedmount": ("check_resource", "medium",
                    "mount failure: the volume/PVC detail is on the pod"),
    "failedcreate": ("check_resource", "medium",
                     "create failure: the controller detail narrows it"),
    "errimage": ("check_resource", "high",
                 "image errors: verify the image reference on the pod"),
    "failed": ("check_logs", "medium",
               "failure events: the pod logs carry the error"),
}


def _from_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Event reasons → targeted next hops, naming the involved objects."""
    out: List[Dict[str, Any]] = []
    seen_objects = set()
    for ev in events[:50]:
        reason = str(ev.get("reason", "")).lower()
        obj = ev.get("involved_object", ev.get("involvedObject", {})) or {}
        name = str(obj.get("name", ""))
        kind = str(obj.get("kind", "Pod"))
        if not name or (kind, name, reason) in seen_objects:
            continue
        for key, (act, priority, why) in _EVENT_REASON_RULES.items():
            if key in reason:
                seen_objects.add((kind, name, reason))
                if act in ("check_logs", "check_logs_previous") and kind != "Pod":
                    # logs live in pods; for a Job/Deployment/ReplicaSet
                    # event the safe next hop is describing the object
                    act = "check_resource"
                if act == "check_logs_previous":
                    action = {"type": "check_logs", "pod_name": name,
                              "previous": True}
                    text = f"Check previous logs of {name}"
                elif act == "check_logs":
                    action = {"type": "check_logs", "pod_name": name}
                    text = f"Check logs of {name}"
                elif act == "run_agent_resources":
                    action = {"type": "run_agent", "agent_type": "resources"}
                    text = f"Analyze resource pressure ({name} unschedulable)"
                else:
                    action = {"type": "check_resource", "kind": kind,
                              "name": name}
                    text = f"Describe {kind}/{name}"
                out.append(_sugg(
                    text, priority,
                    f"{ev.get('reason')} on {kind}/{name}: {why}", action,
                ))
                break
    return out


def _from_resource_details(kind: str, name: str,
                           details: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Resource state → state-specific checks (reference semantics:
    resource_analyzer per-group analyzers, as next actions).  ``check_logs``
    actions are only emitted for Pods — logs live in pods, and a
    Deployment/Job name is not a pod name."""
    out: List[Dict[str, Any]] = []
    is_pod = kind == "Pod"
    blob = json.dumps(details, default=str).lower()
    if "crashloopbackoff" in blob:
        out.append(_sugg(
            f"Check previous logs of {name}" if is_pod
            else f"Inspect events of {kind}/{name}",
            "high",
            f"{kind}/{name} is crash-looping — the cause is in the "
            "previous container's output",
            {"type": "check_logs", "pod_name": name, "previous": True}
            if is_pod
            else {"type": "check_events", "kind": kind, "name": name},
        ))
    if "oomkilled" in blob:
        out.append(_sugg(
            f"Review memory limits of {name}",
            "high",
            f"{kind}/{name} was OOMKilled — its limit is too low or it "
            "leaks; metrics show the usage curve",
            {"type": "run_agent", "agent_type": "metrics"},
        ))
    if "imagepull" in blob or "errimagepull" in blob:
        out.append(_sugg(
            f"Inspect events of {name}",
            "high",
            "image-pull failure — the registry error detail is in events",
            {"type": "check_events", "kind": "Pod", "name": name},
        ))
    if ('"ready": false' in blob or "unhealthy" in blob) and is_pod:
        out.append(_sugg(
            f"Check logs of {name}",
            "medium",
            f"{kind}/{name} is not ready — logs show why it fails its "
            "probes",
            {"type": "check_logs", "pod_name": name},
        ))
    restarts = 0
    try:
        for cs in (details.get("status", {}) or {}).get(
            "container_statuses", []
        ) or []:
            restarts = max(restarts, int(cs.get("restart_count", 0) or 0))
    except (AttributeError, TypeError, ValueError):
        pass
    if restarts > 0 and is_pod and not any(
        s["action"].get("type") == "check_logs" for s in out
    ):
        out.append(_sugg(
            f"Check logs of {name}",
            "medium",
            f"{restarts} restarts recorded — the termination reason is "
            "in the logs",
            {"type": "check_logs", "pod_name": name},
        ))
    return out


def _from_findings(findings: List[Dict[str, Any]],
                   agent_type: str) -> List[Dict[str, Any]]:
    """Analysis findings → per-component targeted checks."""
    out: List[Dict[str, Any]] = []
    for f in findings[:6]:
        comp = str(f.get("component", ""))
        # component strings look like "Pod/name", "Service/name", or bare
        name = comp.split("/", 1)[1] if "/" in comp else comp
        kind = comp.split("/", 1)[0] if "/" in comp else ""
        issue = str(f.get("issue", "")).lower()
        if not name:
            continue
        if any(w in issue for w in ("crash", "restart", "exit")):
            if kind in ("Pod", ""):
                action = {"type": "check_logs", "pod_name": name,
                          "previous": "crash" in issue}
                text = f"Check logs of {name}"
            else:
                # logs live in pods; for Service/Deployment findings the
                # object's events carry the crash detail
                action = {"type": "check_events", "kind": kind, "name": name}
                text = f"Inspect events of {kind}/{name}"
            out.append(_sugg(
                text, "high",
                f"{agent_type} finding: {f.get('issue')}", action,
            ))
        elif any(w in issue for w in ("event", "warning")):
            out.append(_sugg(
                f"Inspect events of {name}",
                "medium",
                f"{agent_type} finding: {f.get('issue')}",
                {"type": "check_events", "kind": kind or "Pod",
                 "name": name},
            ))
        elif any(w in issue for w in ("cpu", "memory", "oom", "limit")):
            out.append(_sugg(
                f"Describe {comp} — resource configuration",
                "medium",
                f"{agent_type} finding: {f.get('issue')}",
                {"type": "check_resource", "kind": kind or "Pod",
                 "name": name},
            ))
    # the correlation engine ranks causes from ALL signals: worth re-running
    # after any single-agent evidence changed the picture
    if findings and agent_type not in ("comprehensive", "correlated"):
        out.append(_sugg(
            "Re-run the comprehensive analysis",
            "low",
            f"{len(findings)} {agent_type} finding(s) gathered — re-fusing "
            "all signals updates the root-cause ranking",
            {"type": "run_agent", "agent_type": "comprehensive"},
        ))
    return out


# -- LLM tier ---------------------------------------------------------------

def _llm_followups(llm, evidence: Dict[str, Any],
                   namespace: str) -> List[Dict[str, Any]]:
    """Up to two ADDITIONAL LLM-proposed suggestions, conditioned on the
    gathered evidence (the reference's :3370 flow, minus its NameError).
    Offline/failed providers contribute nothing — and never break the
    deterministic tier (a provider 500 degrades to [])."""
    if llm is None:
        return []
    if getattr(getattr(llm, "provider", None), "name", "") == "offline":
        # the offline provider never emits suggestions; skip the round trip
        return []
    try:
        out = _llm_followups_inner(llm, evidence, namespace)
    except Exception:
        # any provider failure (network, 5xx, auth) must not cost the
        # caller the deterministic suggestions already computed
        return []
    return out


def _llm_followups_inner(llm, evidence: Dict[str, Any],
                         namespace: str) -> List[Dict[str, Any]]:
    out = llm.generate_structured_output(
        "Given this Kubernetes investigation evidence, propose up to 2 "
        "NEXT diagnostic actions as JSON "
        '{"suggestions": [{"text": "...", "priority": "high|medium|low", '
        '"reasoning": "...", "action": {"type": "run_agent|check_resource'
        '|check_logs|check_events|query", "...": "..."}}]}. '
        "Only include actions justified by the evidence.\nEvidence:\n"
        + json.dumps(evidence, default=str)[:5000],
        namespace=namespace, kind="followups",
    )
    if not isinstance(out, dict):
        return []
    raw = out.get("suggestions", [])
    good = []
    for s in raw[:2]:
        if (
            isinstance(s, dict) and s.get("text")
            and isinstance(s.get("action"), dict)
            and s["action"].get("type") in (
                "run_agent", "check_resource", "check_logs",
                "check_events", "query",
            )
        ):
            s.setdefault("priority", "medium")
            s.setdefault("reasoning", "model-proposed follow-up")
            good.append(s)
    return good


# -- entry point ------------------------------------------------------------

def evidence_followups(
    ctx,
    evidence: Dict[str, Any],
    llm=None,
    max_suggestions: int = 5,
) -> List[Dict[str, Any]]:
    """Targeted follow-ups from the evidence an action just gathered.

    ``evidence`` is a tagged union on ``kind``:

    - ``{"kind": "logs", "pod": str, "pattern_counts": array,
       "previous": bool}``
    - ``{"kind": "events", "events": [dict, ...]}``
    - ``{"kind": "resource", "resource_kind": str, "name": str,
       "details": dict}``
    - ``{"kind": "analysis", "agent_type": str, "findings": [dict, ...]}``

    Deterministic tier first (most specific), then the LLM tier, then the
    generic counts-derived list as backfill; deduped by action, capped."""
    kind = str(evidence.get("kind", ""))
    specific: List[Dict[str, Any]] = []
    if kind == "logs":
        specific = _from_log_patterns(
            str(evidence.get("pod", "")),
            np.asarray(evidence.get("pattern_counts",
                                    np.zeros(len(LOG_PATTERN_NAMES)))),
            bool(evidence.get("previous", False)),
        )
    elif kind == "events":
        specific = _from_events(list(evidence.get("events", [])))
    elif kind == "resource":
        specific = _from_resource_details(
            str(evidence.get("resource_kind", "Pod")),
            str(evidence.get("name", "")),
            evidence.get("details", {}) or {},
        )
    elif kind == "analysis":
        specific = _from_findings(
            list(evidence.get("findings", [])),
            str(evidence.get("agent_type", "")),
        )
    # skip the LLM round trip when the deterministic tier already fills the
    # cap — those entries outrank anything the LLM tier could add
    llm_tier = (
        [] if len(specific) >= max_suggestions
        else _llm_followups(llm, evidence, getattr(ctx, "namespace", ""))
    )
    generic = build_suggestions(cluster_state_counts(ctx))
    return _dedupe_cap([specific, llm_tier, generic], cap=max_suggestions)
