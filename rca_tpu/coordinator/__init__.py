"""Coordinator: orchestration, fusion, chat turns, hypothesis workflow."""

from rca_tpu.coordinator.core import RCACoordinator
from rca_tpu.coordinator.correlate import (
    correlate_deterministic,
    correlate_findings,
    correlate_jax,
    correlate_llm,
    default_backend,
    group_findings,
)
from rca_tpu.coordinator.structured import (
    build_suggestions,
    cluster_state_counts,
    format_structured_response,
    merge_llm_structured,
)

__all__ = [
    "RCACoordinator",
    "build_suggestions",
    "cluster_state_counts",
    "correlate_deterministic",
    "correlate_findings",
    "correlate_jax",
    "correlate_llm",
    "default_backend",
    "format_structured_response",
    "group_findings",
    "merge_llm_structured",
]
