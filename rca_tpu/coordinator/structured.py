"""Deterministic structured-response builder + suggestion scoring.

Parity with the reference's chat-turn backfill machinery (reference:
agents/mcp_coordinator.py — ``_format_structured_response`` :59-241: counts
by status/restart/exit-code, severity scoring CrashLoopBackOff=10 >
Error/Failed=8 > ImagePullBackOff=6 :192-201; severity-scored suggestion
builder :1424-1460; response-schema backfill :1370-1567).  The reference
computed these counts in per-pod Python loops; here they are vector ops
over the packed pod-feature array.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from rca_tpu.agents.base import AnalysisContext
from rca_tpu.features.schema import PodF

# waiting-reason severity ladder (reference: mcp_coordinator.py:192-201)
REASON_SCORES = {
    "CrashLoopBackOff": 10,
    "Error": 8,
    "Failed": 8,
    "OOMKilled": 8,
    "CreateContainerConfigError": 7,
    "ImagePullBackOff": 6,
    "ErrImagePull": 6,
    "Pending": 5,
    "NotReady": 4,
}


def cluster_state_counts(ctx: AnalysisContext) -> Dict[str, Any]:
    """Exact counts for the constrained chat prompt (the reference demanded
    the LLM restate these; we compute them once and never let the LLM
    invent them, reference: mcp_coordinator.py:1311-1333)."""
    fs = ctx.features
    pf = fs.pod_features
    P = fs.num_pods
    phases = {
        "Pending": int(pf[:, PodF.PHASE_PENDING].sum()),
        "Running": int(pf[:, PodF.PHASE_RUNNING].sum()),
        "Succeeded": int(pf[:, PodF.PHASE_SUCCEEDED].sum()),
        "Failed": int(pf[:, PodF.PHASE_FAILED].sum()),
        "Unknown": int(pf[:, PodF.PHASE_UNKNOWN].sum()),
    }
    problem_mask = (
        (pf[:, PodF.WAIT_CRASHLOOP] > 0)
        | (pf[:, PodF.WAIT_IMAGEPULL] > 0)
        | (pf[:, PodF.WAIT_CONFIG] > 0)
        | (pf[:, PodF.INIT_FAILED] > 0)
        | (pf[:, PodF.PHASE_FAILED] > 0)
        | (pf[:, PodF.PHASE_PENDING] > 0)
        | (pf[:, PodF.PHASE_UNKNOWN] > 0)
        | (pf[:, PodF.NOT_READY] > 0)
    )
    problem_idx = np.nonzero(problem_mask)[0]
    problems: List[Dict[str, Any]] = []
    for i in problem_idx.tolist():
        reasons = []
        if pf[i, PodF.WAIT_CRASHLOOP] > 0:
            reasons.append("CrashLoopBackOff")
        if pf[i, PodF.WAIT_IMAGEPULL] > 0:
            reasons.append("ImagePullBackOff")
        if pf[i, PodF.WAIT_CONFIG] > 0:
            reasons.append("CreateContainerConfigError")
        if pf[i, PodF.INIT_FAILED] > 0:
            reasons.append("InitContainerFailed")
        if pf[i, PodF.PHASE_FAILED] > 0:
            reasons.append("Failed")
        if pf[i, PodF.PHASE_PENDING] > 0:
            reasons.append("Pending")
        if pf[i, PodF.PHASE_UNKNOWN] > 0:
            reasons.append("Unknown")
        if not reasons and pf[i, PodF.NOT_READY] > 0:
            reasons.append("NotReady")
        score = max(
            (REASON_SCORES.get(x, 3) for x in reasons), default=3
        ) + min(int(pf[i, PodF.RESTARTS]), 5)
        problems.append(
            {
                "pod": fs.pod_names[i],
                "reasons": reasons,
                "restarts": int(pf[i, PodF.RESTARTS]),
                "severity_score": score,
            }
        )
    problems.sort(key=lambda p: -p["severity_score"])
    warning_events = sum(
        int(e.get("count", 1) or 1)
        for e in ctx.snapshot.events
        if e.get("type") != "Normal"
    )
    state = {
        "namespace": ctx.snapshot.namespace,
        "total_pods": P,
        "pods_by_phase": {k: v for k, v in phases.items() if v},
        "problem_pods": problems,
        "problem_pod_count": len(problems),
        "total_restarts": int(pf[:, PodF.RESTARTS].sum()),
        "warning_event_count": warning_events,
        "services": fs.service_names,
    }
    if ctx.snapshot.errors:
        # partial snapshot: keep the chat turn honest about what's missing —
        # presence + op names, not the full dump (the client buffer caps at
        # 100x300-char entries, far too much to embed in every LLM prompt)
        state["fetch_errors"] = ctx.snapshot.errors[-10:]
        state["fetch_error_count"] = len(ctx.snapshot.errors)
    return state


def format_structured_response(
    ctx: AnalysisContext, query: str = ""
) -> Dict[str, Any]:
    """The deterministic response the chat turn falls back to / backfills
    from (reference: mcp_coordinator.py:59-241)."""
    state = cluster_state_counts(ctx)
    points = [
        f"{state['total_pods']} pods in namespace "
        f"'{state['namespace']}': "
        + ", ".join(f"{v} {k}" for k, v in state["pods_by_phase"].items())
    ]
    if state["problem_pods"]:
        worst = state["problem_pods"][0]
        points.append(
            f"{state['problem_pod_count']} pod(s) show problems; most severe: "
            f"{worst['pod']} ({', '.join(worst['reasons'])}, "
            f"{worst['restarts']} restarts)"
        )
    else:
        points.append("No problem pods detected.")
    if state["warning_event_count"]:
        points.append(
            f"{state['warning_event_count']} warning events recorded."
        )
    sections = [
        {
            "title": "Problem pods",
            "content": [
                f"{p['pod']}: {', '.join(p['reasons'])} "
                f"(restarts {p['restarts']}, score {p['severity_score']})"
                for p in state["problem_pods"][:10]
            ] or ["none"],
        }
    ]
    summary = points[1] if state["problem_pods"] else points[0]
    return {
        "response_data": {"points": points, "sections": sections},
        "summary": summary,
        "suggestions": build_suggestions(state),
        "key_findings": [
            f"{p['pod']}: {', '.join(p['reasons'])}"
            for p in state["problem_pods"][:5]
        ],
        "cluster_state": state,
    }


def build_suggestions(
    state: Dict[str, Any], max_suggestions: int = 5
) -> List[Dict[str, Any]]:
    """Severity-scored next actions (reference: mcp_coordinator.py:1424-1460
    priority ladder; action types per :3173-3314 dispatch)."""
    out: List[Dict[str, Any]] = []
    for p in state["problem_pods"][:3]:
        reason = p["reasons"][0] if p["reasons"] else "NotReady"
        if reason in ("CrashLoopBackOff", "Failed", "Error"):
            out.append(
                {
                    "text": f"Check logs of {p['pod']}",
                    "priority": "high",
                    "reasoning": f"{reason} with {p['restarts']} restarts — "
                    "the crash cause is in the logs",
                    "action": {
                        "type": "check_logs",
                        "pod_name": p["pod"],
                        "previous": reason == "CrashLoopBackOff",
                    },
                }
            )
        elif reason in ("ImagePullBackOff", "ErrImagePull"):
            out.append(
                {
                    "text": f"Inspect events of {p['pod']}",
                    "priority": "high",
                    "reasoning": "image pull errors carry the registry "
                    "message in events",
                    "action": {
                        "type": "check_events",
                        "kind": "Pod",
                        "name": p["pod"],
                    },
                }
            )
        else:
            out.append(
                {
                    "text": f"Describe {p['pod']}",
                    "priority": "medium",
                    "reasoning": f"{reason} — the manifest/status detail "
                    "narrows the cause",
                    "action": {
                        "type": "check_resource",
                        "kind": "Pod",
                        "name": p["pod"],
                    },
                }
            )
    if state["warning_event_count"]:
        out.append(
            {
                "text": "Review warning events",
                "priority": "medium",
                "reasoning": f"{state['warning_event_count']} warning events "
                "may explain the symptoms",
                "action": {"type": "run_agent", "agent_type": "events"},
            }
        )
    out.append(
        {
            "text": "Run comprehensive analysis",
            "priority": "medium" if state["problem_pods"] else "low",
            "reasoning": "correlates metrics, logs, events, topology and "
            "traces into ranked root causes",
            "action": {"type": "run_agent", "agent_type": "comprehensive"},
        }
    )
    return out[:max_suggestions]


def merge_llm_structured(
    base: Dict[str, Any], llm_out: Optional[Dict[str, Any]]
) -> Dict[str, Any]:
    """Overlay LLM-provided fields on the deterministic response, keeping
    the deterministic value for anything missing/malformed (reference
    backfill: mcp_coordinator.py:1370-1567)."""
    if not isinstance(llm_out, dict):
        return base
    merged = dict(base)
    rd = llm_out.get("response_data")
    if isinstance(rd, dict) and rd.get("points"):
        merged["response_data"] = rd
    summary = llm_out.get("summary")
    if (
        isinstance(summary, str)
        and summary.strip()
        # the hermetic provider's canned placeholder must not displace the
        # counts-derived deterministic summary ("3 of 6 pods unhealthy...")
        # the backfill computed — placeholder text is worse than backfill
        and not summary.strip().lower().startswith("offline deterministic")
    ):
        merged["summary"] = summary.strip()
    sugg = llm_out.get("suggestions")
    if isinstance(sugg, list) and sugg:
        cleaned = []
        for s in sugg:
            if isinstance(s, dict) and s.get("text"):
                cleaned.append(
                    {
                        "text": str(s["text"]),
                        "priority": str(s.get("priority", "medium")),
                        "reasoning": str(s.get("reasoning", "")),
                        "action": s.get("action")
                        if isinstance(s.get("action"), dict)
                        else {"type": "query", "query": str(s["text"])},
                    }
                )
        if cleaned:
            merged["suggestions"] = cleaned
    kf = llm_out.get("key_findings")
    if isinstance(kf, list) and kf:
        merged["key_findings"] = [str(x) for x in kf if x][:10]
    return merged
