"""Evidence fusion: agent findings → ranked root causes.

Three backends behind one function (north star: ``RCA_BACKEND`` flag,
BASELINE.json):

- ``deterministic`` — group by component, rank by max-severity ×
  related-finding count (parity with the reference's legacy coordinator,
  reference: agents/coordinator.py:118-184);
- ``jax`` — the TPU engine: explain-away propagation over the service
  dependency graph (rca_tpu.engine), agent findings attached as supporting
  evidence per ranked service.  Scores differ from the deterministic rank
  but the grouped findings JSON is identical (parity gate: same groups,
  same members);
- ``llm`` — one LLM call over the flattened findings, as the reference's
  live path did (reference: agents/mcp_coordinator.py:666-760), with the
  deterministic result as fallback and as the structured skeleton.

All backends return the same schema:
``{root_causes: [{component, severity, score, finding_count, findings[]}],
groups: {component: [finding,...]}, backend, summary}``.
"""

from __future__ import annotations

import re
from typing import AbstractSet, Any, Dict, List, Optional

from rca_tpu.agents.base import AnalysisContext
from rca_tpu.config import env_str, explain_enabled
from rca_tpu.findings import attach_provenance, max_severity, severity_rank

_SERVICE_SUFFIX = re.compile(r"-[a-z0-9]{8,10}-[a-z0-9]{5}$")


def default_backend() -> str:
    return env_str("RCA_BACKEND", "jax", lower=True)


def _component_service(
    component: str, service_names: AbstractSet[str]
) -> Optional[str]:
    """Map 'Pod/frontend-7d8f675c7b-jk2x5' / 'Deployment/frontend' /
    'Service/frontend' onto a service name.  Pass a SET — with a list the
    membership probes make the grouping O(findings × services), which
    measured 2.6 s of a 3.1 s correlate at 10k services."""
    if "/" not in component:
        return component if component in service_names else None
    kind, name = component.split("/", 1)
    if name in service_names:
        return name
    if kind == "Pod":
        base = _SERVICE_SUFFIX.sub("", name)
        if base in service_names:
            return base
        # single-suffix forms (statefulset ordinals, bare replicaset hash)
        while "-" in base:
            base = base.rsplit("-", 1)[0]
            if base in service_names:
                return base
    return None


def group_findings(
    agent_results: Dict[str, Any]
) -> Dict[str, List[dict]]:
    """Flatten every agent's findings, tag source, group by component
    (reference: mcp_coordinator.py:666-698 flatten+tag; coordinator.py:118
    group-by-component)."""
    groups: Dict[str, List[dict]] = {}
    for agent_type, result in agent_results.items():
        findings = (
            result.get("findings", []) if isinstance(result, dict)
            else getattr(result, "findings", [])
        )
        for f in findings:
            tagged = {**f, "source": f.get("source", agent_type)}
            groups.setdefault(str(f.get("component", "unknown")), []).append(
                tagged
            )
    return groups


def _rank_entry(component: str, findings: List[dict], score: float) -> dict:
    return {
        "component": component,
        "severity": max_severity(f.get("severity", "info") for f in findings),
        "score": round(float(score), 4),
        "finding_count": len(findings),
        "findings": findings,
    }


def correlate_deterministic(
    agent_results: Dict[str, Any], top_k: int = 10
) -> Dict[str, Any]:
    groups = group_findings(agent_results)
    ranked = []
    for component, findings in groups.items():
        sev = max(severity_rank(f.get("severity", "info")) for f in findings)
        score = (sev + 1) * 10 + len(findings)
        ranked.append(_rank_entry(component, findings, score))
    ranked.sort(key=lambda r: (-r["score"], r["component"]))
    top = ranked[:top_k]
    summary = (
        f"{len(groups)} component(s) with findings; top root cause: "
        f"{top[0]['component']} ({top[0]['severity']})"
        if top else "No findings to correlate."
    )
    return {
        "root_causes": top,
        "groups": groups,
        "backend": "deterministic",
        "summary": summary,
    }


def correlate_jax(
    agent_results: Dict[str, Any],
    ctx: AnalysisContext,
    top_k: int = 10,
    engine=None,
) -> Dict[str, Any]:
    """TPU propagation ranking with agent findings as supporting evidence.

    Components that do not map onto a graph service (nodes, namespaces,
    HPAs…) are appended after the engine-ranked services, ordered by the
    deterministic severity rank.

    The engine is auto-selected per call (SURVEY §2.9: the sharded
    multi-device engine lives BEHIND this analyze boundary): sharded when
    ``RCA_SHARD`` asks for it or more than one device is visible,
    single-device otherwise; the result records which one ran.
    """
    from rca_tpu.engine import make_engine

    engine = engine or make_engine()
    fs = ctx.features
    src, dst = ctx.dep_edges
    result = engine.analyze_features(fs, src, dst, k=max(top_k, 5))

    groups = group_findings(agent_results)
    by_service: Dict[str, List[dict]] = {}
    unmapped: Dict[str, List[dict]] = {}
    service_set = frozenset(fs.service_names)
    for component, findings in groups.items():
        svc = _component_service(component, service_set)
        if svc is None:
            unmapped[component] = findings
        else:
            by_service.setdefault(svc, []).extend(findings)

    ranked: List[dict] = []
    for entry in result.ranked:
        svc = entry["component"]
        findings = by_service.pop(svc, [])
        if entry["score"] <= 0 and not findings:
            continue
        e = _rank_entry(svc, findings, entry["score"])
        e["anomaly"] = entry["anomaly"]
        e["explained_by_upstream"] = entry["explained_by_upstream"]
        e["downstream_impact"] = entry["downstream_impact"]
        ranked.append(e)
    # services the engine didn't surface but agents flagged
    leftovers = [
        _rank_entry(svc, findings, 0.0)
        for svc, findings in by_service.items()
    ] + [
        _rank_entry(comp, findings, 0.0)
        for comp, findings in unmapped.items()
    ]
    leftovers.sort(
        key=lambda r: (-severity_rank(r["severity"]), r["component"])
    )
    ranked.extend(leftovers)
    top = ranked[:top_k]
    summary = (
        f"TPU propagation over {result.n_services} services / "
        f"{result.n_edges} edges in {result.latency_ms:.1f} ms; top root "
        f"cause: {top[0]['component']}"
        if top else "No findings to correlate."
    )
    out = {
        "root_causes": top,
        "groups": groups,
        "backend": "jax",
        "engine": getattr(result, "engine", "single"),
        "summary": summary,
        "engine_latency_ms": result.latency_ms,
    }
    if explain_enabled():
        # causelens (ISSUE 14): the schema-versioned provenance block
        # rides the findings JSON — per-channel contributions, blame
        # paths, counterfactual evidence for every engine-ranked service.
        # An attribution failure degrades to a named error, never loses
        # the ranking (same honesty rule as the backend fallbacks).
        try:
            attach_provenance(out, result.attribution())
        except Exception as exc:  # noqa: BLE001 - degrade, but say so
            out["provenance_error"] = f"{type(exc).__name__}: {exc}"
    return out


def correlate_llm(
    agent_results: Dict[str, Any],
    llm_client,
    top_k: int = 10,
) -> Dict[str, Any]:
    """LLM fusion over the deterministic skeleton (reference:
    mcp_coordinator.py:698-733 prompt: group related findings, identify
    causal relationships, rank root causes)."""
    import json

    det = correlate_deterministic(agent_results, top_k=top_k)
    flat = [
        {k: f[k] for k in ("component", "issue", "severity", "source")
         if k in f}
        for findings in det["groups"].values()
        for f in findings
    ]
    prompt = (
        "Findings from Kubernetes analysis agents:\n"
        + json.dumps(flat[:80])
        + '\n\nGroup related findings, identify causal relationships, and '
        'rank root causes. Respond as JSON: {"root_causes": [{"component": '
        '"...", "reasoning": "...", "confidence": 0.0}], "summary": "..."}'
    )
    out = llm_client.generate_structured_output(prompt)
    if not isinstance(out, dict) or not out.get("root_causes"):
        return det
    order = {
        str(rc.get("component", "")): i
        for i, rc in enumerate(out["root_causes"])
        if isinstance(rc, dict)
    }
    reasons = {
        str(rc.get("component", "")): rc
        for rc in out["root_causes"]
        if isinstance(rc, dict)
    }
    ranked = sorted(
        det["root_causes"],
        key=lambda r: (order.get(r["component"], len(order)), -r["score"]),
    )
    for r in ranked:
        rc = reasons.get(r["component"])
        if rc:
            r["reasoning"] = str(rc.get("reasoning", ""))
            if isinstance(rc.get("confidence"), (int, float)):
                r["confidence"] = float(rc["confidence"])
    return {
        **det,
        "root_causes": ranked[:top_k],
        "backend": "llm",
        "summary": str(out.get("summary", det["summary"])),
    }


def correlate_findings(
    agent_results: Dict[str, Any],
    ctx: Optional[AnalysisContext] = None,
    backend: Optional[str] = None,
    llm_client=None,
    top_k: int = 10,
    engine=None,
) -> Dict[str, Any]:
    """Dispatch on backend; unusable backends degrade to deterministic.

    A degraded result carries ``fallback_from``/``fallback_reason`` so a
    caller (or a parity test) can tell "deterministic by choice" apart from
    "jax/llm crashed and we hid it" — the same honesty rule the cluster
    client applies to fetch errors."""
    requested = (backend or default_backend()).lower()
    backend = requested
    fallback_reason = None
    if backend == "jax":
        if ctx is None:
            fallback_reason = "no AnalysisContext for the jax engine"
            backend = "deterministic"
        else:
            try:
                return correlate_jax(
                    agent_results, ctx, top_k=top_k, engine=engine
                )
            except Exception as exc:  # noqa: BLE001 - degrade, but say so
                # a misconfigured RCA_SHARD (wrong device count, malformed
                # spec) is an OPERATOR error that must fail loudly, not
                # silently demote every analysis to the deterministic
                # correlator.  Lazy import: on a host where jax itself
                # cannot import, the ImportError stays INSIDE the degrade
                # path (this module is deliberately jax-free).
                try:
                    from rca_tpu.engine.sharded_runner import (
                        ShardConfigError,
                    )
                except Exception:  # noqa: BLE001 - any import failure
                    # (ImportError, jax version-mismatch RuntimeError,
                    # plugin init errors) means the loud-path class can't
                    # exist, so everything degrades
                    ShardConfigError = ()
                if isinstance(exc, ShardConfigError):
                    raise
                fallback_reason = f"{type(exc).__name__}: {exc}"
                backend = "deterministic"
    if backend == "llm":
        if llm_client is None:
            fallback_reason = "no LLM client configured"
            backend = "deterministic"
        else:
            try:
                return correlate_llm(agent_results, llm_client, top_k=top_k)
            except Exception as exc:  # noqa: BLE001 - degrade, but say so
                fallback_reason = f"{type(exc).__name__}: {exc}"
                backend = "deterministic"
    out = correlate_deterministic(agent_results, top_k=top_k)
    if fallback_reason is not None:
        out["fallback_from"] = requested
        out["fallback_reason"] = fallback_reason
    return out
