"""Guided hypothesis workflow: hypotheses → plan → steps → verdict → report.

Parity with the reference's interactive-session backend (reference:
agents/mcp_coordinator.py — ``generate_hypotheses`` :2232 (3-5 hypotheses
with confidence + investigation steps), ``get_investigation_plan`` :2377,
``execute_investigation_step`` :2542 (kubectl/logs/events per step kind),
``_analyze_investigation_evidence`` :2699 (supported/refuted/inconclusive +
confidence), ``_get_evidence_for_component`` :2857 (per-kind evidence),
``generate_root_cause_report`` :3026).  Every LLM-backed stage has a
deterministic twin so the workflow is fully functional offline.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from rca_tpu.features.logscan import LOG_PATTERN_NAMES, scan_text

# deterministic hypothesis templates keyed by symptom keywords
# (the reference asked the LLM; offline we derive from the finding itself)
_TEMPLATES = [
    (("crashloop", "crash", "restart"), [
        ("Application crashes on startup due to a missing or invalid "
         "dependency (config, secret, or reachable backend)", 0.6),
        ("Liveness probe is misconfigured and kills a healthy container",
         0.3),
        ("Container exits after OOM or resource exhaustion", 0.25),
    ]),
    (("imagepull", "image"), [
        ("Image tag does not exist in the registry", 0.5),
        ("Registry credentials (imagePullSecrets) are missing or invalid",
         0.4),
        ("Registry is unreachable from the node network", 0.2),
    ]),
    (("oom", "memory"), [
        ("Memory limit is set below the application's working set", 0.6),
        ("A memory leak grows the footprint until the limit is hit", 0.35),
    ]),
    (("pending", "schedul"), [
        ("No node has capacity for the pod's resource requests", 0.5),
        ("Node taints or affinity rules exclude every node", 0.35),
        ("A referenced PVC is unbound, blocking scheduling", 0.25),
    ]),
    (("selector", "endpoint", "no pods"), [
        ("Service selector labels do not match the workload's pod labels",
         0.6),
        ("The backing workload was never deployed or was scaled to zero",
         0.3),
    ]),
    (("config", "secret"), [
        ("A referenced ConfigMap/Secret does not exist in the namespace",
         0.6),
        ("The referenced key exists but holds a wrong/renamed value", 0.3),
    ]),
    (("cpu", "throttl"), [
        ("CPU limit is set below the workload's sustained demand", 0.55),
        ("A runaway loop consumes all available CPU", 0.35),
    ]),
    (("env", "environment variable"), [
        ("A required environment variable is not set in the pod spec", 0.65),
        ("The env var references a missing ConfigMap/Secret key", 0.3),
    ]),
]

_FALLBACK = [
    ("The component's configuration changed recently and broke it", 0.35),
    ("An upstream dependency of the component is failing", 0.3),
    ("The component is resource-starved (CPU, memory, or IO)", 0.25),
]


def _default_steps(component: str) -> List[Dict[str, Any]]:
    kind = component.split("/", 1)[0] if "/" in component else "Pod"
    name = component.split("/", 1)[1] if "/" in component else component
    steps = [
        {"description": f"Describe {kind} {name} and inspect its status",
         "type": "describe", "kind": kind, "name": name},
        {"description": f"Fetch recent events for {kind} {name}",
         "type": "events", "kind": kind, "name": name},
    ]
    if kind == "Pod":
        steps.insert(
            1,
            {"description": f"Read current and previous logs of {name}",
             "type": "logs", "name": name},
        )
    return steps


def generate_hypotheses(
    coord, component: str, finding: Dict[str, Any], namespace: str,
    investigation_id: str = "",
) -> List[Dict[str, Any]]:
    """3-5 hypotheses with confidence + investigation steps."""
    issue = str(finding.get("issue", "")).lower()
    evidence = _get_evidence_for_component(coord, component, namespace)

    llm_out = coord.llm.generate_structured_output(
        "Component: " + component + "\nFinding: "
        + json.dumps({k: finding.get(k) for k in ("issue", "severity",
                                                  "evidence")}, default=str)[:3000]
        + "\nEvidence: " + json.dumps(evidence, default=str)[:3000]
        + '\n\nPropose 3-5 root-cause hypotheses as JSON: {"hypotheses": '
        '[{"description": "...", "confidence": 0.0, "investigation_steps": '
        '["..."]}]}',
        kind="hypotheses",
    )
    hypotheses: List[Dict[str, Any]] = []
    for h in (llm_out or {}).get("hypotheses", []) or []:
        if isinstance(h, dict) and h.get("description"):
            steps = [
                {"description": str(s), "type": "describe",
                 "kind": component.split("/")[0], "name": component.split("/")[-1]}
                if isinstance(s, str) else s
                for s in h.get("investigation_steps", []) or []
            ]
            hypotheses.append(
                {
                    "description": str(h["description"]),
                    "confidence": float(h.get("confidence", 0.3) or 0.3),
                    "component": component,
                    "investigation_steps": steps or _default_steps(component),
                }
            )
    if not hypotheses:
        ranked = _FALLBACK
        for keywords, templates in _TEMPLATES:
            if any(k in issue for k in keywords):
                ranked = templates
                break
        hypotheses = [
            {
                "description": desc,
                "confidence": conf,
                "component": component,
                "investigation_steps": _default_steps(component),
            }
            for desc, conf in ranked
        ]
    hypotheses.sort(key=lambda h: -h["confidence"])
    hypotheses = hypotheses[:5]
    if coord.evidence is not None:
        for h in hypotheses:
            coord.evidence.log_hypothesis(
                investigation_id, component, h, evidence=evidence,
            )
    return hypotheses


def get_investigation_plan(
    coord, hypothesis: Dict[str, Any], namespace: str
) -> Dict[str, Any]:
    steps = hypothesis.get("investigation_steps") or _default_steps(
        str(hypothesis.get("component", "Pod/unknown"))
    )
    return {
        "hypothesis": hypothesis.get("description", ""),
        "component": hypothesis.get("component", ""),
        "steps": [
            {**s, "index": i, "status": "pending"}
            for i, s in enumerate(steps)
        ],
    }


def execute_investigation_step(
    coord, step: Dict[str, Any], hypothesis: Dict[str, Any],
    namespace: str, investigation_id: str = "",
) -> Dict[str, Any]:
    """Run one evidence-gathering step, then judge the hypothesis."""
    stype = str(step.get("type", "describe"))
    name = str(step.get("name", ""))
    kind = str(step.get("kind", "Pod"))
    try:
        if stype == "logs":
            current = coord.cluster.get_pod_logs(
                namespace, name, tail_lines=100
            )
            previous = ""
            from rca_tpu.resilience.policy import suppressed

            with suppressed("hypotheses.previous_logs"):
                previous = coord.cluster.get_pod_logs(
                    namespace, name, previous=True, tail_lines=100
                )
            result: Any = {"logs": current[-4000:],
                           "previous_logs": previous[-4000:]}
        elif stype == "events":
            result = coord.cluster.get_events(
                namespace,
                field_selector=(
                    f"involvedObject.kind={kind},involvedObject.name={name}"
                ),
            )[:30]
        else:  # describe / kubectl
            result = coord.cluster.get_resource_details(namespace, kind, name)
    except Exception as e:
        result = {"error": f"{type(e).__name__}: {e}"}

    verdict = _analyze_investigation_evidence(coord, hypothesis, step, result)
    if coord.evidence is not None:
        coord.evidence.log_investigation_step(
            investigation_id, str(hypothesis.get("component", "")),
            step, result=result, verdict=verdict,
        )
    return {"step": step, "result": result, "verdict": verdict}


def _analyze_investigation_evidence(
    coord, hypothesis: Dict[str, Any], step: Dict[str, Any], result: Any
) -> Dict[str, Any]:
    """supported / refuted / inconclusive + confidence (reference:
    mcp_coordinator.py:2699-2857)."""
    llm_out = coord.llm.generate_structured_output(
        "Hypothesis: " + str(hypothesis.get("description", ""))
        + "\nStep: " + str(step.get("description", ""))
        + "\nEvidence: " + json.dumps(result, default=str)[:4000]
        + '\n\nJudge the hypothesis. JSON: {"verdict": '
        '"supported|refuted|inconclusive", "confidence": 0.0, '
        '"reasoning": "..."}',
        kind="verdict",
    )
    verdict = (llm_out or {}).get("verdict")
    if verdict in ("supported", "refuted", "inconclusive"):
        return {
            "verdict": verdict,
            "confidence": float((llm_out or {}).get("confidence", 0.5) or 0.5),
            "reasoning": str((llm_out or {}).get("reasoning", "")),
        }
    # deterministic judgement: keyword overlap between hypothesis and
    # error-classed evidence
    text = json.dumps(result, default=str).lower()
    counts = scan_text(text)
    hit_classes = {
        LOG_PATTERN_NAMES[i] for i in range(len(counts)) if counts[i] > 0
    }
    desc = str(hypothesis.get("description", "")).lower()
    signal_map = {
        "oom_kill": ("memory", "oom"),
        "image_pull": ("image", "registry", "tag"),
        "config_error": ("config", "secret"),
        "connection_refused": ("dependency", "backend", "upstream",
                               "reachable"),
        "crash_loop": ("crash", "startup"),
        "permission_denied": ("rbac", "permission"),
        "dns_resolution": ("dns",),
        "timeout": ("timeout", "slow"),
        "authentication": ("credential", "auth", "token"),
        "exception": ("crash", "error", "broke", "failing", "variable",
                      "dependency"),
    }
    supported = any(
        any(k in desc for k in signal_map.get(cls, ()))
        for cls in hit_classes
    )
    if supported:
        return {
            "verdict": "supported",
            "confidence": 0.6,
            "reasoning": "Evidence contains error classes matching the "
            f"hypothesis: {sorted(hit_classes)}",
        }
    if hit_classes:
        return {
            "verdict": "inconclusive",
            "confidence": 0.4,
            "reasoning": "Evidence shows errors "
            f"({sorted(hit_classes)}) but not the hypothesized class",
        }
    return {
        "verdict": "inconclusive",
        "confidence": 0.3,
        "reasoning": "No error signal in the gathered evidence",
    }


def _get_evidence_for_component(
    coord, component: str, namespace: str
) -> Dict[str, Any]:
    """Per-kind evidence gathering (reference: mcp_coordinator.py:2857-3016)."""
    kind, _, name = component.partition("/")
    kind = kind or "Pod"
    out: Dict[str, Any] = {"component": component}
    try:
        if kind.lower() == "pod":
            pod = coord.cluster.get_pod(namespace, name)
            out["status"] = (pod or {}).get("status", {})
            from rca_tpu.resilience.policy import suppressed

            with suppressed("hypotheses.log_tail"):
                out["log_tail"] = coord.cluster.get_pod_logs(
                    namespace, name, tail_lines=50
                )[-2000:]
        elif kind.lower() == "deployment":
            out["deployment"] = coord.cluster.get_deployment(namespace, name)
        elif kind.lower() == "service":
            out["service"] = coord.cluster.get_service(namespace, name)
            out["endpoints"] = [
                e for e in coord.cluster.get_endpoints(namespace)
                if e.get("metadata", {}).get("name") == name
            ]
        elif kind.lower() in ("pvc", "persistentvolumeclaim"):
            out["pvc"] = coord.cluster.get_pvc(namespace, name)
        else:
            out["details"] = coord.cluster.get_resource_details(
                namespace, kind, name
            )
        out["events"] = coord.cluster.get_events(
            namespace,
            field_selector=(
                f"involvedObject.kind={kind},involvedObject.name={name}"
            ),
        )[:20]
        nodes = coord.cluster.get_nodes()
        out["cluster_nodes"] = [
            {
                "name": n.get("metadata", {}).get("name", ""),
                "ready": any(
                    c.get("type") == "Ready" and c.get("status") == "True"
                    for c in n.get("status", {}).get("conditions", []) or []
                ),
            }
            for n in nodes
        ]
    except Exception as e:
        out["error"] = f"{type(e).__name__}: {e}"
    return out


def generate_root_cause_report(coord, session: Dict[str, Any]) -> str:
    """Markdown report from the guided session's history (reference:
    mcp_coordinator.py:3026-3116)."""
    component = str(session.get("component", "unknown"))
    hypothesis = session.get("accepted_hypothesis") or {}
    steps = session.get("steps", [])
    lines = [
        f"# Root Cause Report — {component}",
        "",
        "## Conclusion",
        f"**{hypothesis.get('description', 'No hypothesis accepted')}**",
        f"(confidence {hypothesis.get('confidence', 0):.0%})"
        if hypothesis else "",
        "",
        "## Investigation trail",
    ]
    for i, s in enumerate(steps):
        verdict = s.get("verdict", {})
        lines.append(
            f"{i + 1}. {s.get('step', {}).get('description', 'step')} → "
            f"**{verdict.get('verdict', 'n/a')}** "
            f"({verdict.get('confidence', 0):.0%}) — "
            f"{verdict.get('reasoning', '')}"
        )
    finding = session.get("finding")
    if finding:
        lines += [
            "",
            "## Originating finding",
            f"- {finding.get('issue', '')} [{finding.get('severity', '')}]",
            f"- Recommendation: {finding.get('recommendation', '')}",
        ]
    llm_text = coord.llm.generate_completion(
        "Polish this root-cause report, keeping all facts:\n"
        + "\n".join(lines),
        kind="report",
    )
    if llm_text and not llm_text.startswith("Offline analysis"):
        return llm_text
    return "\n".join(line for line in lines if line is not None)
