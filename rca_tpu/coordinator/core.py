"""RCACoordinator: session registry, analysis pipelines, chat turns,
suggestion engine.

Capability parity with the reference's MCPCoordinator (reference:
agents/mcp_coordinator.py — session registry :243-975, per-signal runners
:322-620, comprehensive pipeline :624-665, ``process_user_query`` :1174,
suggestion dispatch :3152-3314, suggestion regeneration :3370-3505) with the
structural fixes SURVEY.md §2.2 calls out: one definition per method (the
reference shadowed three), the comprehensive fan-out shares ONE snapshot
instead of re-fetching per agent, and fusion runs on the TPU engine by
default (``RCA_BACKEND``).
"""

from __future__ import annotations

import datetime
import json
import uuid
from typing import Any, Dict, List, Optional

from rca_tpu.agents import ALL_AGENT_TYPES, AnalysisContext, make_agents
from rca_tpu.agents.llm_agent import make_llm_agents
from rca_tpu.coordinator import hypotheses as hypo
from rca_tpu.coordinator.correlate import correlate_findings, default_backend
from rca_tpu.coordinator.structured import (
    format_structured_response,
    merge_llm_structured,
)
from rca_tpu.llm import LLMClient, OfflineProvider
from rca_tpu.obslog import EvidenceLogger


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


def _degraded_note(errors: List[Dict[str, str]]) -> str:
    ops = sorted({e.get("op", "?") for e in errors})
    return (
        f"⚠ analysis ran against PARTIAL cluster state — "
        f"{len(errors)} fetch failure(s) ({', '.join(ops[:5])})"
    )


class RCACoordinator:
    def __init__(
        self,
        cluster_client,
        llm_client: Optional[LLMClient] = None,
        evidence_logger: Optional[EvidenceLogger] = None,
        backend: Optional[str] = None,
        use_llm_agents: bool = False,
        engine=None,
        serve=None,
        tenant: Optional[str] = None,
    ):
        self.cluster = cluster_client
        self.llm = llm_client or LLMClient(provider=OfflineProvider())
        self.evidence = evidence_logger
        self.backend = backend or default_backend()
        # ``serve``: a rca_tpu.serve.ServeClient (or a ServeLoop) — the
        # correlation analyses then ride the shared multi-tenant serving
        # queue instead of owning the device exclusively, so concurrent
        # coordinators coalesce into batched dispatches (SERVING.md).
        # Mutually exclusive with a directly-pinned ``engine``.
        if serve is not None:
            if engine is not None:
                raise ValueError("pass either engine= or serve=, not both")
            from rca_tpu.serve.client import ServeClient
            from rca_tpu.serve.loop import ServeLoop

            if isinstance(serve, ServeLoop):
                serve = ServeClient(serve)
            engine = serve.as_engine(
                tenant=tenant or f"coordinator-{uuid.uuid4().hex[:6]}"
            )
        self.serve = serve
        self.engine = engine
        self.use_llm_agents = use_llm_agents
        self.agents = make_agents()
        self._llm_agents: Optional[Dict[str, Any]] = None
        self.analyses: Dict[str, Dict[str, Any]] = {}

    # -- session registry (reference: mcp_coordinator.py:243-975) ----------
    def init_analysis(
        self, analysis_type: str, namespace: str, **config: Any
    ) -> str:
        analysis_id = str(uuid.uuid4())
        self.analyses[analysis_id] = {
            "id": analysis_id,
            "config": {
                "type": analysis_type, "namespace": namespace, **config,
            },
            "status": "initialized",
            "started_at": _now(),
            "results": {},
            "summary": "",
        }
        return analysis_id

    def get_analysis_status(self, analysis_id: str) -> Dict[str, Any]:
        a = self.analyses.get(analysis_id)
        if a is None:
            return {"error": f"unknown analysis {analysis_id}"}
        return {
            "id": a["id"], "status": a["status"],
            "config": a["config"], "started_at": a["started_at"],
        }

    def list_analyses(self) -> List[Dict[str, Any]]:
        return [self.get_analysis_status(aid) for aid in self.analyses]

    def get_analysis_results(self, analysis_id: str) -> Dict[str, Any]:
        a = self.analyses.get(analysis_id)
        if a is None:
            return {"error": f"unknown analysis {analysis_id}"}
        return a

    # -- context capture -----------------------------------------------------
    def capture(self, namespace: str) -> AnalysisContext:
        return AnalysisContext.capture(self.cluster, namespace)

    def _agent_for(self, agent_type: str):
        if self.use_llm_agents:
            # built once; tools bind per-analysis to the snapshot namespace
            if self._llm_agents is None:
                self._llm_agents = make_llm_agents(
                    self.llm, cluster_client=self.cluster
                )
            return self._llm_agents[agent_type]
        return self.agents[agent_type]

    # -- analysis runners ----------------------------------------------------
    def run_analysis(
        self,
        analysis_type: str,
        namespace: str,
        ctx: Optional[AnalysisContext] = None,
        **config: Any,
    ) -> Dict[str, Any]:
        """Run one signal agent or the comprehensive pipeline.  Returns the
        analysis record (registry entry) with ``results`` filled."""
        analysis_id = self.init_analysis(analysis_type, namespace, **config)
        record = self.analyses[analysis_id]
        record["status"] = "running"
        try:
            ctx = ctx or self.capture(namespace)
            if analysis_type == "comprehensive":
                record["results"] = self._run_comprehensive(ctx)
                # the cross-agent summary carries the degraded-state note;
                # fall back to the fusion one-liner
                record["summary"] = (
                    record["results"].get("summary")
                    or record["results"]["correlated"]["summary"]
                )
            elif analysis_type in ALL_AGENT_TYPES:
                res = self._agent_for(analysis_type).analyze(ctx)
                record["results"][analysis_type] = res.to_dict()
                record["summary"] = res.summary
            else:
                raise ValueError(f"unknown analysis type: {analysis_type}")
            record["status"] = "completed"
            # degraded-mode honesty for EVERY analysis type: a snapshot
            # captured through fetch failures is PARTIAL — say so instead
            # of letting an RBAC error read as "no issues detected"
            if ctx.snapshot.errors:
                note = _degraded_note(ctx.snapshot.errors)
                record["results"]["degraded"] = {
                    "errors": ctx.snapshot.errors, "note": note,
                }
                record["summary"] = f"{note}. {record['summary']}"
        except Exception as e:
            record["status"] = "failed"
            record["error"] = f"{type(e).__name__}: {e}"
        record["finished_at"] = _now()
        return record

    def _run_comprehensive(self, ctx: AnalysisContext) -> Dict[str, Any]:
        """All six signals over ONE shared snapshot, then fusion + summary
        (reference ran them serially re-fetching state each time,
        mcp_coordinator.py:624-665).  Per-stage latency recorded under
        ``results["profile"]``."""
        from rca_tpu.obslog.profiling import StageTimer, maybe_jax_profile

        timer = StageTimer()
        results: Dict[str, Any] = {}
        with timer.stage("features"):
            ctx.features  # materialize the shared packed arrays once
        with timer.stage("graph"):
            ctx.graph
            ctx.dep_edges
        for agent_type in ALL_AGENT_TYPES:
            with timer.stage(f"agent.{agent_type}"):
                res = self._agent_for(agent_type).analyze(ctx)
            results[agent_type] = res.to_dict()
        with timer.stage("correlate"), maybe_jax_profile("correlate"):
            correlated = correlate_findings(
                results, ctx=ctx, backend=self.backend, llm_client=self.llm,
                engine=self.engine,
            )
        results["correlated"] = correlated
        with timer.stage("summary"):
            results["summary"] = self.generate_summary(results, ctx)
        results["profile"] = timer.report()
        return results

    # -- summaries -----------------------------------------------------------
    def generate_summary(
        self, results: Dict[str, Any], ctx: Optional[AnalysisContext] = None
    ) -> str:
        """Condensed cross-agent summary.  LLM-written when a capable
        provider exists; deterministic rollup otherwise (reference:
        mcp_coordinator.py:846-926)."""
        correlated = results.get("correlated", {})
        top = correlated.get("root_causes", [])[:3]
        det = "; ".join(
            f"{r['component']} ({r['severity']}, {r['finding_count']} findings)"
            for r in top
        )
        det_summary = (
            f"Top root causes: {det}." if det else "No issues detected."
        )
        condensed = {
            agent: {
                "summary": res.get("summary", ""),
                "finding_count": len(res.get("findings", [])),
            }
            for agent, res in results.items()
            if isinstance(res, dict) and "findings" in res
        }
        text = self.llm.generate_completion(
            "Summarize this Kubernetes analysis in 3 sentences for an "
            "operator. Root causes: " + json.dumps(top and [
                {k: r[k] for k in ("component", "severity", "finding_count")}
                for r in top
            ]) + "\nPer-agent: " + json.dumps(condensed),
            kind="summary",
        )
        if text and not text.startswith("Offline analysis"):
            return text
        return det_summary

    def generate_summary_from_query(
        self, query: str, response: Dict[str, Any]
    ) -> str:
        """Title-style one-liner for a new investigation (reference:
        mcp_coordinator.py:768-840)."""
        text = self.llm.generate_completion(
            "Write a 6-10 word investigation title for this Kubernetes "
            f"question: {query!r}. Answer summary: "
            f"{response.get('summary', '')[:200]}",
            kind="title",
        )
        if text and not text.startswith("Offline analysis"):
            return text.strip().strip('"')[:80]
        return (query.strip().rstrip("?") or "Investigation")[:80]

    # -- chat turn (reference: mcp_coordinator.py:1174-1567) -----------------
    def process_user_query(
        self,
        query: str,
        namespace: str,
        previous_findings: Optional[List[str]] = None,
        ctx: Optional[AnalysisContext] = None,
    ) -> Dict[str, Any]:
        ctx = ctx or self.capture(namespace)
        base = format_structured_response(ctx, query)
        state = base["cluster_state"]
        prompt = (
            "You are a Kubernetes RCA assistant. Cluster state (EXACT "
            "counts — do not invent numbers):\n"
            + json.dumps(state)
            + ("\nAccumulated findings so far:\n"
               + json.dumps(previous_findings[-10:])
               if previous_findings else "")
            + f"\n\nUser question: {query}\n\n"
            'Respond as JSON: {"response_data": {"points": [...], '
            '"sections": [{"title": "...", "content": [...]}]}, '
            '"summary": "...", "suggestions": [{"text": "...", "priority": '
            '"high|medium|low", "reasoning": "...", "action": {"type": '
            '"run_agent|check_resource|check_logs|check_events|query", '
            '...}}], "key_findings": [...]}'
        )
        llm_out = self.llm.generate_structured_output(
            prompt, user_query=query, namespace=namespace, kind="chat_turn",
        )
        merged = merge_llm_structured(base, llm_out)
        merged["namespace"] = namespace
        merged["query"] = query
        merged["timestamp"] = _now()
        return merged

    # -- suggestion engine (reference: mcp_coordinator.py:3152-3505) ---------
    def process_suggestion(
        self,
        action: Dict[str, Any],
        namespace: str,
        previous_findings: Optional[List[str]] = None,
        ctx: Optional[AnalysisContext] = None,
    ) -> Dict[str, Any]:
        """Dispatch on the 5 action types; every branch returns
        ``{response, evidence, suggestions, key_findings}``."""
        atype = str(action.get("type", "query"))
        if atype == "run_agent":
            return self._suggest_run_agent(action, namespace, ctx)
        if atype == "check_resource":
            return self._suggest_check_resource(action, namespace, ctx)
        if atype == "check_logs":
            return self._suggest_check_logs(action, namespace, ctx)
        if atype == "check_events":
            return self._suggest_check_events(action, namespace, ctx)
        # query fallthrough (reference: :3301-3314)
        out = self.process_user_query(
            str(action.get("query", action.get("text", ""))),
            namespace, previous_findings, ctx=ctx,
        )
        return {
            "response": out["response_data"],
            "evidence": {"cluster_state": out["cluster_state"]},
            # free-text queries carry no targeted evidence; the tag keeps
            # the five-branch contract and routes post-action regeneration
            # to the generic tier explicitly
            "evidence_tag": {"kind": "query",
                             "key_findings": out["key_findings"][:5]},
            "suggestions": out["suggestions"],
            "key_findings": out["key_findings"],
        }

    def _followups(
        self, ctx: AnalysisContext, evidence: Dict[str, Any]
    ) -> List[Dict[str, Any]]:
        """Evidence-conditioned follow-ups (coordinator.followups): the
        deterministic rule tier reads the gathered evidence, an optional
        LLM tier adds up to two more, generics only backfill.  The
        round-2 version ignored its evidence argument entirely — every
        branch returned the same counts-derived list (VERDICT item 5)."""
        from rca_tpu.coordinator.followups import evidence_followups

        return evidence_followups(ctx, evidence, llm=self.llm)

    def _analyze_evidence_text(
        self, what: str, payload: Any, question: str
    ) -> str:
        text = self.llm.generate_completion(
            f"Analyze this Kubernetes {what} and answer: {question}\n"
            + json.dumps(payload, default=str)[:6000],
            kind=f"suggestion_{what}",
        )
        if text and not text.startswith("Offline analysis"):
            return text
        return f"Gathered {what}; see evidence."

    def _suggest_run_agent(self, action, namespace, ctx) -> Dict[str, Any]:
        agent_type = str(action.get("agent_type", "comprehensive"))
        ctx = ctx or self.capture(namespace)
        record = self.run_analysis(agent_type, namespace, ctx=ctx)
        results = record.get("results", {})
        if agent_type == "comprehensive":
            correlated = results.get("correlated", {})
            points = [
                f"{r['component']}: {r['severity']} "
                f"({r['finding_count']} findings)"
                for r in correlated.get("root_causes", [])[:5]
            ]
            key_findings = points[:5]
        else:
            res = results.get(agent_type, {})
            points = [
                f"{f['component']}: {f['issue']} [{f['severity']}]"
                for f in res.get("findings", [])[:8]
            ]
            key_findings = points[:5]
        flat_findings = [
            f
            for r in results.values()
            if isinstance(r, dict)
            for f in r.get("findings", [])
        ]
        tag = {
            "kind": "analysis", "agent_type": agent_type,
            "findings": flat_findings,
        }
        return {
            "response": {
                "points": points or ["No findings."],
                "sections": [],
            },
            "evidence": {"analysis": results},
            "evidence_tag": tag,
            "suggestions": self._followups(ctx, tag),
            "key_findings": key_findings,
        }

    def _suggest_check_resource(self, action, namespace, ctx) -> Dict[str, Any]:
        kind = str(action.get("kind", "Pod"))
        name = str(action.get("name", ""))
        details = self.cluster.get_resource_details(namespace, kind, name)
        analysis = self._analyze_evidence_text(
            "resource", details, f"what is wrong with {kind}/{name}?"
        )
        ctx = ctx or self.capture(namespace)
        tag = {
            "kind": "resource", "resource_kind": kind, "name": name,
            "details": details,
        }
        return {
            "response": {"points": [analysis], "sections": []},
            "evidence": {f"{kind}/{name}": details},
            "evidence_tag": tag,
            "suggestions": self._followups(ctx, tag),
            "key_findings": [f"Inspected {kind}/{name}"],
        }

    def _suggest_check_logs(self, action, namespace, ctx) -> Dict[str, Any]:
        pod = str(action.get("pod_name", action.get("name", "")))
        logs = self.cluster.get_pod_logs(
            namespace, pod,
            previous=bool(action.get("previous", False)),
            tail_lines=int(action.get("tail_lines", 100)),
        )
        analysis = self._analyze_evidence_text(
            "logs", logs, f"what do the logs of {pod} show?"
        )
        from rca_tpu.features.logscan import LOG_PATTERN_NAMES, scan_text

        counts = scan_text(logs or "")
        hits = [
            f"{LOG_PATTERN_NAMES[i]}×{int(c)}"
            for i, c in enumerate(counts) if c > 0
        ]
        ctx = ctx or self.capture(namespace)
        # plain list, not ndarray: the tag rides the JSON-serialized result
        tag = {
            "kind": "logs", "pod": pod,
            "pattern_counts": [int(c) for c in counts],
            "previous": bool(action.get("previous", False)),
        }
        return {
            "response": {
                "points": [analysis]
                + ([f"Log error classes: {', '.join(hits)}"] if hits else []),
                "sections": [],
            },
            "evidence": {f"logs/{pod}": (logs or "")[-4000:]},
            "evidence_tag": tag,
            "suggestions": self._followups(ctx, tag),
            "key_findings": [
                f"{pod} log classes: {', '.join(hits)}" if hits
                else f"{pod}: no error classes in logs"
            ],
        }

    def _suggest_check_events(self, action, namespace, ctx) -> Dict[str, Any]:
        kind = action.get("kind")
        name = action.get("name")
        selector = (
            f"involvedObject.kind={kind},involvedObject.name={name}"
            if kind and name else None
        )
        events = self.cluster.get_events(namespace, field_selector=selector)
        analysis = self._analyze_evidence_text(
            "events", events[:30], "what do these events indicate?"
        )
        ctx = ctx or self.capture(namespace)
        # tag carries only the fields the follow-up rules read (the full
        # events are already under "evidence" — no need to double them)
        tag = {
            "kind": "events",
            "events": [
                {
                    "reason": e.get("reason"),
                    "involved_object": e.get(
                        "involved_object", e.get("involvedObject", {})
                    ),
                }
                for e in events[:50]
            ],
        }
        return {
            "response": {"points": [analysis], "sections": []},
            "evidence": {"events": events[:30]},
            "evidence_tag": tag,
            "suggestions": self._followups(ctx, tag),
            "key_findings": [f"{len(events)} events reviewed"],
        }

    def update_suggestions_after_action(
        self,
        taken_action: Dict[str, Any],
        result: Dict[str, Any],
        namespace: str,
        ctx: Optional[AnalysisContext] = None,
    ) -> List[Dict[str, Any]]:
        """Regenerate prioritized next actions after one was taken,
        dropping the action just executed (reference:
        mcp_coordinator.py:3555-3640).  When the result carries its tagged
        evidence (every process_suggestion branch returns one), the fresh
        list is conditioned on THAT evidence — so what was just learned
        drives what to do next."""
        ctx = ctx or self.capture(namespace)
        evidence = (
            result.get("evidence_tag") if isinstance(result, dict) else None
        ) or {"kind": "none"}
        fresh = self._followups(ctx, evidence)
        taken = json.dumps(taken_action, sort_keys=True, default=str)
        return [
            s for s in fresh
            if json.dumps(s.get("action", {}), sort_keys=True, default=str)
            != taken
        ]

    # -- hypothesis workflow (delegates to coordinator.hypotheses) -----------
    def generate_hypotheses(
        self, component: str, finding: Dict[str, Any], namespace: str,
        investigation_id: str = "",
    ) -> List[Dict[str, Any]]:
        return hypo.generate_hypotheses(
            self, component, finding, namespace, investigation_id,
        )

    def get_investigation_plan(
        self, hypothesis: Dict[str, Any], namespace: str
    ) -> Dict[str, Any]:
        return hypo.get_investigation_plan(self, hypothesis, namespace)

    def execute_investigation_step(
        self, step: Dict[str, Any], hypothesis: Dict[str, Any],
        namespace: str, investigation_id: str = "",
    ) -> Dict[str, Any]:
        return hypo.execute_investigation_step(
            self, step, hypothesis, namespace, investigation_id,
        )

    def generate_root_cause_report(
        self, session: Dict[str, Any]
    ) -> str:
        return hypo.generate_root_cause_report(self, session)
