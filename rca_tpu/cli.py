"""Command-line interface: the reference's Streamlit-only surface, scriptable.

``python -m rca_tpu <command>``:

- ``analyze``   one agent or the comprehensive pipeline → findings JSON
- ``chat``      one chat turn (structured response + suggestions);
                ``--investigation`` persists the conversation
- ``report``    comprehensive analysis as a markdown report
- ``suggest``   execute one suggestion action
- ``bench``     engine latency on a synthetic cascade
- ``train``     fit propagation weights; save an orbax checkpoint
- ``stream``    poll-driven live streaming analysis (1 Hz loop)
- ``chaos``     seeded fault-injection soak over a synthetic world
                (``--record`` writes a flight recording + replay-parity leg)
- ``serve``     multi-tenant serving scheduler (continuous shape-bucketed
                batching; ``--selftest`` asserts the serving contract;
                ``--listen HOST:PORT`` puts the stdlib-HTTP gateway in
                front — the wire front door, SERVING.md §Gateway)
- ``canary``    replay-driven regression canary (REPLAY.md §Canary):
                sample live investigations into minted recordings,
                replay them against a candidate build/config, exit
                nonzero on ranking divergence (the bisected tick is in
                the report)
- ``replay``    deterministic incident replay from a flight recording:
                tick-for-tick bit-parity, ``--seek`` time travel,
                ``--bisect`` first-divergent-tick search, ``--mint``
                corpus fixtures (REPLAY.md)
- ``kernels``   the live per-shape kernel registry table: engaged
                kernel, autotune timings, and XLA cost analysis per
                padded shape (engine/registry.py; OBSERVABILITY.md
                §kernelscope).  ``--explain`` explains KERNEL dispatch
                decisions — ranking attributions are ``rca why``
- ``why``       causelens blame tree for a stored investigation's
                latest explained ranking: evidence channels → blame
                edges → ranked service (ISSUE 14; OBSERVABILITY.md
                §causelens)
- ``lint``      graftlint static analysis: JAX/TPU-correctness rules +
                recompile tracecheck (``rca lint --help``; ANALYSIS.md)
- ``investigations``  list / show persisted investigations
- ``ui``        launch the Streamlit app (when streamlit is installed)

Fixtures: ``--fixture 5svc`` (the faulted hermetic world,
reference: utils/mock_k8s_client.py) or ``--fixture <N>svc`` (synthetic
cascade, e.g. ``50svc``, ``2000svc``); omit ``--fixture`` for a live
cluster via kubeconfig.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from typing import Optional


def _make_client(fixture: Optional[str], seed: int = 0,
                 fault_mix: str = "crash"):
    from rca_tpu.cluster.mock_client import MockClusterClient

    if fixture in (None, "", "live"):
        from rca_tpu.cluster.k8s_client import K8sApiClient

        return K8sApiClient(), None
    if fixture == "5svc":
        from rca_tpu.cluster.fixtures import NS, five_service_world

        return MockClusterClient(five_service_world()), NS
    m = re.fullmatch(r"(\d+)svc", fixture)
    if m:
        from rca_tpu.cluster.generator import synthetic_cascade_world

        world = synthetic_cascade_world(
            int(m.group(1)), n_roots=1, seed=seed, fault_mix=fault_mix,
        )
        return MockClusterClient(world), "synthetic"
    raise SystemExit(f"unknown fixture: {fixture!r} (want 5svc, <N>svc, live)")


def _coordinator(args):
    from rca_tpu.coordinator import RCACoordinator
    from rca_tpu.llm import LLMClient, make_provider
    from rca_tpu.obslog import get_logger

    client, ns = _make_client(getattr(args, "fixture", None),
                              getattr(args, "seed", 0),
                              getattr(args, "fault_mix", "crash"))
    namespace = getattr(args, "namespace", None) or ns or "default"
    prompt_logger = get_logger(getattr(args, "log_dir", "logs") + "/prompts")
    llm = LLMClient(
        provider=make_provider(getattr(args, "provider", None)),
        log_fn=prompt_logger.as_log_fn(namespace=namespace),
    )
    coord = RCACoordinator(
        client, llm_client=llm,
        backend=getattr(args, "backend", None),
        use_llm_agents=getattr(args, "llm_agents", False),
    )
    return coord, namespace


def cmd_analyze(args) -> int:
    coord, namespace = _coordinator(args)
    record = coord.run_analysis(args.type, namespace)
    out = record if args.full else {
        "status": record["status"],
        "summary": record.get("summary", ""),
        "root_causes": record.get("results", {})
        .get("correlated", {})
        .get("root_causes", [])
        if args.type == "comprehensive"
        else record.get("results", {}).get(args.type, {}).get("findings", []),
        **({"error": record["error"]} if "error" in record else {}),
    }
    print(json.dumps(out, indent=None if args.compact else 2, default=str))
    return 0 if record["status"] == "completed" else 1


def cmd_hypotheses(args) -> int:
    """Counterfactual hypothesis batch (VERDICT r3 item 7): for each of
    the analysis's top candidates, score a what-if-it-were-healthy feature
    set — all hypotheses in ONE batched device dispatch
    (``EngineAPI.analyze_batch``).  A candidate's SUPPORT is the anomaly
    its removal leaves unexplained elsewhere: muting a true root frees its
    victims from explain-away suppression, so their scores rise; muting a
    mere victim changes little.  Output: candidates ranked by support."""
    import numpy as np

    from rca_tpu.cluster.snapshot import ClusterSnapshot
    from rca_tpu.engine.sharded_runner import make_engine
    from rca_tpu.features.extract import extract_features
    from rca_tpu.graph.build import service_dependency_edges

    client, ns = _make_client(args.fixture, args.seed, args.fault_mix)
    namespace = args.namespace or ns or "default"
    snap = ClusterSnapshot.capture(client, namespace)
    fs = extract_features(snap)
    src, dst = service_dependency_edges(snap, fs)
    engine = make_engine()
    base = engine.analyze_features(fs, src, dst, k=args.candidates)
    cands = [
        r["component"] for r in base.ranked[: args.candidates]
    ]
    if not cands:
        print(json.dumps({
            "namespace": namespace, "engine": base.engine,
            "batch_width": 0, "hypotheses": [],
            "note": "no ranked candidates (empty namespace?)",
        }, indent=None if args.compact else 2))
        return 0
    name_to_idx = {n_: i for i, n_ in enumerate(base.service_names)}
    feats = np.asarray(fs.service_features, np.float32)
    batch = np.repeat(feats[None], len(cands), axis=0)
    for b, comp in enumerate(cands):
        batch[b, name_to_idx[comp]] = 0.0     # the counterfactual: healthy
    results = engine.analyze_batch(
        batch, src, dst, names=base.service_names, k=args.top
    )
    base_total = float(np.sum(base.score))
    out = []
    for comp, res in zip(cands, results):
        i = name_to_idx[comp]
        # support: anomaly left unexplained elsewhere once comp is healthy
        others = float(np.sum(np.delete(res.score, i)))
        base_others = float(base_total - base.score[i])
        out.append({
            "candidate": comp,
            "base_score": round(float(base.score[i]), 4),
            "support": round(others - base_others, 4),
            "counterfactual_top": res.top_components(3),
        })
    out.sort(key=lambda r: -r["support"])
    print(json.dumps({
        "namespace": namespace,
        "engine": results[0].engine if results else base.engine,
        "batch_width": len(cands),
        "batch_latency_ms_per_hypothesis": round(
            results[0].latency_ms, 3
        ) if results else None,
        "hypotheses": out,
    }, indent=None if args.compact else 2))
    return 0


def cmd_chat(args) -> int:
    """One chat turn; with --investigation the turn is a persisted part of
    that conversation — prior accumulated findings feed the prompt, and
    the messages/suggestions/findings land back in the store (reference:
    components/chatbot_interface.py persisted every turn; the CLI makes
    that scriptable)."""
    coord, namespace = _coordinator(args)
    store = inv = None
    if args.investigation:
        from rca_tpu.store import InvestigationStore

        store = InvestigationStore(root=args.log_dir)
        inv = store.get_investigation(args.investigation)
        if inv is None and args.investigation != "new":
            print(json.dumps(
                {"error": f"no investigation {args.investigation}"}
            ))
            return 1
        if inv is None:
            inv = store.create_investigation(
                args.query[:60], namespace=namespace
            )
    out = coord.process_user_query(
        args.query, namespace,
        previous_findings=(inv or {}).get("accumulated_findings"),
    )
    if store is not None:
        iid = inv["id"]
        first_turn = len(inv.get("conversation", [])) == 0
        store.record_chat_turn(iid, args.query, out)
        if first_turn:
            store.set_title(
                iid, coord.generate_summary_from_query(args.query, out)
            )
        out["investigation_id"] = iid
    if not args.full:
        out.pop("cluster_state", None)
    print(json.dumps(out, indent=None if args.compact else 2, default=str))
    return 0


def cmd_report(args) -> int:
    """Comprehensive analysis rendered as the markdown report (reference:
    components/report.py; scriptable here, e.g. for CI artifacts)."""
    coord, namespace = _coordinator(args)
    record = coord.run_analysis("comprehensive", namespace)
    if record["status"] != "completed":
        print(json.dumps({"error": record.get("error", "analysis failed")}))
        return 1
    from rca_tpu.ui.render import report_markdown

    md = report_markdown(record["results"])
    if args.out:
        with open(args.out, "w") as f:
            f.write(md)
        print(json.dumps({"written": args.out, "bytes": len(md)}))
    else:
        print(md)
    return 0


def cmd_suggest(args) -> int:
    coord, namespace = _coordinator(args)
    action = json.loads(args.action)
    out = coord.process_suggestion(action, namespace)
    print(json.dumps(out, indent=None if args.compact else 2, default=str))
    return 0


def cmd_bench(args) -> int:
    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import make_engine

    case = synthetic_cascade_arrays(
        args.services, n_roots=args.roots, seed=args.seed
    )
    # reuse the analyze boundary's engine selection (RCA_SHARD / device
    # count), so `rca bench` measures what `rca analyze` would actually run
    engine = make_engine()
    result = engine.analyze_case(case, k=5, timed=True)
    truth = {case.names[r] for r in case.roots.tolist()}
    print(
        json.dumps(
            {
                "n_services": args.services,
                "n_edges": result.n_edges,
                "latency_ms": round(result.latency_ms, 3),
                "top1_hit": result.ranked[0]["component"] in truth,
                "engine": result.engine,
                "ranked": result.ranked[:5],
            },
            default=str,
        )
    )
    return 0


def cmd_train(args) -> int:
    from rca_tpu.engine.train import (
        TrainConfig,
        hit_at_1,
        save_params,
        shippability_report,
        train,
    )

    cfg = TrainConfig(
        n_services=args.services, n_cases=args.cases,
        iters=args.iters, lr=args.lr, seed=args.seed,
        modes=tuple(args.modes.split(",")),
    )
    params, history = train(cfg)
    acc = hit_at_1(params, cfg)
    # the ship gate (train.shippability_report): physically-sane params,
    # >= defaults on held-out generator settings, fixtures unregressed —
    # a checkpoint that fails it is refused unless --allow-unshippable.
    # Only evaluated when a checkpoint is requested: the gate costs ~60
    # adversarial analyses, too much for a no-output research iteration
    report = shippability_report(params) if args.out else None
    saved = None
    if args.out:
        if report["ships"] or args.allow_unshippable:
            save_params(params, args.out)
            saved = args.out
        else:
            print(
                "refusing to save: shippability gate failed "
                "(--allow-unshippable overrides)", file=sys.stderr,
            )
    print(
        json.dumps(
            {
                "final_loss": round(history[-1], 5),
                "initial_loss": round(history[0], 5),
                "holdout_hit_at_1": acc,
                "checkpoint": saved,
                "decay": round(params.decay, 4),
                "explain_strength": round(params.explain_strength, 4),
                "impact_bonus": round(params.impact_bonus, 4),
                "shippability": report,
            }
        )
    )
    return 0 if (report is None or report["ships"] or saved) else 1


def cmd_stream(args) -> int:
    """Poll-driven live streaming: one JSON line per tick (engine/live.py;
    BASELINE.md row 4's 1 Hz loop, runnable against a fixture or a live
    cluster)."""
    import time as _time

    from rca_tpu.engine import LiveStreamingSession

    client, ns = _make_client(args.fixture, args.seed,
                              getattr(args, 'fault_mix', 'crash'))
    namespace = args.namespace or ns or "default"
    recorder = None
    if getattr(args, "record", None):
        from rca_tpu.replay import Recorder

        recorder = Recorder(args.record, mode="stream")
    live = LiveStreamingSession(
        client, namespace, k=args.top,
        pipeline_depth=getattr(args, "pipeline_depth", None),
        recorder=recorder,
    )
    for i in range(args.ticks):
        out = live.poll()
        line = {
            "tick": out["tick"],
            "latency_ms": round(out["latency_ms"], 3),
            "capture_ms": out["capture_ms"],
            "quiet": out.get("quiet", False),
            "changed_rows": out["changed_rows"],
            "upload_rows": out["upload_rows"],
            "resynced": out["resynced"],
            "ranked": out["ranked"],
        }
        # resilience channel (RESILIENCE.md): only printed when something
        # actually degraded, so the healthy stream output stays identical
        health = out.get("health", {})
        if out.get("degraded"):
            line["degraded"] = True
        if health.get("sanitized_rows"):
            line["sanitized_rows"] = health["sanitized_rows"]
        if health.get("degradation"):
            line["degradation_rung"] = health["degradation_rung"]
        # pipeline channel: only at depth >= 2, so depth-1 output stays
        # byte-identical to the pre-pipeline stream
        if health.get("pipeline_depth", 1) > 1:
            line["pipeline_depth"] = health["pipeline_depth"]
            line["result_lag"] = health["result_lag"]
            if health.get("pipeline_fill"):
                line["pipeline_fill"] = True
        print(json.dumps(line, default=str), flush=True)
        if args.interval > 0 and i + 1 < args.ticks:
            _time.sleep(args.interval)
    if recorder is not None:
        recorder.close()
        print(json.dumps({
            "recording": recorder.path,
            "ticks_recorded": recorder.ticks_recorded,
            "bytes": recorder.bytes_written,
        }), file=sys.stderr)
    return 0


def cmd_chaos(args) -> int:
    """Seeded chaos soak (RESILIENCE.md): run a LiveStreamingSession over
    a fault-injecting :class:`ChaosClusterClient` wrapper for N ticks and
    score the resilience contract — zero uncaught exceptions, every fault
    class observed in the health records, and fault-free ticks
    bit-identical to a fault-free baseline session.  Exit 0 only when the
    contract holds.  ``--seed`` (or ``RCA_CHAOS_SEED``) seeds the fault
    schedule; ``--world-seed`` seeds the synthetic world."""
    from rca_tpu.config import env_int
    from rca_tpu.resilience.chaos import ChaosConfig, run_chaos_soak

    m = re.fullmatch(r"(\d+)svc", args.fixture or "50svc")
    if not m:
        raise SystemExit(
            f"chaos needs a synthetic fixture (<N>svc), got {args.fixture!r}"
        )
    n_services = int(m.group(1))
    seed = (
        args.seed if args.seed is not None
        else env_int("RCA_CHAOS_SEED", 7, 0, 2**31 - 1)
    )

    def make_world():
        from rca_tpu.cluster.generator import synthetic_cascade_world

        return synthetic_cascade_world(
            n_services, n_roots=1, seed=args.world_seed,
            fault_mix=args.fault_mix,
        )

    summary = run_chaos_soak(
        make_world, "synthetic", seed=seed, ticks=args.ticks, k=args.top,
        config=ChaosConfig(seed=seed),
        topology_check_every=args.topology_check_every,
        record_path=args.record,
        pipeline_depth=getattr(args, "pipeline_depth", None),
    )
    # federation chaos leg (ISSUE 15): the three PROCESS-level fault
    # classes — seeded process_kill, worker_hang, coordinator_partition
    # — driven against a live worker fleet under wire load, gated on
    # all-terminal + zero double completions + every class observed +
    # rejoin.  Short exploratory runs (--ticks < 100) skip it, same
    # policy as the all-classes-observed gate above.
    fed_ok = True
    if not getattr(args, "no_federation", False) and args.ticks >= 100:
        from rca_tpu.serve.federation import run_federation_chaos

        summary["federation"] = run_federation_chaos(
            seed=seed, workers=args.federation_workers,
        )
        fed_ok = summary["federation"]["ok"]
    # scaling_storm leg (ISSUE 16): forced scale transitions racing the
    # federation fault seams — scale-up vs SIGKILL, rejoin vs drain,
    # partition during scale-down — gated on all-terminal + zero double
    # completions + bounded stale drops + every race observed
    auto_ok = True
    if (not getattr(args, "no_federation", False)
            and not getattr(args, "no_autoscale", False)
            and args.ticks >= 100):
        from rca_tpu.serve.autoscale import run_scaling_storm

        summary["autoscale"] = run_scaling_storm(seed=seed)
        auto_ok = (
            summary["autoscale"]["ok"]
            and "scaling_storm"
            in summary["autoscale"]["fault_classes_observed"]
        )
    # ingest_death leg (ISSUE 17): SIGKILL the ingest worker that owns
    # capture mirrors mid-soak — gated on the death observed as its own
    # fault class, drain-and-reroute to a survivor, rendezvous reclaim
    # on rejoin, and ZERO double-applied capture ticks (the coordinator
    # cluster table is the exactly-once arbiter).
    ingest_ok = True
    if (not getattr(args, "no_federation", False)
            and not getattr(args, "no_ingest", False)
            and args.ticks >= 100):
        from rca_tpu.serve.federation import (
            INGEST_FAULT_CLASS, run_ingest_chaos,
        )

        summary["ingest"] = run_ingest_chaos(seed=seed)
        ingest_ok = (
            summary["ingest"]["ok"]
            and INGEST_FAULT_CLASS
            in summary["ingest"]["fault_classes_observed"]
            and summary["ingest"]["double_applied"] == 0
        )
    print(json.dumps(summary, indent=None if args.compact else 2))
    scope = summary.get("kernelscope", {})
    ok = (
        summary["uncaught_exceptions"] == 0
        and summary["parity_ok"]
        and (summary["all_classes_observed"] or args.ticks < 100)
        and fed_ok
        and auto_ok
        and ingest_ok
        # --record adds the record→replay parity leg to the contract
        and summary.get("replay", {}).get("parity_ok", True)
        # kernelscope gates (ISSUE 12): zero post-warmup recompiles on
        # the tick path, and device memory must not grow monotonically
        and scope.get("recompiles_post_warm", 0) == 0
        and scope.get("memory_gate", {}).get("ok", True)
    )
    return 0 if ok else 1


def cmd_ingest(args) -> int:
    """Federated capture fleet (SERVING.md §Ingest workers): spawn
    ``--workers`` ingest-class workers, register ``--clusters`` synthetic
    clusters (rendezvous-routed, exactly one capture-mirror owner each),
    soak for ``--duration`` seconds, and print the coordinator's cluster
    table — owner, epoch, ticks, sweep latency, coldiff bytes.  Exits 0
    only when every cluster is owned and ticking with zero double-applied
    ticks and zero stale-stat leaks past the epoch fence."""
    import time as _time

    from rca_tpu.serve.federation import FederationPlane

    plane = FederationPlane(
        workers=0, heartbeat_s=args.heartbeat_s, spawn_workers=False,
    )
    with plane:
        for i in range(args.workers):
            plane.spawn_worker(i, role="ingest")
        if not plane.wait_ready(args.workers, timeout_s=90.0):
            print(json.dumps({"ok": False, "error": "workers never joined",
                              "workers": plane.worker_table()}))
            return 1
        specs = {
            f"c{j}": {
                "digest": f"ingest-{args.seed}-{j}",
                "services": args.services,
                "pods_per_service": args.pods_per_service,
                "seed": args.seed + j,
                "namespace": "synthetic",
            }
            for j in range(args.clusters)
        }
        plane.register_clusters(specs)
        deadline = _time.monotonic() + args.duration
        while _time.monotonic() < deadline:
            _time.sleep(0.1)
        status = plane.ingest_status()
        double = sum(c["double_applied"] for c in status.values())
        summary = {
            "clusters": status,
            "workers": plane.worker_table(),
            "double_applied": double,
            "stale_stats_dropped": plane.ingest_stale,
            "ok": bool(status) and double == 0 and all(
                c["owner"] is not None and c["ticks"] > 0
                for c in status.values()
            ),
        }
    print(json.dumps(summary, indent=None if args.compact else 2))
    return 0 if summary["ok"] else 1


def _parse_autoscale(spec: str):
    """``MIN:MAX`` → (min, max) with loud validation (SERVING.md
    §Autoscaling)."""
    m = re.fullmatch(r"(\d+):(\d+)", (spec or "").strip())
    if not m:
        raise SystemExit(
            f"--autoscale wants MIN:MAX (e.g. 2:8), got {spec!r}"
        )
    mn, mx = int(m.group(1)), int(m.group(2))
    if not 1 <= mn <= mx:
        raise SystemExit(
            f"--autoscale {spec!r}: need 1 <= MIN <= MAX"
        )
    return mn, mx


def cmd_serve(args) -> int:
    """Multi-tenant serving scheduler (SERVING.md).  ``--selftest`` runs
    the end-to-end contract check (mixed-tenant requests over several
    shape buckets, concurrent submitters, deadline sheds, coalesced-vs-
    solo bit parity; ``--chaos`` adds seeded dispatch/fetch faults) and
    exits 0 only when the contract holds.  Without ``--selftest`` it runs
    a synthetic load demo over a ``<N>svc`` fixture graph and prints the
    per-tenant metrics summary."""
    import time as _time

    import numpy as np

    from rca_tpu.config import ServeConfig

    overrides = {
        k: v for k, v in (
            ("max_batch", args.max_batch),
            ("max_wait_us", args.max_wait_us),
            ("queue_cap", args.queue_cap),
            ("replicas", args.replicas),
            ("replica_mix", args.replica_mix),
        ) if v is not None
    }
    if args.no_steal:
        overrides["steal"] = False
    config = ServeConfig.from_env(**overrides)
    if args.listen:
        return _serve_listen(args, config)
    if args.autoscale and args.federation is None:
        # `rca serve --autoscale MIN:MAX` (no listener): the load-ramp
        # soak — a thread-mode fleet scales MIN→MAX→MIN under
        # continuous traffic, gated on all-terminal + exactly-once +
        # bounded windowed p99 through both transitions
        from rca_tpu.serve.autoscale import run_scale_ramp_soak

        mn, mx = _parse_autoscale(args.autoscale)
        summary = run_scale_ramp_soak(
            seed=args.seed, min_workers=mn, max_workers=mx,
            config=config,
        )
        print(json.dumps(summary, indent=None if args.compact else 2,
                         default=str))
        return 0 if summary["ok"] else 1
    if args.federation is not None or args.kill_worker:
        # cross-process federation selftest (ISSUE 15): N real worker
        # processes, wire load, optional SIGKILL mid-wave — exit 0 only
        # when every request is terminal, federation rankings are
        # bit-identical to the single-process engine, and
        # double_completions == 0
        from rca_tpu.serve.federation import federation_selftest

        summary = federation_selftest(
            workers=args.federation or 3,
            n_requests=args.requests,
            seed=args.seed,
            kill_worker=args.kill_worker,
            submitters=args.submitters,
            config=config,
            bind_external=getattr(args, "bind_external", False),
        )
        print(json.dumps(summary, indent=None if args.compact else 2,
                         default=str))
        return 0 if summary["ok"] else 1
    if args.selftest:
        from rca_tpu.serve import serve_selftest

        summary = serve_selftest(
            n_requests=args.requests, seed=args.seed, chaos=args.chaos,
            config=config, submitters=args.submitters,
            replicas=config.replicas, replica_mix=config.replica_mix,
            kill_replica=args.kill_replica,
        )
        print(json.dumps(summary, indent=None if args.compact else 2,
                         default=str))
        return 0 if summary["ok"] else 1

    from rca_tpu.cluster.generator import synthetic_cascade_arrays
    from rca_tpu.engine import make_engine
    from rca_tpu.serve import ServeClient, ServeLoop, ServePool

    m = re.fullmatch(r"(\d+)svc", args.fixture or "500svc")
    if not m:
        raise SystemExit(
            f"serve needs a synthetic fixture (<N>svc), got {args.fixture!r}"
        )
    case = synthetic_cascade_arrays(
        int(m.group(1)), n_roots=1, seed=args.seed
    )
    rng = np.random.default_rng(args.seed)
    recorder = None
    if args.record:
        from rca_tpu.replay import Recorder

        recorder = Recorder(args.record, mode="serve")
    pooled = len(config.replica_specs()) > 1
    if pooled:
        # the multi-replica serving plane: engines + device groups come
        # from the replica mix (RCA_SERVE_REPLICAS / --replica-mix)
        loop = ServePool(config=config, recorder=recorder)
    else:
        loop = ServeLoop(engine=make_engine(), config=config,
                         recorder=recorder)
    tenants = [f"tenant-{i}" for i in range(args.tenants)]
    t0 = _time.perf_counter()
    with loop:
        client = ServeClient(loop)
        reqs = [
            client.submit(
                np.clip(case.features + rng.uniform(
                    0, 0.05, case.features.shape
                ).astype(np.float32), 0, 1),
                case.dep_src, case.dep_dst, names=case.names,
                tenant=tenants[i % len(tenants)], k=args.top,
            )
            for i in range(args.requests)
        ]
        responses = [r.result(timeout=300.0) for r in reqs]
    wall_s = _time.perf_counter() - t0
    if recorder is not None:
        recorder.close()
    by_status = {}
    for resp in responses:
        by_status[resp.status] = by_status.get(resp.status, 0) + 1
    print(json.dumps({
        "requests": args.requests,
        "tenants": len(tenants),
        **({"recording": recorder.path,
            "serve_recorded": recorder.serve_recorded}
           if recorder is not None else {}),
        "by_status": by_status,
        "wall_s": round(wall_s, 3),
        "analyses_per_sec": round(
            by_status.get("ok", 0) / max(wall_s, 1e-9), 1
        ),
        "device_batches": loop.device_batches,
        "metrics": loop.metrics.summary(),
    }, indent=None if args.compact else 2, default=str))
    return 0 if by_status.get("ok", 0) == args.requests else 1


def _serve_listen(args, config) -> int:
    """``rca serve --listen HOST:PORT`` (SERVING.md §Gateway): start the
    serving plane (ServeLoop, or the pool when the resolved replica
    count exceeds 1), put the stdlib-HTTP gateway in front, print ONE
    JSON line naming the bound address (port 0 = kernel-chosen, so
    callers read it from here), and serve until SIGTERM/SIGINT.  The
    shutdown summary (per-tenant/per-replica metrics) goes to stderr —
    stdout stays machine-parseable."""
    import signal
    import threading

    from rca_tpu.config import gateway_port
    from rca_tpu.engine import make_engine
    from rca_tpu.gateway import GatewayServer
    from rca_tpu.serve import ServeLoop, ServePool
    from rca_tpu.store import InvestigationStore
    from rca_tpu.util.net import parse_hostport

    host, port = parse_hostport(args.listen, gateway_port())
    recorder = None
    if args.record:
        from rca_tpu.replay import Recorder

        recorder = Recorder(args.record, mode="serve")
    # wire requests carrying an investigation_id land store notes +
    # recording_ref exactly like in-process submissions
    store = InvestigationStore(root=args.log_dir)
    federated = getattr(args, "federation", None)
    pooled = len(config.replica_specs()) > 1
    if federated and recorder is not None:
        raise SystemExit(
            "--record is not supported with --federation yet: serve "
            "frames live in the worker processes (use `rca canary "
            "--listen-url` to mint recordings off the live gateway)"
        )
    autoscale_spec = getattr(args, "autoscale", None)
    if autoscale_spec and not federated:
        raise SystemExit(
            "--autoscale with --listen needs --federation N (an elastic "
            "fleet is a federation property; in-process pools resize "
            "via RCA_SERVE_REPLICAS)"
        )
    controller = None
    if federated:
        # the TLS+authn front door over a whole worker fleet (ISSUE 15)
        from rca_tpu.serve.federation import FederationPlane

        plane_kwargs = {}
        if getattr(args, "bind_external", False):
            from rca_tpu.util.net import primary_host_ip

            plane_kwargs.update(
                host="0.0.0.0", advertise_host=primary_host_ip(),
            )
        loop = FederationPlane(
            workers=federated, config=config, store=store,
            **plane_kwargs,
        )
        loop.start()
        if not loop.wait_ready(federated, timeout_s=120.0):
            loop.stop()
            raise SystemExit(
                f"federation: only {len(loop.live_workers())}/"
                f"{federated} workers joined"
            )
        if autoscale_spec:
            # elasticmesh (ISSUE 16): the controller watches queue-time
            # p99 / SLO-burn / occupancy and walks the fleet inside
            # MIN..MAX through SCALE_RULES; --federation N is the
            # starting width and must sit inside the bounds
            from rca_tpu.serve.autoscale import AutoscaleController

            mn, mx = _parse_autoscale(autoscale_spec)
            if not mn <= federated <= mx:
                loop.stop()
                raise SystemExit(
                    f"--autoscale {autoscale_spec}: --federation "
                    f"{federated} is outside [{mn}, {mx}]"
                )
            controller = AutoscaleController(
                loop, min_workers=mn, max_workers=mx,
            )
            controller.start(spawn_min=False)
    elif pooled:
        loop = ServePool(config=config, recorder=recorder, store=store)
        loop.start()
    else:
        loop = ServeLoop(engine=make_engine(), config=config,
                         recorder=recorder, store=store)
        loop.start()
    gw = GatewayServer(loop, host=host, port=port)
    gw.start()
    stop = threading.Event()

    def _on_signal(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(json.dumps({
        "listening": gw.address,
        **({"workers": len(loop.live_workers())} if federated else
           {"replicas": len(loop.replicas) if pooled else 1}),
        **({"control": loop.address} if federated else {}),
        **({"autoscale": f"{controller.min_workers}:"
                         f"{controller.max_workers}"}
           if controller is not None else {}),
        "tls": gw.tls_context is not None,
        "authn": bool(gw.tokens),
        "max_body": gw.max_body,
        "endpoints": ["/v1/analyze", "/v1/subscribe", "/v1/traces",
                      "/metrics", "/healthz"],
        **({"recording": recorder.path} if recorder is not None else {}),
    }), flush=True)
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        if controller is not None:
            controller.stop()
        gw.close()
        loop.stop()
        if recorder is not None:
            recorder.close()
        snap = gw.metrics.snapshot()
        print(json.dumps({
            "stopped": True,
            "gateway_requests": {
                f"{route}:{code}": n
                for (route, code), n in snap["requests"].items()
            },
            "metrics": loop.metrics.summary(),
        }, default=str), file=sys.stderr)
    return 0


def cmd_fleet(args) -> int:
    """``rca fleet URL`` (SERVING.md §Autoscaling): the operator's view
    of a RUNNING elastic federation — one /healthz call rendered as a
    worker table (state, outstanding, served, placement evidence) plus
    the controller's bounds and last decision.  ``--json`` prints the
    raw health body instead."""
    from rca_tpu.gateway.client import GatewayClient

    client = GatewayClient.from_url(
        args.url, token=args.token, ca_file=args.ca_file,
        cert_file=args.cert_file, key_file=args.key_file,
        timeout_s=args.timeout,
    )
    status, body = client.healthz()
    if args.json:
        print(json.dumps(body, indent=2, default=str))
        return 0 if status == 200 else 1
    fleet = body.get("fleet")
    if fleet is None:
        print(json.dumps({
            "error": "not a federation gateway (no fleet in /healthz)",
            "health": body,
        }, indent=2, default=str))
        return 1
    cols = ("worker", "state", "outstanding", "served", "shapes",
            "mem_bytes", "engine", "pid")
    rows = [
        (str(w.get("worker_id")), str(w.get("state")),
         str(w.get("outstanding")), str(w.get("served")),
         str(w.get("shapes_known")), str(w.get("mem_bytes") or "-"),
         str(w.get("engine") or "-"), str(w.get("pid") or "-"))
        for w in fleet
    ]
    widths = [
        max(len(cols[i]), *(len(r[i]) for r in rows)) if rows
        else len(cols[i])
        for i in range(len(cols))
    ]
    print("  ".join(c.ljust(widths[i]) for i, c in enumerate(cols)))
    for r in rows:
        print("  ".join(v.ljust(widths[i]) for i, v in enumerate(r)))
    line = {
        "ok": body.get("ok"),
        "queue_depth": body.get("queue_depth"),
        "pending": body.get("pending"),
    }
    auto = body.get("autoscale")
    if auto:
        line["autoscale"] = {
            "bounds": f"{auto.get('min')}:{auto.get('max')}",
            "running": auto.get("running"),
            "decisions": auto.get("decisions"),
            "last": (auto.get("last_decision") or {}).get("action"),
        }
    print(json.dumps(line, default=str))
    return 0 if status == 200 and body.get("ok") else 1


def cmd_canary(args) -> int:
    """Replay-driven regression canary (REPLAY.md §Canary): sample live
    investigations into minted recordings (stamping each into the store
    as a replayable ``recording_ref``), replay them — plus any
    ``--corpus`` recordings — against the candidate build/config, and
    exit nonzero on ranking divergence.  The report names the exact
    bisected tick (stream) or request index (serve)."""
    import glob
    import os as _os

    from rca_tpu.gateway import build_candidate_engine, run_canary

    m = re.fullmatch(r"(\d+)svc", args.fixture or "20svc")
    if not m:
        raise SystemExit(
            f"canary needs a synthetic fixture (<N>svc), got "
            f"{args.fixture!r}"
        )
    candidate, info = build_candidate_engine(
        kind=args.candidate_engine,
        weights=args.candidate_weights,
        decay=args.candidate_decay,
        explain_strength=args.candidate_explain_strength,
        impact_bonus=args.candidate_impact_bonus,
    )
    corpus = []
    if args.corpus:
        if _os.path.isdir(args.corpus):
            corpus = sorted(glob.glob(_os.path.join(args.corpus, "*.rcz")))
        else:
            corpus = [args.corpus]
    store = None
    if not args.no_store:
        from rca_tpu.store import InvestigationStore

        store = InvestigationStore(root=args.log_dir)
    report = run_canary(
        args.out,
        rounds=args.rounds,
        ticks=args.ticks,
        services=int(m.group(1)),
        seed=args.seed,
        sample_rate=args.sample_rate,
        mode=args.mode,
        k=args.top,
        candidate=candidate,
        candidate_info=info,
        corpus=corpus,
        store=store,
        serve_requests=args.requests,
        listen_url=args.listen_url,
        token=args.token,
        ca_file=args.ca_file,
    )
    print(json.dumps(report, indent=None if args.compact else 2,
                     default=str))
    return 0 if report["ok"] else 1


def _replay_engine(choice: Optional[str]):
    """Engine for a replay run: ``auto`` (None) lets the replayer pick
    the RECORDED engine kind — the bitwise contract is like-for-like;
    ``single``/``sharded`` force a cross-engine replay (stream rankings
    stay parity-locked across kinds; REPLAY.md)."""
    if choice in (None, "", "auto"):
        return None
    if choice == "single":
        from rca_tpu.engine.runner import GraphEngine

        return GraphEngine()
    if choice == "sharded":
        from rca_tpu.engine.sharded_runner import ShardedGraphEngine

        return ShardedGraphEngine()
    raise SystemExit(f"unknown engine {choice!r} (want auto|single|sharded)")


def cmd_replay(args) -> int:
    """Deterministic incident replay (REPLAY.md).  Re-drives the REAL
    engine from a flight recording and asserts tick-for-tick (stream) or
    request-for-request (serve) bit-identity; exit 0 = parity holds.
    ``--seek`` time-travels to one tick, ``--bisect`` binary-searches a
    diverging log to its first divergent tick and dumps both sides'
    tensors, ``--mint`` compacts a recording into a one-file corpus
    fixture, ``--investigation`` resolves the log from a stored
    investigation's ``recording_ref``."""
    from rca_tpu.replay import (
        bisect_divergence,
        load_recording,
        mint_recording,
        replay_serve,
        replay_stream,
    )

    path = args.log
    if args.investigation:
        from rca_tpu.store import InvestigationStore

        store = InvestigationStore(root=args.log_dir)
        path = store.get_recording_ref(args.investigation)
        if not path:
            print(json.dumps({
                "error": f"investigation {args.investigation} has no "
                "recording_ref",
            }))
            return 1
    if not path:
        raise SystemExit("replay needs a LOG path or --investigation ID")
    if args.mint:
        stats = mint_recording(path, args.mint)
        print(json.dumps(stats, indent=None if args.compact else 2))
        return 0
    if args.trace_out:
        # timeline reconstruction (ISSUE 11): the Chrome trace comes
        # from the spans embedded in the recording's tick frames — the
        # times the incident actually had, no re-run required
        from rca_tpu.observability.export import (
            recording_trace,
            write_chrome_trace,
        )

        trace = recording_trace(path)
        write_chrome_trace(trace, args.trace_out)
        print(json.dumps({
            "trace_out": args.trace_out,
            "trace_events": len(trace["traceEvents"]),
        }, indent=None if args.compact else 2))
        return 0 if trace["traceEvents"] else 1
    engine = _replay_engine(args.engine)
    rec = load_recording(path)
    if rec.mode == "serve":
        report = replay_serve(path, engine=engine)
    elif args.bisect:
        report = bisect_divergence(
            path, engine=engine, pipeline_depth=args.pipeline_depth,
            dump_path=args.dump,
        )
    else:
        report = replay_stream(
            path, engine=engine, pipeline_depth=args.pipeline_depth,
            seek=args.seek, ticks=args.ticks,
            parity="rank" if getattr(args, "rank_parity", False)
            else "exact",
            explain=getattr(args, "explain", False),
        )
    print(json.dumps(report, indent=None if args.compact else 2,
                     default=str))
    ok = report.get("parity_ok", not report.get("divergent", False))
    return 0 if ok else 1


def cmd_profile(args) -> int:
    """``rca profile`` (OBSERVABILITY.md): wrap a synthetic streaming
    session's ticks in a ``jax.profiler`` capture, with per-tick
    ``StepTraceAnnotation`` grouping and the per-shape kernel
    attribution stamped into the summary — the diagnosis surface for
    ``pallas_engaged: false`` regressions."""
    from rca_tpu.observability.profile import profile_ticks

    summary = profile_ticks(
        args.out, ticks=args.ticks, services=args.services,
        seed=args.seed,
    )
    print(json.dumps(summary, indent=None if args.compact else 2))
    return 0


def cmd_kernels(args) -> int:
    """``rca kernels`` (ISSUE 12/13): the live per-shape kernel registry
    as a table — one row per ``(variant, n_pad, e_pad, backend)`` with
    the engaged kernel, WHY it won, the autotune timings, and the winner
    executable's XLA cost analysis (FLOPs / bytes accessed / peak temp
    and output memory).  ``--services`` (paired with ``--edges``)
    resolves rows for those graph sizes first (a fresh process has only
    what its sessions asked about); ``--explain`` prints the full
    candidate set per shape — the eligibility reason each declined
    kernel never raced with, or the timing it lost with; cost capture
    compiles the canonical executable per shape, so ``--no-cost`` skips
    it and ``--cost-max-pad`` bounds it."""
    from rca_tpu.config import RCAConfig, bucket_for
    from rca_tpu.engine.registry import KERNELS, get_registry, kernel_table

    reg = get_registry()
    buckets = RCAConfig().shape_buckets

    def ints(raw, flag):
        out = []
        for part in (raw or "").split(","):
            part = part.strip()
            if not part:
                continue
            try:
                out.append(int(part))
            except ValueError:
                raise SystemExit(
                    f"{flag} expects comma-separated ints, got {part!r}"
                )
        return out

    services = ints(args.services, "--services")
    edges = ints(getattr(args, "edges", ""), "--edges")
    for i, n in enumerate(services):
        e = edges[i] if i < len(edges) else max(1, int(n * 2.5))
        reg.resolve(bucket_for(n + 1, buckets),
                    e_pad=bucket_for(e, buckets))
    rows = kernel_table(
        ensure_cost=not args.no_cost, cost_max_pad=args.cost_max_pad,
    )
    if args.json:
        print(json.dumps({"rows": rows},
                         indent=None if args.compact else 2))
        return 0

    def fmt(x, unit=""):
        if x is None:
            return "-"
        if isinstance(x, float):
            return f"{x:.4g}{unit}"
        return f"{x}{unit}"

    cols = ("n_pad", "e_pad", "variant", "backend", "winner", "source",
            "t_xla_ms", "t_win_ms", "flops", "bytes", "peak_temp",
            "output")
    table = [cols]
    for row in rows:
        cost = row.get("cost") or {}
        timings = row.get("timings_ms") or {}
        # attribution rows (ISSUE 14) time the whole causelens sweep,
        # recorded under "attribution" rather than the winner's name
        t_win = (timings.get("attribution")
                 if row["variant"] == "attribution"
                 else timings.get(row["winner"]))
        table.append((
            str(row["n_pad"]), fmt(row.get("e_pad")), row["variant"],
            row["backend"], row["winner"], row["source"],
            fmt(timings.get("xla")), fmt(t_win),
            fmt(cost.get("flops")), fmt(cost.get("bytes_accessed")),
            fmt(cost.get("peak_temp_bytes")),
            fmt(cost.get("output_bytes")),
        ))
    widths = [max(len(r[i]) for r in table) for i in range(len(cols))]
    for i, r in enumerate(table):
        print("  ".join(c.ljust(w) for c, w in zip(r, widths)).rstrip())
        if i == 0:
            print("  ".join("-" * w for w in widths))
    if getattr(args, "explain", False):
        # name-collision pointer (ISSUE 14 satellite): this flag
        # explains KERNEL decisions; ranking attributions live under
        # `rca why`
        print("\n(explaining KERNEL dispatch decisions — for RANKING "
              "attributions / blame trees, see `rca why "
              "<investigation-id>`)")
        # the full candidate set per shape (ISSUE 13 satellite): the
        # registry records every decision — ineligible candidates name
        # their gate, timed losers show both timings
        for row in rows:
            shape = (f"{row['variant']} n_pad={row['n_pad']} "
                     f"e_pad={fmt(row.get('e_pad'))}")
            print(f"\n{shape}: winner={row['winner']} "
                  f"({row['source']})")
            timings = row.get("timings_ms") or {}
            t_win = timings.get(row["winner"])
            for k in KERNELS:
                if k == row["winner"]:
                    detail = "engaged"
                    if t_win is not None:
                        detail += f" ({t_win:.4g} ms)"
                    print(f"  {k:10s} {detail}")
                    continue
                elig = (row.get("eligible") or {}).get(k)
                if elig is not True and elig is not None:
                    print(f"  {k:10s} ineligible: {elig}")
                elif k in timings:
                    t = timings[k]
                    if t is None:
                        print(f"  {k:10s} failed to time (cannot win)")
                    elif t_win is not None:
                        print(f"  {k:10s} lost the timing: {t:.4g} ms "
                              f"vs {t_win:.4g} ms")
                    else:
                        print(f"  {k:10s} timed {t:.4g} ms")
                else:
                    print(f"  {k:10s} not raced "
                          f"(decision source: {row['source']})")
    return 0


def cmd_why(args) -> int:
    """``rca why <investigation-id>`` (ISSUE 14): render the stored
    causelens provenance — the blame tree behind the investigation's
    latest explained ranking (evidence channels → blame edges → ranked
    service).  NOT ``rca kernels --explain``, which explains KERNEL
    dispatch decisions; this explains RANKINGS.

    Provenance lands in the store when an explained analysis names the
    investigation: a serve/gateway request with ``investigation_id`` +
    ``explain``, or a correlate run under ``RCA_EXPLAIN=1`` persisted
    through the chat/analyze flows."""
    from rca_tpu.observability.causelens import render_blame_tree
    from rca_tpu.store import InvestigationStore

    store = InvestigationStore(root=args.log_dir)
    inv = store.get_investigation(args.investigation_id)
    if inv is None:
        print(json.dumps(
            {"error": f"no investigation {args.investigation_id}"}
        ))
        return 1
    provenance = inv.get("provenance")
    if provenance is None:
        # fall back to the newest chat turn that carried one
        for msg in reversed(inv.get("conversation", []) or []):
            content = msg.get("content")
            if isinstance(content, dict):
                rd = content.get("response_data") or {}
                cand = (
                    content.get("provenance")
                    or rd.get("provenance")
                    or (rd.get("correlated") or {}).get("provenance")
                )
                if cand is not None:
                    provenance = cand
                    break
    if provenance is None:
        hint = {
            "error": f"investigation {args.investigation_id} carries no "
            "provenance block",
            "hint": "serve the analysis with explain=true (wire: "
            "?explain=1) naming this investigation_id, or run the "
            "correlate flow with RCA_EXPLAIN=1",
        }
        if inv.get("recording_ref"):
            hint["recording_ref"] = inv["recording_ref"]
            hint["hint"] += (
                "; the investigation has a recording — `rca replay "
                "--explain` can recompute attributions from the tape "
                "when it was recorded with RCA_EXPLAIN=1"
            )
        print(json.dumps(hint, indent=None if args.compact else 2))
        return 1
    if args.json:
        print(json.dumps(provenance,
                         indent=None if args.compact else 2))
        return 0
    print(f"investigation {args.investigation_id} · "
          f"{inv.get('title', '')}".rstrip(" ·"))
    print(render_blame_tree(provenance))
    return 0


def cmd_lint(args) -> int:
    """graftlint (ANALYSIS.md): delegate to the analyzer CLI so
    ``rca lint ...`` and ``python -m rca_tpu.analysis ...`` are the same
    tool with the same exit-code contract (0 clean / 1 findings /
    2 usage error)."""
    from rca_tpu.analysis.__main__ import main as lint_main

    return lint_main(args.lint_args)


def cmd_investigations(args) -> int:
    from rca_tpu.store import InvestigationStore

    store = InvestigationStore(root=args.log_dir)
    if args.id:
        inv = store.get_investigation(args.id)
        if inv is None:
            print(json.dumps({"error": f"no investigation {args.id}"}))
            return 1
        print(json.dumps(inv, indent=2, default=str))
    else:
        print(json.dumps(store.list_investigations(), indent=2, default=str))
    return 0


def cmd_ui(args) -> int:
    try:
        import streamlit  # noqa: F401
    except ImportError:
        print(
            "streamlit is not installed; the coordinator API and CLI expose "
            "the same capabilities (try: python -m rca_tpu analyze "
            "--fixture 5svc).",
            file=sys.stderr,
        )
        return 1
    import subprocess

    from rca_tpu.ui import app as ui_app

    return subprocess.call(
        [sys.executable, "-m", "streamlit", "run", ui_app.__file__,
         "--server.port", str(args.port)]
    )


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="rca_tpu", description="TPU-native Kubernetes RCA framework"
    )
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp):
        sp.add_argument("--fixture", default=None,
                        help="5svc | <N>svc | live (default: live)")
        sp.add_argument("--fault-mix", default="crash", dest="fault_mix",
                        help="synthetic fixtures' root fault archetypes: "
                        "crash | mixed | oom | image | config | pending")
        sp.add_argument("--namespace", default=None)
        sp.add_argument("--backend", default=None,
                        help="jax | deterministic | llm (default: $RCA_BACKEND or jax)")
        sp.add_argument("--provider", default=None,
                        help="openai | anthropic | offline")
        sp.add_argument("--llm-agents", action="store_true",
                        help="use LLM agents instead of deterministic rules")
        sp.add_argument("--seed", type=int, default=0)
        sp.add_argument("--log-dir", default="logs")
        sp.add_argument("--full", action="store_true",
                        help="print the full record")
        sp.add_argument("--compact", action="store_true",
                        help="single-line JSON")

    sp = sub.add_parser("analyze", help="run an analysis")
    common(sp)
    sp.add_argument("--type", default="comprehensive",
                    help="comprehensive | resources | metrics | logs | "
                    "events | topology | traces")
    sp.set_defaults(fn=cmd_analyze)

    sp = sub.add_parser(
        "hypotheses",
        help="counterfactual hypothesis batch: what-if-healthy scoring of "
        "the top candidates in one batched dispatch",
    )
    sp.add_argument("--fixture", default=None)
    sp.add_argument("--namespace", default=None)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--fault-mix", default="crash", dest="fault_mix")
    sp.add_argument("--candidates", type=int, default=8,
                    help="batch width: top-N candidates to counterfactual")
    sp.add_argument("--top", type=int, default=5)
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_hypotheses)

    sp = sub.add_parser("chat", help="one chat turn")
    common(sp)
    sp.add_argument("query")
    sp.add_argument("--investigation", default=None,
                    help="persist the turn into this investigation id "
                    "('new' creates one); prior findings feed the prompt")
    sp.set_defaults(fn=cmd_chat)

    sp = sub.add_parser(
        "report", help="comprehensive analysis as a markdown report"
    )
    common(sp)
    sp.add_argument("--out", default=None,
                    help="write the report here instead of stdout")
    sp.set_defaults(fn=cmd_report)

    sp = sub.add_parser("suggest", help="execute one suggestion action")
    common(sp)
    sp.add_argument("action", help='JSON, e.g. {"type": "check_logs", '
                    '"pod_name": "x"}')
    sp.set_defaults(fn=cmd_suggest)

    sp = sub.add_parser("bench", help="engine latency benchmark")
    sp.add_argument("--services", type=int, default=2000)
    sp.add_argument("--roots", type=int, default=3)
    sp.add_argument("--seed", type=int, default=0)
    sp.set_defaults(fn=cmd_bench)

    sp = sub.add_parser(
        "stream", help="poll-driven live streaming analysis (1 Hz loop)"
    )
    sp.add_argument("--fixture", default=None,
                    help="5svc | <N>svc | live (default: live)")
    sp.add_argument("--fault-mix", default="crash", dest="fault_mix",
                    help="synthetic fixtures' root fault archetypes")
    sp.add_argument("--namespace", default=None)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--ticks", type=int, default=5)
    sp.add_argument("--interval", type=float, default=1.0,
                    help="seconds between polls (0 = as fast as possible)")
    sp.add_argument("--top", type=int, default=5)
    sp.add_argument("--pipeline-depth", type=int, default=None,
                    dest="pipeline_depth",
                    help="tick pipeline depth (default $RCA_PIPELINE_DEPTH "
                    "or 1): 2 overlaps each tick's device round trip with "
                    "the next poll's capture; rankings arrive depth-1 "
                    "ticks late")
    sp.add_argument("--record", default=None, metavar="PATH",
                    help="flight-record every tick to PATH (a directory); "
                    "re-drive later with `rca replay PATH`")
    sp.set_defaults(fn=cmd_stream)

    sp = sub.add_parser("train", help="fit propagation weights on "
                        "synthetic cascades; save an orbax checkpoint")
    sp.add_argument("--services", type=int, default=256)
    sp.add_argument("--cases", type=int, default=64)
    sp.add_argument("--iters", type=int, default=150)
    sp.add_argument("--lr", type=float, default=0.05)
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--modes", default="standard,crashing_victims,"
                    "correlated_noise,adversarial",
                    help="comma-separated cascade modes for the dataset")
    sp.add_argument("--out", default=None,
                    help="checkpoint directory (loadable via RCA_WEIGHTS)")
    sp.add_argument("--allow-unshippable", action="store_true",
                    help="save the checkpoint even when the shippability "
                    "gate fails (research use)")
    sp.set_defaults(fn=cmd_train)

    sp = sub.add_parser(
        "chaos",
        help="seeded chaos soak: fault injection over a synthetic world; "
        "asserts zero uncaught exceptions + fault-free tick parity",
    )
    sp.add_argument("--fixture", default="50svc", help="<N>svc synthetic")
    sp.add_argument("--seed", type=int, default=None,
                    help="chaos schedule seed (default: $RCA_CHAOS_SEED or 7)")
    sp.add_argument("--world-seed", type=int, default=0, dest="world_seed")
    sp.add_argument("--fault-mix", default="crash", dest="fault_mix")
    sp.add_argument("--ticks", type=int, default=200)
    sp.add_argument("--top", type=int, default=5)
    sp.add_argument("--topology-check-every", type=int, default=5,
                    dest="topology_check_every")
    sp.add_argument("--record", default=None, metavar="PATH",
                    help="flight-record the chaos session to PATH and add "
                    "the record→replay bit-parity leg to the contract")
    sp.add_argument("--pipeline-depth", type=int, default=None,
                    dest="pipeline_depth",
                    help="tick pipeline depth for the soaked session")
    sp.add_argument("--no-federation", action="store_true",
                    dest="no_federation",
                    help="skip the federation chaos leg (worker process "
                    "kill/hang/partition over a live 3-worker fleet)")
    sp.add_argument("--federation-workers", type=int, default=3,
                    dest="federation_workers",
                    help="worker processes in the federation chaos leg")
    sp.add_argument("--no-autoscale", action="store_true",
                    dest="no_autoscale",
                    help="skip the scaling_storm chaos leg (forced scale "
                    "transitions racing kill/hang/partition)")
    sp.add_argument("--no-ingest", action="store_true",
                    dest="no_ingest",
                    help="skip the ingest_death chaos leg (SIGKILL the "
                    "capture-mirror owner; exactly-once tick gate)")
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_chaos)

    sp = sub.add_parser(
        "ingest",
        help="federated capture fleet: ingest-class workers owning "
        "columnar cluster mirrors, rendezvous-routed (SERVING.md)",
    )
    sp.add_argument("--workers", type=int, default=2,
                    help="ingest worker processes")
    sp.add_argument("--clusters", type=int, default=3,
                    help="synthetic clusters to register")
    sp.add_argument("--services", type=int, default=20,
                    help="services per synthetic cluster")
    sp.add_argument("--pods-per-service", type=int, default=1,
                    dest="pods_per_service")
    sp.add_argument("--duration", type=float, default=5.0,
                    help="soak seconds before scoring")
    sp.add_argument("--heartbeat-s", type=float, default=0.25,
                    dest="heartbeat_s")
    sp.add_argument("--seed", type=int, default=17)
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_ingest)

    sp = sub.add_parser(
        "serve",
        help="multi-tenant serving scheduler: continuous shape-bucketed "
        "batching of concurrent analyze requests (SERVING.md)",
    )
    sp.add_argument("--selftest", action="store_true",
                    help="run the serving-contract selftest (all requests "
                    "answered or shed, coalesced-vs-solo bit parity); "
                    "exit 0 only when the contract holds")
    sp.add_argument("--chaos", action="store_true",
                    help="selftest with seeded dispatch/fetch fault "
                    "injection (breaker + degraded path)")
    sp.add_argument("--requests", type=int, default=32)
    sp.add_argument("--submitters", type=int, default=4,
                    help="concurrent submitter threads (selftest)")
    sp.add_argument("--tenants", type=int, default=4,
                    help="logical tenants (load demo)")
    sp.add_argument("--fixture", default="500svc",
                    help="<N>svc synthetic graph (load demo)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--top", type=int, default=5)
    sp.add_argument("--max-batch", type=int, default=None, dest="max_batch",
                    help="override RCA_SERVE_MAX_BATCH")
    sp.add_argument("--max-wait-us", type=int, default=None,
                    dest="max_wait_us",
                    help="override RCA_SERVE_MAX_WAIT_US")
    sp.add_argument("--queue-cap", type=int, default=None, dest="queue_cap",
                    help="override RCA_SERVE_QUEUE_CAP")
    sp.add_argument("--replicas", type=int, default=None,
                    help="serve-pool width: N engine replicas behind the "
                    "shared queue (override RCA_SERVE_REPLICAS; >1 "
                    "selects the pool scheduler)")
    sp.add_argument("--replica-mix", default=None, dest="replica_mix",
                    metavar="SPEC",
                    help="replica kinds + device groups, e.g. "
                    "'dense:2,sharded@4:2' (override "
                    "RCA_SERVE_REPLICA_MIX; defines the replica count "
                    "when given)")
    sp.add_argument("--no-steal", action="store_true",
                    help="disable work-stealing rebalance (RCA_SERVE_"
                    "STEAL=0): a dead replica's staged work rides the "
                    "degradation ladder instead)")
    sp.add_argument("--kill-replica", action="store_true",
                    dest="kill_replica",
                    help="selftest chaos: kill replica 0 mid-wave and "
                    "assert the steal protocol drops nothing "
                    "(implies a pool of >= 2 replicas)")
    sp.add_argument("--federation", type=int, default=None,
                    metavar="N",
                    help="cross-process federation (SERVING.md "
                    "§Federation): N worker PROCESSES under one control "
                    "plane.  Alone: run the federation selftest "
                    "(all-answered-or-shed, pool-vs-federation bit "
                    "parity, zero double completions).  With --listen: "
                    "the gateway fronts the federation instead of an "
                    "in-process plane")
    sp.add_argument("--kill-worker", action="store_true",
                    dest="kill_worker",
                    help="federation selftest chaos: SIGKILL one worker "
                    "process mid-wave and assert drain-and-reroute "
                    "leaves every request terminal with zero double "
                    "completions")
    sp.add_argument("--autoscale", default=None, metavar="MIN:MAX",
                    help="elastic fleet bounds (SERVING.md §Autoscaling). "
                    "Alone: run the 2→8→2-style load-ramp soak (thread "
                    "fleet scales MIN→MAX→MIN under continuous traffic; "
                    "exit 0 only on all-terminal + exactly-once + "
                    "bounded p99).  With --listen --federation N: attach "
                    "the SCALE_RULES controller to the live fleet")
    sp.add_argument("--bind-external", action="store_true",
                    dest="bind_external",
                    help="bind the federation control port on 0.0.0.0 "
                    "and advertise this host's primary IP, so workers "
                    "on OTHER hosts can join via --connect (selftest: "
                    "workers join through the advertised non-loopback "
                    "address; SERVING.md §Deploy)")
    sp.add_argument("--record", default=None, metavar="PATH",
                    help="flight-record every served request to PATH "
                    "(load-demo and --listen modes); re-check with "
                    "`rca replay PATH`")
    sp.add_argument("--listen", default=None, metavar="HOST:PORT",
                    help="serve over the wire: start the stdlib-HTTP "
                    "gateway (POST /v1/analyze, GET /v1/subscribe, "
                    "/metrics, /healthz) in front of the scheduler and "
                    "run until SIGTERM; port 0 binds an ephemeral port "
                    "(the bound address prints as the first stdout "
                    "line); default port $RCA_GATEWAY_PORT")
    sp.add_argument("--log-dir", default="logs",
                    help="investigation store root for --listen "
                    "(wire requests carrying an investigation_id "
                    "append serve notes there)")
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser(
        "fleet",
        help="status table for a running elastic federation: one "
        "/healthz call rendered as worker rows + autoscale bounds "
        "and last decision (SERVING.md §Autoscaling)",
    )
    sp.add_argument("url", metavar="URL",
                    help="gateway address, http[s]://host:port")
    sp.add_argument("--token", default=None,
                    help="bearer token for a gateway with "
                    "RCA_GATEWAY_TOKENS set")
    sp.add_argument("--ca-file", default=None, dest="ca_file",
                    metavar="PEM",
                    help="verify a TLS gateway against this cert")
    sp.add_argument("--cert-file", default=None, dest="cert_file",
                    metavar="PEM",
                    help="client certificate for an mTLS gateway "
                    "(RCA_GATEWAY_TLS_CLIENT_CA)")
    sp.add_argument("--key-file", default=None, dest="key_file",
                    metavar="PEM",
                    help="client key (defaults to the cert file)")
    sp.add_argument("--timeout", type=float, default=10.0)
    sp.add_argument("--json", action="store_true",
                    help="print the raw /healthz body instead of the "
                    "rendered table")
    sp.set_defaults(fn=cmd_fleet)

    sp = sub.add_parser(
        "canary",
        help="replay-driven regression canary: sample live "
        "investigations into minted recordings, replay them against a "
        "candidate build/config, exit nonzero on ranking divergence "
        "(the exact bisected tick is in the report; REPLAY.md §Canary)",
    )
    sp.add_argument("--out", default="logs/canary",
                    help="directory the minted canary corpus grows in")
    sp.add_argument("--corpus", default=None, metavar="PATH",
                    help="existing recordings added to the replay gate "
                    "(a directory of *.rcz, or one file) — e.g. a "
                    "previous canary's corpus or a recorded gateway "
                    "session")
    sp.add_argument("--rounds", type=int, default=2,
                    help="sampling rounds (each records one session at "
                    "the sample rate)")
    sp.add_argument("--ticks", type=int, default=12,
                    help="streaming ticks per sampled session")
    sp.add_argument("--requests", type=int, default=8,
                    help="serve requests per sampled wave (mode "
                    "serve/both)")
    sp.add_argument("--fixture", default="20svc",
                    help="<N>svc synthetic world per sampled session")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--sample-rate", type=float, default=None,
                    dest="sample_rate",
                    help="per-round recording probability (override "
                    "$RCA_CANARY_SAMPLE_RATE; default 1.0)")
    sp.add_argument("--mode", default="stream",
                    choices=["stream", "serve", "both", "multicluster"],
                    help="what each round samples: streaming "
                    "investigations (bisect names the exact tick), "
                    "serve waves (first divergent request index), "
                    "both, or merged multi-cluster sessions captured "
                    "through the live columnar adapter")
    sp.add_argument("--listen-url", default=None, dest="listen_url",
                    metavar="URL",
                    help="sample through a RUNNING gateway "
                    "(http[s]://host:port) instead of in-process — the "
                    "live plane behind it (pool or federation) mints "
                    "the corpus; overrides --mode")
    sp.add_argument("--token", default=None,
                    help="bearer token for a --listen-url gateway with "
                    "RCA_GATEWAY_TOKENS set")
    sp.add_argument("--ca-file", default=None, dest="ca_file",
                    metavar="PEM",
                    help="verify a --listen-url TLS gateway against "
                    "this cert (self-signed deployments pin their own)")
    sp.add_argument("--top", type=int, default=5)
    sp.add_argument("--candidate-engine", default="auto",
                    dest="candidate_engine",
                    help="auto (= current build, recorded kind) | "
                    "single | sharded")
    sp.add_argument("--candidate-weights", default=None,
                    dest="candidate_weights", metavar="CKPT",
                    help="candidate scoring checkpoint (RCA_WEIGHTS "
                    "form) the corpus replays against")
    sp.add_argument("--candidate-decay", type=float, default=None,
                    dest="candidate_decay",
                    help="perturb the candidate's per-hop decay")
    sp.add_argument("--candidate-explain-strength", type=float,
                    default=None, dest="candidate_explain_strength")
    sp.add_argument("--candidate-impact-bonus", type=float,
                    default=None, dest="candidate_impact_bonus")
    sp.add_argument("--no-store", action="store_true", dest="no_store",
                    help="skip stamping sampled recordings into the "
                    "investigation store")
    sp.add_argument("--log-dir", default="logs")
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_canary)

    sp = sub.add_parser(
        "replay",
        help="deterministic incident replay from a flight recording: "
        "bit-parity check, --seek time travel, --bisect divergence "
        "search, --mint corpus fixtures (REPLAY.md)",
    )
    sp.add_argument("log", nargs="?", default=None,
                    help="recording directory (or minted single file)")
    sp.add_argument("--seek", type=int, default=None, metavar="TICK",
                    help="replay up to TICK and attach its full detail "
                    "(both rankings, feature digests) to the report")
    sp.add_argument("--bisect", action="store_true",
                    help="on divergence, binary-search to the FIRST "
                    "divergent tick and dump both feature/ranking "
                    "tensors for diffing")
    sp.add_argument("--mint", default=None, metavar="OUT",
                    help="compact the recording into one compressed file "
                    "(the committed tests/corpus fixture form)")
    sp.add_argument("--dump", default=None, metavar="PATH",
                    help="where --bisect writes the divergence tensors "
                    "(default: <log>.divergence.json)")
    sp.add_argument("--pipeline-depth", type=int, default=None,
                    dest="pipeline_depth",
                    help="replay at this depth (default: the recorded "
                    "one; a different depth compares lag-stripped "
                    "serial sequences)")
    sp.add_argument("--engine", default="auto",
                    help="auto (= the recorded engine kind) | single | "
                    "sharded (stream rankings are parity-locked across "
                    "kinds; serve per-node channels are bitwise only "
                    "like-for-like)")
    sp.add_argument("--ticks", type=int, default=None,
                    help="replay only the first N ticks")
    sp.add_argument("--rank-parity", action="store_true",
                    dest="rank_parity",
                    help="judge ticks by hit@1/hit@3 + Kendall-tau "
                    "instead of bitwise digests (ISSUE 13: the gate "
                    "mode that makes the quantized kernel replayable)")
    sp.add_argument("--explain", action="store_true",
                    help="causelens parity leg (ISSUE 14): recompute "
                    "per-tick attribution blocks from the tape and "
                    "REQUIRE their digests to match the live-recorded "
                    "ones (needs a recording made with RCA_EXPLAIN=1; "
                    "digests present in the log are compared even "
                    "without this flag)")
    sp.add_argument("--investigation", default=None, metavar="ID",
                    help="resolve the recording from this stored "
                    "investigation's recording_ref")
    sp.add_argument("--trace-out", default=None, dest="trace_out",
                    metavar="PATH",
                    help="write the recording's span timeline as "
                    "Perfetto-loadable Chrome trace JSON (from the "
                    "spans embedded in its tick frames; needs a "
                    "recording made with RCA_TRACE=1) and exit")
    sp.add_argument("--log-dir", default="logs")
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_replay)

    sp = sub.add_parser(
        "profile",
        help="opt-in jax.profiler capture around N live ticks "
        "(OBSERVABILITY.md): TensorBoard/Perfetto-loadable device "
        "trace + per-shape kernel attribution",
    )
    sp.add_argument("--out", default="logs/profile", metavar="DIR",
                    help="profile output directory (default logs/profile)")
    sp.add_argument("--ticks", type=int, default=20)
    sp.add_argument("--services", type=int, default=200,
                    help="synthetic world size the capture runs over")
    sp.add_argument("--seed", type=int, default=7)
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser(
        "kernels",
        help="print the live per-shape kernel registry: engaged kernel, "
        "autotune timings, and XLA cost analysis per padded shape "
        "(engine/registry.py — ISSUE 12)",
    )
    sp.add_argument("--services", default="500,2000",
                    help="comma-separated service counts whose shape "
                    "buckets to resolve before printing (default "
                    "500,2000)")
    sp.add_argument("--edges", default="",
                    help="comma-separated edge counts paired with "
                    "--services (default: ~2.5 edges/service) — the "
                    "edge tier gates the segscan/quantized/doubling "
                    "candidates")
    sp.add_argument("--explain", action="store_true",
                    help="per shape, print WHY each non-winning KERNEL "
                    "was declined: the eligibility reason, or the "
                    "timing it lost with (ISSUE 13).  Explains kernel "
                    "dispatch decisions only — RANKING attributions "
                    "(blame trees) live under `rca why`")
    sp.add_argument("--no-cost", action="store_true", dest="no_cost",
                    help="skip XLA cost analysis (cost capture compiles "
                    "the canonical executable once per shape)")
    sp.add_argument("--cost-max-pad", type=int, default=4096,
                    dest="cost_max_pad",
                    help="largest padded shape cost capture may compile "
                    "(default 4096; bigger rows still show winner + "
                    "timings)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable rows instead of the table")
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_kernels)

    sp = sub.add_parser(
        "lint",
        help="graftlint static analysis: tracer leaks, retrace hazards, "
        "RNG reuse, lock/env discipline, tick-sync + swallowed-fault "
        "contracts; --tracecheck adds the dynamic recompile gate",
        add_help=False,  # every flag (incl. --help) goes to the analyzer
    )
    sp.set_defaults(fn=cmd_lint, lint_args=[])

    sp = sub.add_parser(
        "why",
        help="render an investigation's causelens blame tree: which "
        "evidence channels, dependency edges, and counterfactual rows "
        "produced its ranking (ISSUE 14; kernel DISPATCH decisions are "
        "`rca kernels --explain`)",
    )
    sp.add_argument("investigation_id",
                    help="stored investigation id (see `rca "
                    "investigations`)")
    sp.add_argument("--json", action="store_true",
                    help="print the raw provenance block instead of the "
                    "ASCII tree")
    sp.add_argument("--log-dir", default="logs")
    sp.add_argument("--compact", action="store_true")
    sp.set_defaults(fn=cmd_why)

    sp = sub.add_parser("investigations", help="list/show investigations")
    sp.add_argument("--id", default=None)
    sp.add_argument("--log-dir", default="logs")
    sp.set_defaults(fn=cmd_investigations)

    sp = sub.add_parser("ui", help="launch the Streamlit app")
    sp.add_argument("--port", type=int, default=5000)
    sp.set_defaults(fn=cmd_ui)

    return p


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    # `rca lint` forwards its whole tail to the analyzer's own parser
    # (argparse.REMAINDER cannot: it refuses leading optionals)
    if argv and argv[0] == "lint":
        from rca_tpu.analysis.__main__ import main as lint_main

        return lint_main(list(argv[1:]))
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
