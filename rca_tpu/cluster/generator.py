"""Synthetic fault-cascade generators (the scaled benchmark configs).

The reference's scale story stops at a hand-written 5-service mock and a kind
cluster (reference: utils/mock_k8s_client.py, setup_test_cluster.py).  The
BASELINE.json configs require 50 / 2k / 10k / 50k-service worlds with known
ground-truth fault roots, so this module generates them:

- a random service-dependency DAG (each service depends on 1..3
  earlier services, preferential-attachment flavored so hub services emerge),
- fault injection at ``n_roots`` services,
- symptom propagation to transitive dependents with per-hop decay
  (dependents of a faulty service show timeouts / elevated latency / error
  rates; the roots themselves show crash loops),
- two output forms: a full dict :class:`World` (drives the agent layer) and
  raw numpy arrays (drives the TPU engine / bench directly at 10k-50k scale).

Ground truth is recorded in ``World.ground_truth`` / ``CascadeArrays.roots``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from rca_tpu.cluster.world import (
    World,
    make_deployment,
    make_endpoints,
    make_event,
    make_node,
    make_pod,
    make_service,
    pod_metric,
    waiting_status,
)

# Feature channels shared with the extractor (rca_tpu.features.schema.SvcF);
# generated cascades and extracted worlds feed the same engine arrays.
from rca_tpu.features.schema import NUM_SERVICE_FEATURES as NUM_FEATURES  # noqa: E402
from rca_tpu.features.schema import (  # noqa: E402
    NUM_RAW_SERVICE_FEATURES as NUM_RAW,
    SvcF,
    derive_silent_channel,
)

F_CRASH = int(SvcF.CRASH)
F_ERROR_RATE = int(SvcF.ERROR_RATE)
F_LATENCY = int(SvcF.LATENCY)
F_RESTARTS = int(SvcF.RESTARTS)
F_EVENTS = int(SvcF.EVENTS)
F_LOG_ERRORS = int(SvcF.LOG_ERRORS)
F_NOT_READY = int(SvcF.NOT_READY)
F_RESOURCE = int(SvcF.RESOURCE)
F_IMAGE = int(SvcF.IMAGE)
F_CONFIG = int(SvcF.CONFIG)
F_PENDING = int(SvcF.PENDING)
F_OOM = int(SvcF.OOM)

# Root fault archetypes (fault_mix="mixed"): what KIND of fault the root
# has, mirroring the reference's injected fault classes
# (reference: setup_test_cluster.py — crash loop :209, missing env/config
# :256, memory :303; plus image-pull and unschedulable, the other pod
# states its resource analyzer buckets, agents/resource_analyzer.py:275).
# The default "crash" keeps every pre-existing seed's cascade byte-stable.
ROOT_ARCHETYPES = ("crash", "oom", "image", "config", "pending")


@dataclasses.dataclass
class CascadeArrays:
    """Raw-array cascade: the direct input to the TPU engine."""

    n: int
    # COO edge list, dependency direction: edge (s, d) means service s
    # depends on service d (faults flow d -> s).
    dep_src: np.ndarray  # int32 [E] — the dependent
    dep_dst: np.ndarray  # int32 [E] — the dependency
    features: np.ndarray  # float32 [n, NUM_FEATURES]
    roots: np.ndarray  # int32 [n_roots] ground-truth fault roots
    anomaly: np.ndarray  # float32 [n] scalar anomaly per service
    names: Optional[List[str]] = None
    # diagnosis metadata (autopsy tooling, not consumed by the engine):
    # decoy service indices (correlated modes), hop distance from the
    # nearest root along dependent edges (INT32_MAX = unaffected), and
    # each root's fault archetype (parallel to ``roots``)
    decoys: Optional[np.ndarray] = None
    hops: Optional[np.ndarray] = None
    root_kinds: Optional[List[str]] = None


def _build_dag(n: int, rng: np.random.Generator, max_deps: int = 3):
    """Random layered DAG with preferential attachment; returns (src, dst)."""
    if n <= 1:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    # weight[i] grows as i acquires dependents -> hub services
    weights = np.ones(n, dtype=np.float64)
    src_list: List[np.ndarray] = []
    dst_list: List[np.ndarray] = []
    for i in range(1, n):
        k = int(rng.integers(1, max_deps + 1))
        k = min(k, i)
        p = weights[:i] / weights[:i].sum()
        deps = rng.choice(i, size=k, replace=False, p=p)
        weights[deps] += 1.0
        src_list.append(np.full(k, i, dtype=np.int32))
        dst_list.append(deps.astype(np.int32))
    return np.concatenate(src_list), np.concatenate(dst_list)


def _dependents_adj(n: int, dep_src: np.ndarray, dep_dst: np.ndarray):
    """dependency -> list of dependents (the direction faults travel)."""
    adj: List[List[int]] = [[] for _ in range(n)]
    for s, d in zip(dep_src.tolist(), dep_dst.tolist()):
        adj[d].append(s)
    return adj


def _bfs_hops(n: int, adj, roots: np.ndarray) -> np.ndarray:
    """Hop distance from the nearest fault root along dependent edges."""
    INF = np.iinfo(np.int32).max
    dist = np.full(n, INF, dtype=np.int64)
    frontier = list(int(r) for r in roots)
    for r in frontier:
        dist[r] = 0
    while frontier:
        nxt = []
        for u in frontier:
            for v in adj[u]:
                if dist[v] > dist[u] + 1:
                    dist[v] = dist[u] + 1
                    nxt.append(v)
        frontier = nxt
    return dist


CASCADE_MODES = (
    "standard",
    "crashing_victims",
    "missing_signals",
    "correlated_noise",
    "overlapping_roots",
    "adversarial",
)


def synthetic_cascade_arrays(
    n_services: int,
    n_roots: int = 1,
    seed: int = 0,
    decay: float = 0.75,
    noise: float = 0.05,
    mode: str = "standard",
    max_deps: int = 3,
    dropout_keep: float = 0.65,
    fault_mix: str = "crash",
) -> CascadeArrays:
    """Generate the raw-array cascade (any scale; used for bench + training).

    ``mode`` selects how adversarial the cascade is (VERDICT round-1: the
    standard generator makes roots nearly separable from the noisy-OR alone,
    so accuracy numbers ride an easy distribution):

    - ``standard`` — roots crash hard, victims degrade softly (no crash).
    - ``crashing_victims`` — probe-kill: victims near the root ALSO crash
      and restart (liveness probes kill pods that time out on a dead
      dependency), while roots crash with a wider, weaker range; the max
      per-service feature no longer identifies the root.
    - ``missing_signals`` — per-(service, channel) dropout: each fault
      signal is observed only with probability ~0.65 (agents miss data in
      real clusters); roots can lose their crash channel entirely.
    - ``correlated_noise`` — low-rank correlated background (shared noise
      factors across services, e.g. a noisy node or scrape jitter) plus
      loud decoy services with error/latency spikes but no downstream
      blast radius.
    - ``overlapping_roots`` — multi-root with overlapping blast radii:
      later roots are drawn from inside the first root's affected set, so
      victim symptoms stack and per-root evidence overlaps.
    - ``adversarial`` — crashing_victims + missing_signals +
      correlated_noise at once.

    ``decay``/``noise``/``max_deps``/``dropout_keep`` are the generator's
    domain knobs (symptom per-hop decay, background noise ceiling, DAG
    fan-out, per-channel observation probability in the dropout modes) —
    exposed so training can domain-randomize over them instead of
    overfitting one fixed world (VERDICT r2 item 4).

    ``fault_mix`` selects the roots' fault ARCHETYPE (round 3: a
    crash-only generator let fitted weights zero the image/config/
    pending/oom channels the real rule agents depend on):

    - ``"crash"`` (default) — every root crash-loops; byte-stable with
      every pre-existing seed;
    - ``"mixed"`` — each root draws an archetype from
      :data:`ROOT_ARCHETYPES` (crash / oom / image / config / pending),
      with archetype-appropriate channels (an image-pull root produces NO
      logs and NO crashes — the container never started);
    - one archetype name — every root has that fault (the shippability
      gate uses this to verify each channel family individually).
    """
    if mode not in CASCADE_MODES:
        raise ValueError(f"unknown cascade mode {mode!r}; one of {CASCADE_MODES}")
    rng = np.random.default_rng(seed)
    dep_src, dep_dst = _build_dag(n_services, rng, max_deps=max_deps)
    adj = _dependents_adj(n_services, dep_src, dep_dst)

    # Prefer roots with real downstream impact (≥1 dependent when possible).
    impact = np.array([len(a) for a in adj])
    candidates = np.nonzero(impact > 0)[0]
    if len(candidates) < n_roots:
        candidates = np.arange(n_services)
    if mode == "overlapping_roots" and n_roots > 1:
        first = rng.choice(candidates, size=1)
        hops0 = _bfs_hops(n_services, adj, first.astype(np.int32))
        blast = np.nonzero(
            (hops0 > 0) & (hops0 < np.iinfo(np.int32).max)
        )[0]
        pool = blast if len(blast) >= n_roots - 1 else np.setdiff1d(
            candidates, first
        )
        rest = rng.choice(pool, size=min(n_roots - 1, len(pool)), replace=False)
        roots = np.concatenate([first, rest])
    else:
        roots = rng.choice(
            candidates, size=min(n_roots, len(candidates)), replace=False
        )
    roots = roots.astype(np.int32)

    hops = _bfs_hops(n_services, adj, roots)
    feats = np.zeros((n_services, NUM_FEATURES), dtype=np.float32)

    correlated = mode in ("correlated_noise", "adversarial")
    # all rng draws cover only the RAW (observed) channels: the derived
    # SILENT channel is computed afterwards with no randomness of its own,
    # so every pre-existing seed's raw channels stay byte-stable
    if correlated:
        # low-rank noise: a few shared factors load onto every service
        # (scrape jitter, a hot node) — raises the background floor in a
        # structured way that per-service thresholds cannot remove.  The
        # factors load only onto SOFT channels: jitter inflates latency /
        # error rates / event counts, it does not fabricate OOM kills or
        # image-pull failures.
        n_factors = 3
        soft = np.zeros(NUM_RAW, dtype=np.float32)
        soft[[F_ERROR_RATE, F_LATENCY, F_EVENTS, F_LOG_ERRORS, F_RESOURCE]] = 1.0
        loadings = rng.uniform(0, 1, (n_services, n_factors)).astype(np.float32)
        factors = (
            rng.uniform(0, 0.25, (n_factors, NUM_RAW)).astype(np.float32)
            * soft[None, :]
        )
        background = loadings @ factors + rng.uniform(
            0.0, noise, size=(n_services, NUM_RAW)
        ).astype(np.float32)
    else:
        background = rng.uniform(
            0.0, noise, size=(n_services, NUM_RAW)
        ).astype(np.float32)
    feats[:, :NUM_RAW] += background

    is_root = np.zeros(n_services, dtype=bool)
    is_root[roots] = True
    affected = (hops < np.iinfo(np.int32).max) & ~is_root
    aff_idx = np.nonzero(affected)[0]
    aff_decay = (decay ** hops[aff_idx]).astype(np.float32)

    crashing_victims = mode in ("crashing_victims", "adversarial")
    if fault_mix == "crash":
        # byte-stable legacy path: identical rng draw sequence to the
        # pre-archetype generator, so every published seed/band reproduces
        if crashing_victims:
            # roots crash over a wider, weaker range (flaky rather than dead)
            feats[roots, F_CRASH] = rng.uniform(0.55, 0.95, size=len(roots))
            feats[roots, F_RESTARTS] = rng.uniform(0.5, 0.9, size=len(roots))
        else:
            feats[roots, F_CRASH] = rng.uniform(0.85, 1.0, size=len(roots))
            feats[roots, F_RESTARTS] = rng.uniform(0.7, 1.0, size=len(roots))
        feats[roots, F_EVENTS] = rng.uniform(0.6, 1.0, size=len(roots))
        feats[roots, F_LOG_ERRORS] = rng.uniform(0.7, 1.0, size=len(roots))
        feats[roots, F_NOT_READY] = rng.uniform(0.8, 1.0, size=len(roots))
        feats[roots, F_ERROR_RATE] = rng.uniform(0.5, 1.0, size=len(roots))
        root_kinds = ["crash"] * len(roots)
    else:
        if fault_mix == "mixed":
            root_kinds = [
                ROOT_ARCHETYPES[k]
                for k in rng.integers(0, len(ROOT_ARCHETYPES), len(roots))
            ]
        elif fault_mix in ROOT_ARCHETYPES:
            root_kinds = [fault_mix] * len(roots)
        else:
            raise ValueError(
                f"unknown fault_mix {fault_mix!r}; one of "
                f"('crash', 'mixed', *{ROOT_ARCHETYPES})"
            )
        for j, r in enumerate(roots.tolist()):
            kind = root_kinds[j]
            # common: the root is down/unready, K8s surfaces warning
            # events, callers see errors
            feats[r, F_EVENTS] = rng.uniform(0.6, 1.0)
            feats[r, F_NOT_READY] = rng.uniform(0.8, 1.0)
            feats[r, F_ERROR_RATE] = rng.uniform(0.5, 1.0)
            if kind == "crash":
                # ranges mirror the legacy crash path exactly (both
                # channels), so one archetype never has two different
                # evidence distributions between train (mixed) and eval
                # (crash) data
                if crashing_victims:
                    feats[r, F_CRASH] = rng.uniform(0.55, 0.95)
                    feats[r, F_RESTARTS] = rng.uniform(0.5, 0.9)
                else:
                    feats[r, F_CRASH] = rng.uniform(0.85, 1.0)
                    feats[r, F_RESTARTS] = rng.uniform(0.7, 1.0)
                feats[r, F_LOG_ERRORS] = rng.uniform(0.7, 1.0)
            elif kind == "oom":
                # memory at limit, kernel kills → restart loop with a
                # strong OOM channel and saturated resource pressure
                feats[r, F_OOM] = rng.uniform(0.8, 1.0)
                feats[r, F_CRASH] = rng.uniform(0.4, 0.8)
                feats[r, F_RESTARTS] = rng.uniform(0.5, 0.9)
                feats[r, F_RESOURCE] = rng.uniform(0.8, 1.0)
                feats[r, F_LOG_ERRORS] = rng.uniform(0.3, 0.8)
            elif kind == "image":
                # the container NEVER starts: no logs, no crashes — the
                # only signals are the waiting reason and events
                feats[r, F_IMAGE] = rng.uniform(0.85, 1.0)
                feats[r, F_LOG_ERRORS] = 0.0
            elif kind == "config":
                # missing ConfigMap/Secret/env: config-error waiting state,
                # possibly a few crash-exits when the app starts then dies
                feats[r, F_CONFIG] = rng.uniform(0.85, 1.0)
                feats[r, F_CRASH] = rng.uniform(0.3, 0.7)
                feats[r, F_LOG_ERRORS] = rng.uniform(0.2, 0.7)
            else:  # pending
                # unschedulable: never placed, no container, no logs
                feats[r, F_PENDING] = rng.uniform(0.8, 1.0)
                feats[r, F_LOG_ERRORS] = 0.0

    # Dependents: soft degradation decaying with hop distance.  In standard
    # mode victims carry NO crash signal (they are victims, not causes);
    # in probe-kill modes close victims saturate latency/errors AND crash,
    # so their max feature routinely exceeds the root's.
    jitter = rng.uniform(0.8, 1.0, size=len(aff_idx)).astype(np.float32)
    feats[aff_idx, F_LOG_ERRORS] = 0.4 * aff_decay * jitter
    feats[aff_idx, F_EVENTS] = 0.3 * aff_decay * jitter
    if crashing_victims:
        feats[aff_idx, F_LATENCY] = np.clip(
            1.1 * aff_decay * jitter, 0, 1.0
        )
        feats[aff_idx, F_ERROR_RATE] = np.clip(
            1.0 * aff_decay * rng.uniform(0.85, 1.0, len(aff_idx)), 0, 1.0
        )
        feats[aff_idx, F_CRASH] = np.clip(
            0.75 * aff_decay * rng.uniform(0.7, 1.0, len(aff_idx)), 0, 1.0
        )
        feats[aff_idx, F_RESTARTS] = np.clip(
            0.7 * aff_decay * rng.uniform(0.6, 1.0, len(aff_idx)), 0, 1.0
        )
        feats[aff_idx, F_NOT_READY] = (aff_decay > 0.5).astype(np.float32)
    else:
        feats[aff_idx, F_ERROR_RATE] = 0.7 * aff_decay * jitter
        feats[aff_idx, F_LATENCY] = 0.8 * aff_decay * jitter

    decoys = None
    if correlated:
        # decoy services: loud but inert (no blast radius) — error/latency
        # spikes from e.g. a bad canary; ~2% of services, never roots or
        # their direct dependents
        n_decoys = max(1, n_services // 50)
        eligible = np.nonzero(~is_root & ~affected)[0]
        if len(eligible) >= n_decoys:
            decoys = rng.choice(eligible, size=n_decoys, replace=False)
            feats[decoys, F_ERROR_RATE] = rng.uniform(0.9, 1.0, n_decoys)
            feats[decoys, F_LATENCY] = rng.uniform(0.9, 1.0, n_decoys)
            feats[decoys, F_LOG_ERRORS] = rng.uniform(0.3, 0.7, n_decoys)

    if mode in ("missing_signals", "adversarial"):
        # per-(service, channel) dropout of the fault signals: each channel
        # is observed with probability ``dropout_keep`` (background survives
        # — missing data looks like *quiet*, not like zeroed noise).  Only
        # the RAW channels drop: SILENT is the analyzer's own derivation
        # from whatever WAS observed, not an independent observation.
        keep = rng.random((n_services, NUM_RAW)) < dropout_keep
        feats[:, :NUM_RAW] = np.where(
            keep, feats[:, :NUM_RAW], background
        ).astype(np.float32)

    derive_silent_channel(feats)
    # the naive max-anomaly baseline reads OBSERVED channels only: scoring
    # the derived SILENT channel would credit "naive" with the analyzer's
    # own engineered absence evidence (and break comparability with every
    # pre-round-4 naive row)
    anomaly = feats[:, :NUM_RAW].max(axis=1)
    names = None
    if n_services <= 4096:
        names = [f"svc-{i:05d}" for i in range(n_services)]
    return CascadeArrays(
        n=n_services,
        dep_src=dep_src,
        dep_dst=dep_dst,
        features=feats,
        roots=np.sort(roots),
        anomaly=anomaly.astype(np.float32),
        names=names,
        decoys=None if decoys is None else np.sort(decoys).astype(np.int32),
        hops=hops.astype(np.int64),
        # roots are returned sorted; reorder the parallel kinds list the
        # same way (fault assignment iterated the UNSORTED draw order,
        # which legacy-seed byte-stability forbids changing)
        root_kinds=[root_kinds[j] for j in np.argsort(roots)],
    )


def _faulty_pod_parts(kind: str, svc: str, rng: np.random.Generator):
    """Per-archetype pod state for the dict-world form: container status,
    pod phase, event (reason, message), log text (None = container never
    produced logs), and metrics (cpu_m, mem_mib) — each chosen so the
    feature extractor's reason/phase/termination matching lights the
    archetype's DEFINING channels (features/extract.py:36-108).  The
    secondary-channel mix is the plausible K8s realization, not a replica
    of the raw-array generator's exact per-channel ranges — an OOM-killed
    pod really does carry a CrashLoopBackOff waiting reason, and a
    config-error pod really produces no logs."""
    if kind == "oom":
        status = waiting_status(
            svc, "CrashLoopBackOff", "Back-off restarting failed container",
            restarts=int(rng.integers(3, 10)),
            last_exit_code=137, last_reason="OOMKilled",
        )
        return (status, "Running",
                ("OOMKilling",
                 f"Memory cgroup out of memory: Killed process ({svc})"),
                "INFO: allocating buffers\n"
                "ERROR: Out of memory: killed by cgroup limit\n",
                (30, 127))
    if kind == "image":
        status = waiting_status(
            svc, "ImagePullBackOff",
            f'Back-off pulling image "{svc}:latest"',
        )
        return (status, "Pending",
                ("Failed", f'Failed to pull image "{svc}:latest": not found'),
                None, (0, 0))
    if kind == "config":
        status = waiting_status(
            svc, "CreateContainerConfigError",
            f'configmap "{svc}-config" not found',
        )
        return (status, "Pending",
                ("FailedCreate", f'configmap "{svc}-config" not found'),
                None, (0, 0))
    if kind == "pending":
        return (None, "Pending",
                ("FailedScheduling",
                 "0/3 nodes are available: 3 Insufficient memory."),
                None, (0, 0))
    # crash (default)
    status = waiting_status(
        svc, "CrashLoopBackOff", "Back-off restarting failed container",
        restarts=int(rng.integers(4, 12)), last_exit_code=1,
    )
    return (status, "Running",
            ("BackOff", f"Back-off restarting failed container {svc}"),
            "ERROR: fatal error during startup\n"
            "Exception in thread main\nERROR: exiting\n", (5, 20))


def synthetic_cascade_world(
    n_services: int,
    n_roots: int = 1,
    seed: int = 0,
    namespace: str = "synthetic",
    pods_per_service: int = 1,
    mode: str = "standard",
    fault_mix: str = "crash",
) -> World:
    """Generate a full dict-world cascade (drives the agent/coordinator layer).

    Suitable up to a few thousand services; the raw-array form above covers
    10k-50k scale without dict materialization.  ``fault_mix`` selects the
    roots' fault archetypes exactly as in :func:`synthetic_cascade_arrays`
    — the dict world realizes each archetype as the K8s states the feature
    extractor and rule agents classify (ImagePullBackOff waiting status,
    OOMKilled termination, FailedScheduling events, ...).
    """
    case = synthetic_cascade_arrays(
        n_services, n_roots, seed, mode=mode, fault_mix=fault_mix,
    )
    rng = np.random.default_rng(seed + 1)
    names = [f"svc-{i:05d}" for i in range(n_services)]

    w = World(cluster_name=f"synthetic-{n_services}")
    n_nodes = max(2, n_services // 50)
    w.nodes = [make_node(f"node-{i}") for i in range(n_nodes)]
    w.node_metrics = {
        f"node-{i}": {
            "cpu": {"usage_percentage": int(rng.uniform(30, 70))},
            "memory": {"usage_percentage": int(rng.uniform(30, 70))},
        }
        for i in range(n_nodes)
    }

    root_set = set(case.roots.tolist())
    kind_of = dict(zip(case.roots.tolist(), case.root_kinds or []))
    hops = _bfs_hops(
        n_services, _dependents_adj(n_services, case.dep_src, case.dep_dst), case.roots
    )
    w.pod_metrics[namespace] = {"pods": {}}
    w.logs[namespace] = {}
    events: List[dict] = []

    # deps per service for env-var DNS inference (topology agent input)
    deps_of: Dict[int, List[int]] = {}
    for s, d in zip(case.dep_src.tolist(), case.dep_dst.tolist()):
        deps_of.setdefault(s, []).append(d)

    for i, svc in enumerate(names):
        faulty = i in root_set
        degraded = (not faulty) and hops[i] < np.iinfo(np.int32).max
        env = [
            {
                "name": f"DEP_{j}_URL",
                "value": f"http://{names[d]}.{namespace}.svc.cluster.local:8080",
            }
            for j, d in enumerate(deps_of.get(i, []))
        ]
        pod_names = []
        for r in range(pods_per_service):
            pod_name = f"{svc}-{r}"
            pod_names.append(pod_name)
            if faulty:
                status, phase, (ev_reason, ev_msg), log_text, (cpu_m, mem_mib) = (
                    _faulty_pod_parts(kind_of.get(i, "crash"), svc, rng)
                )
                pod = make_pod(
                    pod_name,
                    namespace,
                    svc,
                    phase=phase,
                    container_statuses=[status] if status is not None else [],
                )
                if log_text is not None:
                    w.logs[namespace][pod_name] = {svc: log_text}
                events.append(
                    make_event(
                        namespace, "Pod", pod_name, ev_reason, ev_msg,
                        count=int(rng.integers(5, 25)),
                    )
                )
                w.pod_metrics[namespace]["pods"][pod_name] = pod_metric(
                    cpu_m, mem_mib, 200, 128, svc
                )
            else:
                pod = make_pod(pod_name, namespace, svc)
                if degraded:
                    w.logs[namespace][pod_name] = {
                        svc: "WARN: upstream timeout\n"
                        "ERROR: connection timed out waiting for dependency\n"
                    }
                    events.append(
                        make_event(
                            namespace, "Pod", pod_name, "Unhealthy",
                            "Readiness probe failed: upstream dependency timeout",
                            count=int(rng.integers(1, 6)),
                        )
                    )
                else:
                    w.logs[namespace][pod_name] = {svc: "INFO: serving\n"}
                w.pod_metrics[namespace]["pods"][pod_name] = pod_metric(
                    int(rng.uniform(20, 120)), int(rng.uniform(30, 90)), 200, 128, svc
                )
            if env:
                pod["spec"]["containers"][0].setdefault("env", env)
            w.add("pods", namespace, pod)

        ready = 0 if faulty else pods_per_service
        w.add(
            "deployments",
            namespace,
            make_deployment(svc, namespace, svc, pods_per_service, ready),
        )
        w.add("services", namespace, make_service(svc, namespace))
        w.add(
            "endpoints",
            namespace,
            make_endpoints(svc, namespace, [] if faulty else pod_names),
        )

    w.events[namespace] = events

    # Traces derived from the same ground truth.
    latency = {}
    error_rates = {}
    for i, svc in enumerate(names):
        if i in root_set:
            error_rates[svc] = round(float(case.features[i, F_ERROR_RATE]), 3)
            latency[svc] = {"p50": 50, "p95": 120, "p99": 250}
        else:
            error_rates[svc] = round(float(case.features[i, F_ERROR_RATE]), 3)
            scale = 1.0 + 4.0 * float(case.features[i, F_LATENCY])
            latency[svc] = {
                "p50": int(100 * scale),
                "p95": int(300 * scale),
                "p99": int(600 * scale),
            }
    w.traces = {
        "trace_ids": {namespace: [f"trace-{i:05d}" for i in range(20)]},
        "traces": {},
        "latency": {namespace: latency},
        "error_rates": {namespace: error_rates},
        "dependencies": {
            namespace: {
                names[s]: sorted(names[d] for d in deps)
                for s, deps in ((k, v) for k, v in deps_of.items())
            }
        },
        "slow_ops": {namespace: []},
    }

    w.ground_truth = {
        "namespace": namespace,
        "fault_roots": [names[r] for r in case.roots.tolist()],
        "fault_kinds": list(case.root_kinds or []),
        "n_services": n_services,
        "seed": seed,
        "mode": mode,
        "fault_mix": fault_mix,
    }
    return w
