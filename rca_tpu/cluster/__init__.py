"""Cluster data-access layer: one typed client protocol, two backends.

Fixes the reference's real/mock interface skew (reference:
utils/k8s_client.py vs utils/mock_k8s_client.py — seven methods existed only
on the mock, and ``get_pod_logs`` argument order differed between definition
and call sites).  Here there is exactly one :class:`ClusterClient` protocol;
``MockClusterClient`` and ``K8sApiClient`` both implement it and a
conformance test asserts the surfaces match.
"""

from rca_tpu.cluster.protocol import ClusterClient, CLUSTER_CLIENT_METHODS
from rca_tpu.cluster.world import World
from rca_tpu.cluster.mock_client import MockClusterClient
from rca_tpu.cluster.snapshot import ClusterSnapshot

__all__ = [
    "ClusterClient",
    "CLUSTER_CLIENT_METHODS",
    "World",
    "MockClusterClient",
    "ClusterSnapshot",
]
