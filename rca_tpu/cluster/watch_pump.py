"""Background kubernetes watch pumps feeding the incremental-change feed.

The live :meth:`K8sApiClient.watch_changes` surface must never block the
1 Hz streaming poll loop on the API server, so watches run in daemon
threads: each pump holds one long ``kubernetes.watch.Watch`` stream (pods,
events) and appends ``{"kind", "name"}`` notifications to a bounded
thread-safe journal; consumers drain it without blocking.

One :class:`WatchPumpSet` is shared by every consumer of a namespace: the
journal is an append-only window with absolute sequence numbers, and each
consumer holds a **token** mapping to its own read position
(:meth:`register` / :meth:`drain`).  Two streaming sessions over the same
namespace therefore share two watch streams total instead of thrashing a
single token back and forth — the round-3 design replaced the whole set on
every reopen, so the other session's next poll saw a cursor mismatch and
degraded every poll into a full sweep+resync loop (round-3 advisor
finding).

Each pump pins its stream to a **resourceVersion**: an initial ``limit=1``
list yields the collection RV, every delivered event (and every bookmark —
``allow_watch_bookmarks``) advances it, and stream renewals resume FROM
that RV — without this, every 30 s renewal would replay the whole
collection as synthetic ADDED events and a 10k-pod namespace would
overflow the journal into a permanent expire/resync loop (round-3 review
finding).

Failure semantics mirror a real watch consumer's contract:

- **410 Gone** (the server compacted past our resourceVersion) or any
  stream error expires the whole pump set — every consumer re-lists (full
  resync) and reopens with ``cursor=None``;
- a consumer that falls further behind than the journal window retains
  expires **individually**; other consumers keep draining;
- a normal end of stream (server-side timeout) is NOT an expiry: the
  stream reopens at the tracked RV with no replay and no gap.

``stop()`` calls ``watch.Watch.stop()`` on each pump's stream handle in
addition to setting the stop event, so a stream terminates at its next
delivered event instead of looping into another 30 s renewal (round-3
advisor finding).  This is best-effort, not instant: the real kubernetes
client only checks the stop flag between yielded events, so a pump blocked
in a quiet HTTP read still lingers until the server-side
``timeout_seconds=30`` close — bounded, and harmless: a stopped pump's
late pushes land in an orphaned journal no consumer reads.

Tested hermetically with a stub ``kubernetes`` module
(tests/test_watch.py) — the same technique as the provider contract tests.

Replay note (ISSUE 5): this module is inside the flight recorder's
nondet-discipline fence (rca_tpu/analysis/rules/nondet.py) — it holds no
wall-clock reads by design.  Pump retry backoff sleeps through the
injectable seeded :class:`rca_tpu.resilience.policy.Retry`, and every
notification a consumer drains reaches the recorder as a
``watch_changes`` call result, so recordings capture the feed's OUTPUT
and never depend on pump thread timing.
"""

from __future__ import annotations

import collections
import itertools
import threading
from typing import Any, Dict, List, Optional

from rca_tpu.resilience.policy import Retry, record_fault, suppressed
from rca_tpu.util.threads import make_lock

QUEUE_CAP = 10_000
# registry bound: dropping a consumer record is always safe (an unknown
# token reads as expired, which forces the one correct recovery — resync)
MAX_CONSUMERS = 256

# resource kinds pumped: churn in these drives streaming features; other
# kinds (services, deployments, config) change topology and are handled by
# the session's periodic full check
_PUMPED = (
    ("pod", "list_namespaced_pod"),
    ("event", "list_namespaced_event"),
)


def _looks_like_gone(exc: BaseException) -> bool:
    """Is this a 410 Gone (resourceVersion compacted away)?  A 410 is NOT
    retryable at the stream level: the tracked RV is dead, consumers must
    re-list.  Matched on the ApiException status when present, else on the
    server's message shape."""
    status = getattr(exc, "status", None)
    if status == 410:
        return True
    msg = str(exc).lower()
    return "410" in msg or "too old resource version" in msg or (
        "expired" in msg
    )


def _meta_attr(obj: Any, attr: str) -> str:
    meta = getattr(obj, "metadata", None)
    if meta is not None:
        return getattr(meta, attr, "") or ""
    if isinstance(obj, dict):
        key = "resourceVersion" if attr == "resource_version" else attr
        return obj.get("metadata", {}).get(key, "") or ""
    return ""


class _Pump(threading.Thread):
    def __init__(self, owner: "WatchPumpSet", kind: str, list_method: str):
        super().__init__(daemon=True, name=f"rca-watch-{kind}")
        self.owner = owner
        self.kind = kind
        self.list_method = list_method
        self.watch_handle: Optional[Any] = None

    def run(self) -> None:
        from kubernetes import watch

        w = watch.Watch()
        # published so WatchPumpSet.stop() can break the blocking stream
        # iteration promptly instead of waiting out the server timeout
        self.watch_handle = w
        list_fn = getattr(self.owner.core, self.list_method)
        retry = self.owner.retry
        attempt = 0
        rv = None
        listed = False
        try:
            while not self.owner._stop.is_set():
                try:
                    if not listed:
                        # initial list pins the stream start (collection
                        # RV): the watch resumes from "now" with no
                        # synthetic replay of the existing objects
                        resp = list_fn(
                            namespace=self.owner.namespace, limit=1
                        )
                        rv = getattr(
                            getattr(resp, "metadata", None),
                            "resource_version", None,
                        )
                        listed = True
                    stream = w.stream(
                        list_fn,
                        namespace=self.owner.namespace,
                        timeout_seconds=30,
                        resource_version=rv,
                        allow_watch_bookmarks=True,
                    )
                    for ev in stream:
                        if self.owner._stop.is_set():
                            return
                        obj = ev.get("object")
                        # every event (bookmarks included) advances the RV
                        # so the next renewal resumes without replay
                        new_rv = _meta_attr(obj, "resource_version")
                        if new_rv:
                            rv = new_rv
                        if str(ev.get("type", "")).upper() == "BOOKMARK":
                            continue
                        name = _meta_attr(obj, "name")
                        if self.kind == "event":
                            # the change the analyzer cares about is the
                            # event's INVOLVED object; fall back to the
                            # event's own name
                            inv = getattr(obj, "involved_object", None)
                            if inv is not None and getattr(inv, "name", ""):
                                name = inv.name
                            elif isinstance(obj, dict):
                                name = (
                                    obj.get("involvedObject", {})
                                    .get("name", "")
                                    or name
                                )
                        if name:
                            # the delivered object's resourceVersion rides
                            # along (ISSUE 10): row-write consumers key
                            # re-encodes on it and skip already-seen
                            # versions without a re-fetch
                            self.owner.push(self.kind, name, rv=new_rv)
                    # normal stream end (server timeout): reopen at the
                    # tracked RV; a clean round also resets the backoff
                    attempt = 0
                except Exception as exc:
                    if self.owner._stop.is_set():
                        # a teardown-induced stream break is a shutdown,
                        # not a 410: expiring here would force every
                        # consumer of the NEXT connection's feed into a
                        # spurious resync
                        return
                    if _looks_like_gone(exc) or attempt >= retry.attempts:
                        # 410 (RV compacted — consumers MUST re-list) or
                        # retries exhausted: a dead pump silently dropping
                        # changes would be the one unrecoverable failure
                        # mode, so expire the set loudly
                        self.owner.mark_expired()
                        return
                    # transient stream error: resuming at the tracked RV
                    # replays nothing and loses nothing (that is what RV
                    # tracking buys) — back off and reopen instead of
                    # expiring every consumer into a full resync
                    attempt += 1
                    record_fault(f"watch_pump.{self.kind}.reopen", exc)
                    retry.sleep_for(attempt)
        finally:
            w.stop()


# process-wide consumer-token sequence.  This was a CLASS attribute
# incremented under each instance's own lock — a per-instance lock cannot
# guard class-shared state, so two pump sets (two namespaces) registering
# concurrently could mint the SAME token and silently cross their read
# positions.  gravelock's race-guard flags exactly that shape; the fix is
# a module-level atomic counter (itertools.count.__next__ is one bytecode
# on CPython — no lock needed, no shared RMW left to race).
_TOKEN_SEQ = itertools.count(1)


class WatchPumpSet:
    """Shared pumps + change journal for one namespace, many consumers."""

    def __init__(self, core_api: Any, namespace: str,
                 retry: Optional[Retry] = None):
        self.core = core_api
        self.namespace = namespace
        # transient stream errors reopen at the tracked RV with backoff
        # before the set expires (a 410 still expires immediately);
        # injectable for hermetic tests
        self.retry = retry or Retry(
            attempts=2, base_delay=0.2, max_delay=5.0, seed=0,
        )
        self._lock = make_lock("WatchPumpSet._lock")
        # journal window: _journal[i] has absolute sequence _base + i
        self._journal: collections.deque = collections.deque()
        self._base = 0
        self._next = 0
        # token -> absolute read position
        self._consumers: Dict[str, int] = {}
        self._stop = threading.Event()
        self._expired = threading.Event()
        self._threads = [_Pump(self, k, m) for k, m in _PUMPED]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            w = t.watch_handle
            if w is not None:
                with suppressed("watch_pump.stop"):
                    w.stop()

    # -- consumer registry --------------------------------------------------
    def register(self) -> str:
        """New consumer token positioned at the journal head (changes that
        predate the registration are the caller's resync's problem)."""
        with self._lock:
            token = f"pumps-{next(_TOKEN_SEQ)}"
            self._consumers[token] = self._next
            if len(self._consumers) > MAX_CONSUMERS:
                # evict the most-behind token (likely abandoned by a
                # resync); if its owner ever polls again the unknown token
                # reads as expired — the correct recovery either way
                victim = min(self._consumers, key=self._consumers.get)
                del self._consumers[victim]
            return token

    def deregister(self, token: str) -> None:
        """Drop a consumer whose owner is done with it (e.g. a session
        acquiring a fresh token on resync).  Without this, an abandoned
        token pins the journal's trim floor at its frozen position and the
        window sits at ``QUEUE_CAP`` entries forever on a busy namespace."""
        with self._lock:
            self._consumers.pop(token, None)
            floor = min(self._consumers.values(), default=self._next)
            while self._journal and self._base < floor:
                self._journal.popleft()
                self._base += 1

    def push(self, kind: str, name: str, rv: str = "") -> None:
        with self._lock:
            entry = {"kind": kind, "name": name}
            if rv:
                entry["rv"] = rv
            self._journal.append(entry)
            self._next += 1
            # trim what every consumer has already read
            floor = min(self._consumers.values(), default=self._next)
            while self._journal and self._base < floor:
                self._journal.popleft()
                self._base += 1
            # cap the window regardless: consumers lagging past the cap
            # expire individually on their next drain
            while len(self._journal) > QUEUE_CAP:
                self._journal.popleft()
                self._base += 1

    def drain(self, token: str) -> Optional[List[Dict[str, str]]]:
        """Changes since this consumer's position, deduped; ``None`` means
        the consumer (or the whole set) expired and must resync."""
        with self._lock:
            if self._expired.is_set():
                self._consumers.pop(token, None)
                return None
            pos = self._consumers.get(token)
            if pos is None or pos < self._base:
                # unknown token or lagged past the retained window
                self._consumers.pop(token, None)
                return None
            by_key: Dict[tuple, Dict[str, str]] = {}
            out = []
            for i in range(pos - self._base, len(self._journal)):
                c = self._journal[i]
                key = (c["kind"], c["name"])
                prev = by_key.get(key)
                if prev is None:
                    rec = dict(c)
                    by_key[key] = rec
                    out.append(rec)
                elif c.get("rv"):
                    # deduped entry keeps its first-seen position but the
                    # NEWEST resourceVersion (a row write wants the latest)
                    prev["rv"] = c["rv"]
            self._consumers[token] = self._next
            return out

    @property
    def expired(self) -> bool:
        return self._expired.is_set()

    def mark_expired(self) -> None:
        self._expired.set()
