"""Background kubernetes watch pumps feeding the incremental-change feed.

The live :meth:`K8sApiClient.watch_changes` surface must never block the
1 Hz streaming poll loop on the API server, so watches run in daemon
threads: each pump holds one long ``kubernetes.watch.Watch`` stream (pods,
events) and appends ``{"kind", "name"}`` notifications to a bounded
thread-safe queue; :meth:`WatchPumpSet.drain` empties it without blocking.

Each pump pins its stream to a **resourceVersion**: an initial ``limit=1``
list yields the collection RV, every delivered event (and every bookmark —
``allow_watch_bookmarks``) advances it, and stream renewals resume FROM
that RV — without this, every 30 s renewal would replay the whole
collection as synthetic ADDED events and a 10k-pod namespace would
overflow the queue into a permanent expire/resync loop (round-3 review
finding).

Failure semantics mirror a real watch consumer's contract:

- **410 Gone** (the server compacted past our resourceVersion), queue
  overflow, or any stream error marks the pump set ``expired`` — the
  caller re-lists (full resync) and reopens with ``cursor=None``;
- a normal end of stream (server-side timeout) is NOT an expiry: the
  stream reopens at the tracked RV with no replay and no gap.

Tested hermetically with a stub ``kubernetes`` module
(tests/test_watch.py) — the same technique as the provider contract tests.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List

QUEUE_CAP = 10_000

# resource kinds pumped: churn in these drives streaming features; other
# kinds (services, deployments, config) change topology and are handled by
# the session's periodic full check
_PUMPED = (
    ("pod", "list_namespaced_pod"),
    ("event", "list_namespaced_event"),
)


def _meta_attr(obj: Any, attr: str) -> str:
    meta = getattr(obj, "metadata", None)
    if meta is not None:
        return getattr(meta, attr, "") or ""
    if isinstance(obj, dict):
        key = "resourceVersion" if attr == "resource_version" else attr
        return obj.get("metadata", {}).get(key, "") or ""
    return ""


class _Pump(threading.Thread):
    def __init__(self, owner: "WatchPumpSet", kind: str, list_method: str):
        super().__init__(daemon=True, name=f"rca-watch-{kind}")
        self.owner = owner
        self.kind = kind
        self.list_method = list_method

    def run(self) -> None:
        from kubernetes import watch

        w = watch.Watch()
        list_fn = getattr(self.owner.core, self.list_method)
        try:
            # initial list pins the stream start (collection RV): the
            # watch resumes from "now" with no synthetic replay of the
            # existing objects
            resp = list_fn(namespace=self.owner.namespace, limit=1)
            rv = getattr(
                getattr(resp, "metadata", None), "resource_version", None,
            )
            while not self.owner._stop.is_set():
                stream = w.stream(
                    list_fn,
                    namespace=self.owner.namespace,
                    timeout_seconds=30,
                    resource_version=rv,
                    allow_watch_bookmarks=True,
                )
                for ev in stream:
                    if self.owner._stop.is_set():
                        return
                    obj = ev.get("object")
                    # every event (bookmarks included) advances the RV so
                    # the next renewal resumes without replay
                    new_rv = _meta_attr(obj, "resource_version")
                    if new_rv:
                        rv = new_rv
                    if str(ev.get("type", "")).upper() == "BOOKMARK":
                        continue
                    name = _meta_attr(obj, "name")
                    if self.kind == "event":
                        # the change the analyzer cares about is the event's
                        # INVOLVED object; fall back to the event's own name
                        inv = getattr(obj, "involved_object", None)
                        if inv is not None and getattr(inv, "name", ""):
                            name = inv.name
                        elif isinstance(obj, dict):
                            name = (
                                obj.get("involvedObject", {}).get("name", "")
                                or name
                            )
                    if name:
                        self.owner.push(self.kind, name)
                # normal stream end (server timeout): reopen at tracked RV
        except Exception:
            # 410 Gone / network error / anything: the consumer must
            # re-list; a dead pump silently dropping changes would be the
            # one unrecoverable failure mode
            self.owner.mark_expired()
        finally:
            w.stop()


class WatchPumpSet:
    """One pump per watched kind for a single namespace."""

    _counter = 0

    def __init__(self, core_api: Any, namespace: str):
        self.core = core_api
        self.namespace = namespace
        WatchPumpSet._counter += 1
        self.token = f"pumps-{WatchPumpSet._counter}"
        self._lock = threading.Lock()
        self._queue: collections.deque = collections.deque()
        self._stop = threading.Event()
        self._expired = threading.Event()
        self._threads = [_Pump(self, k, m) for k, m in _PUMPED]

    def start(self) -> None:
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()

    def push(self, kind: str, name: str) -> None:
        with self._lock:
            if len(self._queue) >= QUEUE_CAP:
                # overflow: the consumer fell too far behind to trust a
                # drain — same contract as a compacted resourceVersion
                self._expired.set()
                return
            self._queue.append({"kind": kind, "name": name})

    def drain(self) -> List[Dict[str, str]]:
        with self._lock:
            seen = set()
            out = []
            while self._queue:
                c = self._queue.popleft()
                key = (c["kind"], c["name"])
                if key not in seen:
                    seen.add(key)
                    out.append(c)
            return out

    @property
    def expired(self) -> bool:
        return self._expired.is_set()

    def mark_expired(self) -> None:
        self._expired.set()
