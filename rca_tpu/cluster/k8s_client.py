"""Live-cluster client: kubernetes python lib when available, kubectl fallback.

Implements the same :class:`ClusterClient` protocol as the mock — including
the trace methods (which return empty structures unless a trace backend is
configured), so agents never hit AttributeError against a live cluster the
way the reference's mock-only methods did (reference: utils/mock_k8s_client.py
:1044-1303 vs utils/k8s_client.py — seven methods existed only on the mock).

Metrics come from ``kubectl top`` subprocess parsing with usage percentages
computed against container limits, matching the reference's approach
(reference: utils/k8s_client.py:441-554), and resource-quantity parsing
covers millicores and the full binary/decimal memory suffix ladder
(reference: utils/k8s_client.py:886-947).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
from typing import Any, Dict, List, Optional

from rca_tpu.config import env_int, env_raw
from rca_tpu.findings import utcnow_iso
from rca_tpu.resilience.policy import Retry, suppressed

try:  # gated: the kubernetes lib is an optional dependency
    from kubernetes import client as k8s_api
    from kubernetes import config as k8s_config

    HAVE_K8S_LIB = True
except Exception:  # pragma: no cover - exercised only without the lib
    k8s_api = None
    k8s_config = None
    HAVE_K8S_LIB = False


# ---------------------------------------------------------------------------
# Resource-quantity parsing
# ---------------------------------------------------------------------------

_MEM_SUFFIXES = {
    "Ki": 2**10, "Mi": 2**20, "Gi": 2**30, "Ti": 2**40, "Pi": 2**50, "Ei": 2**60,
    "K": 10**3, "M": 10**6, "G": 10**9, "T": 10**12, "P": 10**15, "E": 10**18,
    "k": 10**3, "m": 1e-3,
}


def parse_cpu(value: Any) -> float:
    """CPU quantity -> millicores. '100m' -> 100, '2' -> 2000, '1500000n' -> 1.5."""
    if value is None:
        return 0.0
    s = str(value).strip()
    if not s:
        return 0.0
    try:
        if s.endswith("n"):
            return float(s[:-1]) / 1e6
        if s.endswith("u"):
            return float(s[:-1]) / 1e3
        if s.endswith("m"):
            return float(s[:-1])
        return float(s) * 1000.0
    except ValueError:
        return 0.0


def parse_memory(value: Any) -> float:
    """Memory quantity -> bytes. Handles Ki..Ei binary and K..E decimal."""
    if value is None:
        return 0.0
    s = str(value).strip()
    if not s:
        return 0.0
    for suffix in ("Ki", "Mi", "Gi", "Ti", "Pi", "Ei"):
        if s.endswith(suffix):
            try:
                return float(s[: -len(suffix)]) * _MEM_SUFFIXES[suffix]
            except ValueError:
                return 0.0
    for suffix in ("K", "M", "G", "T", "P", "E", "k", "m"):
        if s.endswith(suffix):
            try:
                return float(s[: -len(suffix)]) * _MEM_SUFFIXES[suffix]
            except ValueError:
                return 0.0
    try:
        return float(s)
    except ValueError:
        return 0.0


class K8sApiClient:
    """Live :class:`ClusterClient` backend."""

    def __init__(
        self,
        kubeconfig: Optional[str] = None,
        context: Optional[str] = None,
        verify_ssl: bool = True,
    ):
        self._connected = False
        self._core = self._apps = self._net = self._batch = self._autoscaling = None
        # degraded-mode channel: every swallowed API/kubectl failure is
        # recorded here so "empty" is distinguishable from "denied/broken"
        # (VERDICT round-1: an RBAC error must not read as a clean bill of
        # health; the reference at least surfaced connection errors,
        # reference: app.py:39-42)
        self._errors: List[Dict[str, str]] = []
        self._kubectl = shutil.which("kubectl")
        self._kubeconfig = kubeconfig or env_raw("KUBECONFIG")
        self._context = context
        self._verify_ssl = verify_ssl
        # transient API flakes retry with backoff before landing in the
        # degraded-mode error channel (RCA_API_RETRIES=0 disables)
        self._retry = Retry(
            attempts=env_int("RCA_API_RETRIES", 2, 0, 100),
            base_delay=0.1, max_delay=2.0, seed=0,
        )
        self._connect()

    def _connect(self) -> None:
        """(Re)build API clients and probe the connection."""
        self._connected = False
        # tear down watch pumps bound to the PREVIOUS connection: their
        # threads captured the old CoreV1Api at construction, so leaving
        # them running would keep serving the old cluster's change feed
        # (with still-valid tokens) while list/get calls hit the new one —
        # a streaming session would then patch new-cluster snapshots from
        # old-cluster churn (round-3 advisor finding).  Clearing the
        # registry makes every existing cursor read as expired, which
        # forces the one correct recovery: a full resync against the new
        # connection.
        with self._pumps_registry() as pumps_by_ns:
            for pumps in pumps_by_ns.values():
                with suppressed("k8s.reconnect_pump_stop"):
                    pumps.stop()
            pumps_by_ns.clear()
            # columnar feeds ride the pumps: their shadow worlds mirror
            # the previous cluster, so a reconnect discards them too —
            # feed generations make every old cursor read out-of-range
            # and the next get_columnar serves a fresh full dump
            self.__dict__.setdefault("_colfeeds", {}).clear()
        if not HAVE_K8S_LIB:
            return
        try:
            if self._kubeconfig:
                k8s_config.load_kube_config(
                    config_file=self._kubeconfig, context=self._context
                )
            else:
                try:
                    k8s_config.load_kube_config(context=self._context)
                except Exception:
                    k8s_config.load_incluster_config()
            if not self._verify_ssl:
                cfg = k8s_api.Configuration.get_default_copy()
                cfg.verify_ssl = False
                k8s_api.Configuration.set_default(cfg)
            self._core = k8s_api.CoreV1Api()
            self._apps = k8s_api.AppsV1Api()
            self._net = k8s_api.NetworkingV1Api()
            self._batch = k8s_api.BatchV1Api()
            self._autoscaling = k8s_api.AutoscalingV1Api()
            self._api_client = k8s_api.ApiClient()
            # connection probe (reference: utils/k8s_client.py:139)
            self._core.list_namespace(limit=1)
            self._connected = True
        except Exception as exc:
            self._record_error("connect", f"{type(exc).__name__}: {exc}")
            self._connected = False

    def reload_config(self) -> bool:
        """Re-read the kubeconfig and reconnect (reference:
        utils/k8s_client.py:181 reload_config)."""
        self._connect()
        return self._connected

    def _load_kubeconfigs(self, op: str):
        """(path, parsed) per readable kubeconfig file in the multi-file
        KUBECONFIG order, plus the resolved active context name — the ONE
        merge implementation the repair flow and the context picker share.
        Unreadable files are skipped with the failure recorded under
        ``op`` so a partial view is never silent."""
        import yaml

        raw = self._kubeconfig or os.path.expanduser("~/.kube/config")
        configs = []
        for path in [p for p in raw.split(os.pathsep) if p]:
            if not os.path.exists(path):
                continue
            try:
                with open(path) as f:
                    configs.append((path, yaml.safe_load(f) or {}))
            except Exception as exc:
                self._record_error(
                    op, f"{path}: {type(exc).__name__}: {exc}"
                )
        current = self._context or next(
            (c.get("current-context") for _, c in configs
             if c.get("current-context")), None,
        )
        return configs, current

    def update_server_url(self, new_server_url: str) -> bool:
        """Rewrite the CURRENT context's cluster ``server`` and reconnect —
        the endpoint-repair flow for tunneled clusters whose public URL
        rotates (reference: components/sidebar.py:7-47 rewrote every
        cluster; scoping to the active context keeps a multi-cluster
        kubeconfig's other entries intact).  Honors the colon-separated
        multi-file ``KUBECONFIG`` form by repairing the file that defines
        the target cluster, and leaves a ``<file>.bak`` of the original."""
        try:
            import yaml

            # pass 1 — merged view, the way the kubernetes lib reads the
            # multi-file form: resolve the active context, then the cluster
            # it points at, across ALL files
            configs, ctx_name = self._load_kubeconfigs("update_server_url")
            target = next(
                ((ctx.get("context") or {}).get("cluster")
                 for _, c in configs
                 for ctx in c.get("contexts", []) or []
                 if ctx.get("name") == ctx_name),
                None,
            )
            if target is None:
                all_clusters = [
                    cl for _, c in configs
                    for cl in c.get("clusters", []) or []
                ]
                if len(all_clusters) == 1:
                    target = all_clusters[0].get("name")

            # pass 2 — rewrite the one file that defines the target cluster
            for path, cfg in configs:
                updated = False
                for cluster in cfg.get("clusters", []) or []:
                    if cluster.get("name") != target:
                        continue
                    inner = cluster.get("cluster")
                    if isinstance(inner, dict) and "server" in inner:
                        inner["server"] = new_server_url
                        updated = True
                if not updated:
                    continue
                # keep only the FIRST backup: a retry after a typo'd URL
                # must not clobber the pristine original with the mangled
                # intermediate
                if not os.path.exists(path + ".bak"):
                    original = open(path).read()
                    with open(path + ".bak", "w") as f:
                        f.write(original)
                with open(path, "w") as f:
                    yaml.safe_dump(cfg, f, sort_keys=False)
                return self.reload_config()
            self._record_error(
                "update_server_url",
                "no kubeconfig file defines the active context's cluster "
                "(or has a server entry to rewrite): "
                + ", ".join(p for p, _ in configs),
            )
            return False
        except Exception as exc:
            self._record_error(
                "update_server_url", f"{type(exc).__name__}: {exc}"
            )
            return False

    def list_contexts(self) -> Dict[str, Any]:
        """Contexts defined across the kubeconfig file(s) plus the active
        one — the sidebar's context picker reads this (reference:
        components/sidebar.py namespace/context pickers).  Honors the
        colon-separated multi-file ``KUBECONFIG`` form; unreadable files
        are skipped with the failure recorded, so the listing is as
        complete as the readable files allow."""
        configs, current = self._load_kubeconfigs("list_contexts")
        names: List[str] = []
        for _, cfg in configs:
            for ctx in cfg.get("contexts", []) or []:
                name = ctx.get("name")
                if name and name not in names:
                    names.append(name)
        return {"contexts": names, "current": current}

    def switch_context(self, context: str) -> bool:
        """Reconnect against another kubeconfig context (reference:
        components/sidebar.py context picker).  Leaves the kubeconfig file
        untouched — the choice is per-client.  In kubectl-only mode (no
        kubernetes lib) the switch validates the target context with a
        bounded kubectl probe instead of the lib reconnect."""
        previous = self._context
        self._context = context
        self._connect()
        if self._connected:
            return True
        if not HAVE_K8S_LIB and self._kubectl:
            # kubectl-only clients can still serve data for the new
            # context (run_kubectl passes --context); validate it works
            cmd = [self._kubectl]
            if self._kubeconfig:
                cmd += ["--kubeconfig", self._kubeconfig]
            cmd += ["--context", context, "get", "namespaces",
                    "-o", "name", "--request-timeout=5s"]
            with suppressed("k8s.switch_context_probe"):
                proc = subprocess.run(
                    cmd, capture_output=True, text=True, timeout=10,
                    check=False,
                )
                if proc.returncode == 0:
                    return True
        # restore rather than strand the client on a broken context
        self._context = previous
        self._connect()
        return False

    # ---- helpers ---------------------------------------------------------
    def _pumps_registry(self):
        """Locked access to the namespace→WatchPumpSet registry.  The lock
        and dict are created lazily (and atomically, via ``setdefault`` on
        ``__dict__``) because concurrent sessions call ``watch_changes``
        from their own threads — an unlocked check-then-create would let
        two openers race and orphan a started pump set whose watch threads
        nothing ever stops."""
        import contextlib

        from rca_tpu.util.threads import make_lock

        lock = self.__dict__.setdefault(
            "_pumps_lock", make_lock("K8sApiClient._pumps_lock")
        )
        pumps = self.__dict__.setdefault("_pumps", {})

        @contextlib.contextmanager
        def held():
            with lock:
                yield pumps

        return held()

    def _sanitize(self, obj: Any) -> Any:
        return self._api_client.sanitize_for_serialization(obj)

    def _record_error(self, op: str, detail: str) -> None:
        if len(self._errors) < 100:
            self._errors.append({"op": op, "error": detail[:300]})

    def collect_errors(self, clear: bool = True) -> List[Dict[str, str]]:
        """Swallowed failures since the last drain.  Callers (snapshot
        capture, UI status) surface these as "analysis ran against partial
        cluster state"."""
        out = list(self._errors)
        if clear:
            self._errors.clear()
        return out

    def _list(self, api, method: str, *args, **kwargs) -> List[dict]:
        # api object is looked up lazily so disconnected clients (no
        # kubernetes lib / no cluster) degrade to [] instead of raising —
        # but NEVER silently: the failure lands in the error channel.
        # Transient failures retry with backoff first (self._retry).
        if not self._connected or api is None:
            return []
        try:
            resp = self._retry.call(getattr(api, method), *args, **kwargs)
            return [self._sanitize(item) for item in resp.items]
        except Exception as exc:
            self._record_error(method, f"{type(exc).__name__}: {exc}")
            return []

    def _kubectl_json(self, args: List[str]) -> Any:
        out = self.run_kubectl(args + ["-o", "json"])
        try:
            return json.loads(out)
        except Exception:
            return None

    # ---- connection / identity -------------------------------------------
    def is_connected(self) -> bool:
        return self._connected or self._kubectl is not None

    def get_current_time(self) -> str:
        return utcnow_iso()

    def get_cluster_info(self) -> Dict[str, Any]:
        return {
            "connected": self._connected,
            "kubeconfig": self._kubeconfig,
            "nodes": len(self.get_nodes()),
            "errors": self.collect_errors(clear=False)[-10:],
            "mock": False,
        }

    def get_namespaces(self) -> List[str]:
        items = self._list(self._core, "list_namespace") if self._connected else []
        return [i.get("metadata", {}).get("name", "") for i in items]

    # ---- pods ------------------------------------------------------------
    def get_pods(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._core, "list_namespaced_pod", namespace)

    def get_pod(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        if not self._connected:
            return None
        try:
            return self._sanitize(self._retry.call(
                self._core.read_namespaced_pod, name, namespace
            ))
        except Exception as exc:
            self._record_error(
                "read_namespaced_pod", f"{type(exc).__name__}: {exc}"
            )
            return None

    def get_pod_logs(
        self,
        namespace: str,
        pod_name: str,
        container: Optional[str] = None,
        previous: bool = False,
        tail_lines: Optional[int] = None,
    ) -> str:
        if not self._connected:
            return ""
        try:
            return self._retry.call(
                self._core.read_namespaced_pod_log,
                pod_name,
                namespace,
                container=container,
                previous=previous,
                tail_lines=tail_lines,
            )
        except Exception as exc:
            self._record_error(
                "read_namespaced_pod_log", f"{type(exc).__name__}: {exc}"
            )
            return f"Error retrieving logs: {exc}"

    def get_recently_terminated_pods(self, namespace: str) -> List[Dict[str, Any]]:
        out = []
        for pod in self.get_pods(namespace):
            for cs in pod.get("status", {}).get("containerStatuses", []) or []:
                state = cs.get("state") or {}
                last = cs.get("lastState") or {}
                if "terminated" in state or "terminated" in last:
                    out.append(pod)
                    break
        return out

    # ---- workloads -------------------------------------------------------
    def get_deployments(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._apps, "list_namespaced_deployment", namespace)

    def get_deployment(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        if not self._connected:
            return None
        try:
            return self._sanitize(
                self._apps.read_namespaced_deployment(name, namespace)
            )
        except Exception:
            return None

    def get_statefulsets(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._apps, "list_namespaced_stateful_set", namespace)

    def get_daemonsets(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._apps, "list_namespaced_daemon_set", namespace)

    def get_cronjobs(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._batch, "list_namespaced_cron_job", namespace)

    # ---- services / networking -------------------------------------------
    def get_services(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._core, "list_namespaced_service", namespace)

    def get_service(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        if not self._connected:
            return None
        try:
            return self._sanitize(self._core.read_namespaced_service(name, namespace))
        except Exception:
            return None

    def get_endpoints(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._core, "list_namespaced_endpoints", namespace)

    def get_ingresses(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._net, "list_namespaced_ingress", namespace)

    def get_network_policies(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._net, "list_namespaced_network_policy", namespace)

    # ---- config / storage ------------------------------------------------
    def get_configmaps(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._core, "list_namespaced_config_map", namespace)

    def get_secrets(self, namespace: str) -> List[Dict[str, Any]]:
        secrets = self._list(self._core, "list_namespaced_secret", namespace)
        # redact values (reference: utils/k8s_client.py:693-698)
        for s in secrets:
            if isinstance(s.get("data"), dict):
                s["data"] = {k: "**REDACTED**" for k in s["data"]}
        return secrets

    def get_pvcs(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(
            self._core, "list_namespaced_persistent_volume_claim", namespace
        )

    def get_pvc(self, namespace: str, name: str) -> Optional[Dict[str, Any]]:
        for p in self.get_pvcs(namespace):
            if p.get("metadata", {}).get("name") == name:
                return p
        return None

    def get_resource_quotas(self, namespace: str) -> List[Dict[str, Any]]:
        return self._list(self._core, "list_namespaced_resource_quota", namespace)

    # ---- nodes / metrics / autoscaling -----------------------------------
    def get_nodes(self) -> List[Dict[str, Any]]:
        if not self._connected:
            return []
        return self._list(self._core, "list_node")

    def get_node_metrics(self) -> Dict[str, Any]:
        """Parse ``kubectl top nodes`` into per-node usage percentages."""
        out = self.run_kubectl(["top", "nodes", "--no-headers"])
        metrics: Dict[str, Any] = {}
        for line in out.splitlines():
            parts = line.split()
            # NAME CPU(cores) CPU% MEMORY(bytes) MEMORY%
            if len(parts) >= 5 and parts[2].endswith("%") and parts[4].endswith("%"):
                try:
                    metrics[parts[0]] = {
                        "cpu": {
                            "usage": parts[1],
                            "usage_percentage": float(parts[2].rstrip("%")),
                        },
                        "memory": {
                            "usage": parts[3],
                            "usage_percentage": float(parts[4].rstrip("%")),
                        },
                    }
                except ValueError:
                    continue
        return metrics

    def get_pod_metrics(self, namespace: str) -> Dict[str, Any]:
        """``kubectl top pods --containers`` joined against container limits."""
        limits: Dict[str, Dict[str, Dict[str, float]]] = {}
        for pod in self.get_pods(namespace):
            pod_name = pod.get("metadata", {}).get("name", "")
            for c in pod.get("spec", {}).get("containers", []) or []:
                lim = (c.get("resources") or {}).get("limits") or {}
                limits.setdefault(pod_name, {})[c["name"]] = {
                    "cpu_m": parse_cpu(lim.get("cpu")),
                    "mem_b": parse_memory(lim.get("memory")),
                }
        out = self.run_kubectl(
            ["top", "pods", "-n", namespace, "--containers", "--no-headers"]
        )
        pods: Dict[str, Any] = {}
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 4:
                continue
            pod_name, container, cpu_s, mem_s = parts[0], parts[1], parts[2], parts[3]
            cpu_m = parse_cpu(cpu_s)
            mem_b = parse_memory(mem_s)
            rec = pods.setdefault(
                pod_name,
                {"cpu": {"usage_m": 0.0}, "memory": {"usage_b": 0.0}, "containers": {}},
            )
            rec["cpu"]["usage_m"] += cpu_m
            rec["memory"]["usage_b"] += mem_b
            entry: Dict[str, Any] = {
                "cpu": {"usage": cpu_s},
                "memory": {"usage": mem_s},
            }
            lim = limits.get(pod_name, {}).get(container)
            if lim:
                if lim["cpu_m"]:
                    entry["cpu"]["usage_percentage"] = round(
                        100.0 * cpu_m / lim["cpu_m"], 2
                    )
                if lim["mem_b"]:
                    entry["memory"]["usage_percentage"] = round(
                        100.0 * mem_b / lim["mem_b"], 2
                    )
            rec["containers"][container] = entry
        # pod-level percentages: max over containers (worst container governs)
        for rec in pods.values():
            cpu_pcts = [
                c["cpu"].get("usage_percentage")
                for c in rec["containers"].values()
                if c["cpu"].get("usage_percentage") is not None
            ]
            mem_pcts = [
                c["memory"].get("usage_percentage")
                for c in rec["containers"].values()
                if c["memory"].get("usage_percentage") is not None
            ]
            if cpu_pcts:
                rec["cpu"]["usage_percentage"] = max(cpu_pcts)
            if mem_pcts:
                rec["memory"]["usage_percentage"] = max(mem_pcts)
        return {"pods": pods}

    def get_hpas(self, namespace: str) -> List[Dict[str, Any]]:
        if self._connected:
            return self._list(
                self._autoscaling,
                "list_namespaced_horizontal_pod_autoscaler",
                namespace,
            )
        data = self._kubectl_json(["get", "hpa", "-n", namespace])
        return (data or {}).get("items", [])

    # ---- events ----------------------------------------------------------
    def get_events(
        self, namespace: str, field_selector: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        if not self._connected:
            return []
        try:
            resp = self._retry.call(
                self._core.list_namespaced_event,
                namespace, field_selector=field_selector,
            )
            return [self._sanitize(i) for i in resp.items]
        except Exception as exc:
            self._record_error(
                "list_namespaced_event", f"{type(exc).__name__}: {exc}"
            )
            return []

    # ---- traces -----------------------------------------------------------
    # A REAL live signal when RCA_TRACE_ENDPOINT points at a Jaeger query
    # service (VERDICT r3 item 5; rca_tpu/cluster/trace_backend.py); empty
    # structures otherwise — which matches the reference, whose live
    # client had no trace surface at all (trace data existed only on its
    # mock, reference: utils/mock_k8s_client.py:1146-1303).
    def _traces(self):
        backend = self.__dict__.get("_trace_backend", False)
        if backend is False:
            from rca_tpu.cluster.trace_backend import make_trace_backend

            backend = self._trace_backend = make_trace_backend()
        return backend

    def _trace_call(self, method: str, default, *args):
        backend = self._traces()
        if backend is None:
            return default
        out = getattr(backend, method)(*args)
        for err in backend.errors:
            self._record_error(f"trace.{method}", err)
        backend.errors.clear()
        return out

    def get_trace_ids(self, namespace: str, limit: int = 20) -> List[str]:
        return self._trace_call("trace_ids", [], namespace, limit)

    def get_trace_details(self, trace_id: str) -> Dict[str, Any]:
        return self._trace_call("trace_details", {}, trace_id)

    def get_service_latency_stats(self, namespace: str) -> Dict[str, Any]:
        return self._trace_call("service_latency_stats", {}, namespace)

    def get_error_rate_by_service(self, namespace: str) -> Dict[str, Any]:
        return self._trace_call("error_rate_by_service", {}, namespace)

    def get_service_dependencies(self, namespace: str) -> Dict[str, Any]:
        return self._trace_call("service_dependencies", {}, namespace)

    def find_slow_operations(
        self, namespace: str, threshold_ms: float = 500.0
    ) -> List[Dict[str, Any]]:
        return self._trace_call(
            "find_slow_operations", [], namespace, threshold_ms
        )

    # ---- generic ---------------------------------------------------------
    _KIND_ALIASES = {
        "pod": "pod", "deployment": "deployment", "statefulset": "statefulset",
        "daemonset": "daemonset", "cronjob": "cronjob", "service": "service",
        "endpoints": "endpoints", "ingress": "ingress",
        "networkpolicy": "networkpolicy", "configmap": "configmap",
        "secret": "secret", "persistentvolumeclaim": "pvc", "pvc": "pvc",
        "resourcequota": "resourcequota", "horizontalpodautoscaler": "hpa",
        "hpa": "hpa", "node": "node",
    }

    def get_resource_details(
        self, namespace: str, kind: str, name: str
    ) -> Dict[str, Any]:
        k = self._KIND_ALIASES.get(kind.lower())
        if k is None:
            return {"error": f"unsupported resource kind: {kind}"}
        data = self._kubectl_json(["get", k, name, "-n", namespace])
        if data is None:
            return {"error": f"{kind}/{name} not found in namespace {namespace}"}
        if isinstance(data, dict):
            from rca_tpu.findings import annotate_created_ago

            annotate_created_ago(data, self.get_current_time())
        return data

    # ---- incremental changes (watch surface) ------------------------------
    def watch_changes(
        self, namespace: str, cursor: Optional[str]
    ) -> Dict[str, Any]:
        """Kubernetes-watch-backed incremental change feed (VERDICT r2
        item 6).  Background pump threads hold long watch streams on pods
        and events (the kinds whose churn drives streaming features) and
        queue ``(kind, name)`` notifications; each call drains the queue
        without blocking — the poll loop never waits on the API server.

        ``cursor=None`` registers a NEW consumer on the namespace's shared
        pump set (creating the set on first use) and returns its token —
        any number of sessions share the same two watch streams, each with
        its own read position, so concurrent sessions on one namespace no
        longer thrash each other's feed (round-3 advisor finding).  A pump
        death (410 Gone, network error), a consumer lagging past the
        journal window, or an unknown/stale token reports ``expired`` —
        the caller resyncs from a full list exactly as a real watch
        consumer re-lists, then reopens with ``cursor=None``.  Without the
        kubernetes lib (kubectl-only clients) this surface is
        ``supported: False`` and callers keep the full-sweep path."""
        if not HAVE_K8S_LIB or not self._connected:
            return {"supported": False, "cursor": None,
                    "expired": False, "changes": []}
        from rca_tpu.cluster.watch_pump import WatchPumpSet

        # one pump set PER NAMESPACE, shared by all consumers of it
        with self._pumps_registry() as pumps_by_ns:
            pumps = pumps_by_ns.get(namespace)
            if cursor is None:
                if pumps is None or pumps.expired:
                    # a dead set is replaced; live consumers of the old set
                    # observe expiry on their next drain and reopen here too
                    if pumps is not None:
                        pumps.stop()
                    pumps = pumps_by_ns[namespace] = WatchPumpSet(
                        self._core, namespace
                    )
                    # register BEFORE starting so nothing the pumps deliver
                    # can land ahead of the first consumer's read position
                    token = pumps.register()
                    pumps.start()
                else:
                    token = pumps.register()
                return {"supported": True, "cursor": token,
                        "expired": False, "changes": []}
        changes = pumps.drain(cursor) if pumps is not None else None
        if changes is None:
            return {"supported": True, "cursor": cursor,
                    "expired": True, "changes": []}
        return {"supported": True, "cursor": cursor,
                "expired": False, "changes": changes}

    # ---- columnar feed (live adapter; ISSUE 17) ---------------------------
    def get_columnar(self, namespace: str,
                     cursor: Optional[str] = None) -> Dict[str, Any]:
        """Live columnar capture feed: the same payload protocol the mock
        serves (full column dump once, ordered column-diff ops after, a
        full rebuild on watch expiry), built on the watch pumps' per-event
        resourceVersions by one :class:`~rca_tpu.cluster.live_columnar.
        LiveColumnarFeed` per namespace.  ``supported: False`` (no
        kubernetes lib / not connected / pumps unsupported) keeps callers
        on the dict-sweep path — ``ClusterSnapshot.capture`` falls back
        exactly as it does for degenerate worlds."""
        if not HAVE_K8S_LIB or not self._connected:
            return {"supported": False, "reason": "no live connection"}
        from rca_tpu.cluster.live_columnar import LiveColumnarFeed

        with self._pumps_registry():
            feeds = self.__dict__.setdefault("_colfeeds", {})
            feed = feeds.get(namespace)
            if feed is None:
                feed = feeds[namespace] = LiveColumnarFeed(self, namespace)
        return feed.payload(cursor)

    def watch_close(self, namespace: str, cursor: Optional[str]) -> None:
        """Release a consumer token acquired from :meth:`watch_changes`.
        Sessions call this when they abandon a cursor (resync acquires a
        fresh one) — an orphaned token would otherwise pin the shared
        journal's trim floor at its frozen read position forever."""
        if cursor is None:
            return
        with self._pumps_registry() as pumps_by_ns:
            pumps = pumps_by_ns.get(namespace)
        if pumps is not None:
            pumps.deregister(cursor)

    def run_kubectl(self, args: List[str]) -> str:
        if not self._kubectl:
            return "kubectl not available"
        cmd = [self._kubectl]
        if self._kubeconfig:
            cmd += ["--kubeconfig", self._kubeconfig]
        if self._context:
            # every kubectl-backed surface (top metrics, HPA fallback,
            # escape hatch) must follow a context switch, not silently
            # keep serving the previous cluster's data
            cmd += ["--context", self._context]
        cmd += args
        try:
            proc = subprocess.run(
                cmd, capture_output=True, text=True, timeout=30, check=False
            )
            if proc.returncode != 0:
                self._record_error(
                    "kubectl " + " ".join(args[:3]),
                    (proc.stderr or "").strip(),
                )
            return proc.stdout if proc.returncode == 0 else proc.stderr
        except Exception as exc:
            self._record_error(
                "kubectl " + " ".join(args[:3]), f"{type(exc).__name__}: {exc}"
            )
            return f"kubectl error: {exc}"
