"""Label-selector semantics shared by feature extraction, graph building,
and the agents (reference: agents/topology_agent.py:133 selector ⊆ labels)."""

from __future__ import annotations

from typing import Dict


def selector_matches(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    """True when every selector key/value pair appears in ``labels``.

    Empty selectors match nothing (a service without a selector is
    headless/external and backs no pods directly).
    """
    if not selector:
        return False
    return all(labels.get(k) == v for k, v in selector.items())
