"""Label-selector semantics shared by feature extraction, graph building,
and the agents (reference: agents/topology_agent.py:133 selector ⊆ labels)."""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple


def selector_matches(selector: Dict[str, str], labels: Dict[str, str]) -> bool:
    """True when every selector key/value pair appears in ``labels``.

    Empty selectors match nothing (a service without a selector is
    headless/external and backs no pods directly).
    """
    if not selector:
        return False
    return all(labels.get(k) == v for k, v in selector.items())


class SelectorIndex:
    """Inverted index over N selectors for O(labels) matching per query.

    Single-label selectors (the overwhelmingly common case) resolve by one
    dict lookup per label item; multi-label selectors index on their first
    item and verify the full subset only for those candidates.  Replaces the
    O(N) scan per workload/pod that made graph building quadratic.
    """

    def __init__(self, selectors: Sequence[Dict[str, str]]):
        self.selectors = list(selectors)
        self._by_item: Dict[Tuple[str, str], List[int]] = {}
        for j, sel in enumerate(self.selectors):
            if not sel:
                continue
            # index on the lexicographically-first item for determinism
            key = min(sel.items())
            self._by_item.setdefault(key, []).append(j)

    def matches(self, labels: Dict[str, str]) -> List[int]:
        """Indices of all selectors matching ``labels``, ascending."""
        if not labels:
            return []
        hits: List[int] = []
        for item in labels.items():
            for j in self._by_item.get(item, ()):
                sel = self.selectors[j]
                if len(sel) == 1 or selector_matches(sel, labels):
                    hits.append(j)
        hits.sort()
        return hits
