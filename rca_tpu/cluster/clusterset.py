"""ClusterSet: multi-cluster federation of capture (ISSUE 17).

One engine, many clusters.  A :class:`ClusterSet` holds N member
ClusterClients keyed by cluster id and presents the fleet two ways:

- **per-cluster**: merged namespaces are ``"<cluster>/<ns>"``
  (:meth:`ClusterSet.namespaces`); :meth:`ClusterSet.bound` binds one of
  them to a routed proxy whose whole client surface (``get_nodes``
  included) hits exactly that member — this is what streaming sessions,
  ingest workers, and the 1M-pod soak capture through, so snapshot
  parity is the member's own parity;
- **merged**: :meth:`ClusterSet.merged_client` returns a
  :class:`MergedClusterClient` presenting ONE namespace that unions the
  member namespaces of the same name — object names and node names are
  prefixed ``"<cluster>/"``, every pod grows a synthetic
  ``rca.tpu/cluster`` label and every service selector requires it (so
  selector matching — and therefore every service-membership edge —
  stays cluster-local), and trace-derived service-dependency edges are
  prefixed within their own cluster only.  ``get_columnar`` on the
  merged view is a :class:`~rca_tpu.cluster.live_columnar.
  LiveColumnarFeed` over the merged client itself — the SAME live
  adapter the real ``K8sApiClient`` uses, so merged columnar-vs-dict
  bit-parity is structural.

Identity rules (merged-world namespace-collision rejection): cluster ids
must be unique, non-empty, and ``"/"``-free (the separator), and member
namespaces must be ``"/"``-free — a member namespace carrying the
separator could alias another cluster's prefixed path and is rejected
loudly rather than silently merged.

Routing: each cluster digest (:meth:`ClusterSet.cluster_digest`) is a
stable hash of the member's topology — the rendezvous routing key the
fleetmesh control plane assigns ingest ownership by — and
:meth:`ClusterSet.graph_digest` covers the merged topology (order-
independent over members).
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Mapping, Optional, Tuple

#: the synthetic label pair that keeps merged-view selector matching
#: cluster-local: injected into every pod's labels AND every service's
#: selector, so a c0 service can never adopt a c1 pod with the same app
#: label
CLUSTER_LABEL = "rca.tpu/cluster"

SEP = "/"

#: member-client list getters forwarded per namespace (first positional
#: arg is the namespace on every one of them)
_NS_LIST_GETTERS = (
    "get_pods", "get_services", "get_deployments", "get_statefulsets",
    "get_daemonsets", "get_cronjobs", "get_endpoints", "get_ingresses",
    "get_network_policies", "get_configmaps", "get_secrets", "get_pvcs",
    "get_resource_quotas", "get_hpas", "get_events",
    "get_recently_terminated_pods",
)

#: which stores carry a flat ``spec.selector`` that must grow the
#: cluster pair in the merged view
_SELECTOR_GETTERS = ("get_services",)


def _name_of(obj: dict) -> str:
    return (obj.get("metadata") or {}).get("name", "")


def _check_id(cid: str) -> str:
    if not cid or not isinstance(cid, str):
        raise ValueError(f"cluster id must be a non-empty string: {cid!r}")
    if SEP in cid or cid != cid.strip():
        raise ValueError(
            f"cluster id {cid!r} may not contain {SEP!r} or edge "
            "whitespace — it prefixes merged namespaces and names"
        )
    return cid


def _check_ns(cid: str, ns: str) -> str:
    if SEP in ns:
        raise ValueError(
            f"cluster {cid!r} namespace {ns!r} contains {SEP!r}: it "
            "would alias another cluster's prefixed path in the merged "
            "world — rejected, not merged"
        )
    return ns


class ClusterSet:
    """N member clients, one merged world.  See module docstring."""

    def __init__(self, members: Mapping[str, Any]):
        if not members:
            raise ValueError("ClusterSet needs at least one member")
        seen = set()
        for cid in members:
            _check_id(cid)
            if cid in seen:
                raise ValueError(f"duplicate cluster id {cid!r}")
            seen.add(cid)
        #: sorted by id so every merged surface (namespaces, digests,
        #: concatenated object lists) is member-insertion-order-free
        self.members: Dict[str, Any] = {
            cid: members[cid] for cid in sorted(members)
        }

    @property
    def ids(self) -> List[str]:
        return list(self.members)

    def member(self, cid: str) -> Any:
        return self.members[cid]

    # -- namespaces ----------------------------------------------------------
    def namespaces(self) -> List[str]:
        """Every member namespace, cluster-prefixed, collision-checked."""
        out = []
        for cid, m in self.members.items():
            for ns in m.get_namespaces():
                out.append(f"{cid}{SEP}{_check_ns(cid, ns)}")
        return sorted(out)

    def split(self, merged_ns: str) -> Tuple[str, str]:
        """``"<cluster>/<ns>"`` -> (cluster id, member namespace)."""
        cid, sep, ns = merged_ns.partition(SEP)
        if not sep or cid not in self.members or not ns:
            raise KeyError(
                f"{merged_ns!r} is not a <cluster>{SEP}<namespace> of "
                f"this set (clusters: {', '.join(self.members)})"
            )
        return cid, ns

    def bound(self, merged_ns: str) -> "BoundClusterClient":
        """A full ClusterClient for ONE merged namespace's cluster:
        namespace args arrive cluster-prefixed and route stripped; the
        namespace-free surface (``get_nodes`` et al) hits the same
        member — capture through this proxy is single-cluster-consistent
        by construction."""
        cid, _ns = self.split(merged_ns)
        return BoundClusterClient(self.members[cid], cid)

    # -- digests (rendezvous routing + stability tests) ----------------------
    def cluster_digest(self, cid: str) -> str:
        """Stable topology digest for one member: the ingest-ownership
        rendezvous key.  Covers namespaces, service names, and
        dependency edges — all sorted, so world construction order and
        dict insertion order cannot move ownership."""
        from rca_tpu.engine.streaming import topology_digest

        m = self.members[cid]
        parts = []
        for ns in sorted(m.get_namespaces()):
            svcs = sorted(_name_of(s) for s in m.get_services(ns) or [])
            deps = m.get_service_dependencies(ns) or {}
            edges = sorted(
                (src, dst)
                for src, dsts in deps.items()
                for dst in (dsts or [])
            )
            parts.append((ns, tuple(svcs), tuple(edges)))
        return topology_digest(cid, parts)

    def graph_digest(self) -> str:
        """One digest over the MERGED topology: the fleet's identity for
        routing and replay labelling, order-independent over members."""
        from rca_tpu.engine.streaming import topology_digest

        return topology_digest(
            "clusterset",
            [(cid, self.cluster_digest(cid)) for cid in self.members],
        )

    def merged_client(self) -> "MergedClusterClient":
        return MergedClusterClient(self)


class BoundClusterClient:
    """One member, addressed by merged (cluster-prefixed) namespaces.
    Unknown attributes forward to the member verbatim (``get_nodes``,
    ``get_node_metrics``, ``is_connected``, ...)."""

    def __init__(self, member: Any, cid: str):
        self._member = member
        self._cid = cid

    def _strip(self, ns: str) -> str:
        prefix = f"{self._cid}{SEP}"
        return ns[len(prefix):] if ns.startswith(prefix) else ns

    def __getattr__(self, name: str) -> Any:
        inner = getattr(self._member, name)
        if name in _NS_FORWARDED and callable(inner):
            def stripped(ns, *args, **kwargs):
                return inner(self._strip(ns), *args, **kwargs)

            return stripped
        return inner


#: every member method whose FIRST positional argument is a namespace
_NS_FORWARDED = frozenset(_NS_LIST_GETTERS) | {
    "get_pod", "get_pod_logs", "get_pod_metrics", "get_trace_ids",
    "get_service_latency_stats", "get_error_rate_by_service",
    "get_service_dependencies", "find_slow_operations",
    "watch_changes", "watch_close", "get_columnar",
}


class MergedClusterClient:
    """The union view: one namespace merging every member's namespace of
    that name, names ``"<cluster>/"``-prefixed, selector matching and
    dependency edges cluster-local.  ``get_columnar`` runs the live
    columnar adapter over this client itself — merged capture pays
    column-diff costs, not per-object re-scans."""

    def __init__(self, cluster_set: ClusterSet):
        self.set = cluster_set
        self._token_seq = itertools.count(1)
        #: merged watch token -> {cluster id -> member cursor}
        self._tokens: Dict[str, Dict[str, str]] = {}
        #: merged namespace -> LiveColumnarFeed over self
        self._feeds: Dict[str, Any] = {}

    # -- identity ------------------------------------------------------------
    def is_connected(self) -> bool:
        return all(m.is_connected() for m in self.set.members.values())

    def get_current_time(self) -> str:
        first = next(iter(self.set.members.values()))
        return first.get_current_time()

    def get_cluster_info(self) -> Dict[str, Any]:
        return {
            "clusters": {
                cid: m.get_cluster_info()
                for cid, m in self.set.members.items()
            },
            "merged": True,
            "graph_digest": self.set.graph_digest(),
        }

    def collect_errors(self, clear: bool = True) -> List[Dict[str, str]]:
        out: List[Dict[str, str]] = []
        for cid, m in self.set.members.items():
            for e in m.collect_errors(clear) or []:
                out.append({**e, "cluster": cid})
        return out

    def get_namespaces(self) -> List[str]:
        """The union namespace names (each merges every member that has
        it); the per-cluster prefixed list lives on the ClusterSet."""
        names = set()
        for cid, m in self.set.members.items():
            for ns in m.get_namespaces():
                names.add(_check_ns(cid, ns))
        return sorted(names)

    # -- prefixing -----------------------------------------------------------
    def _prefixed_obj(self, obj: dict, cid: str,
                      with_selector: bool = False) -> dict:
        """Copy-on-write cluster prefixing: name, node binding, and the
        cluster label pair (selector too, for services).  Member objects
        are never mutated — only the touched sub-dicts are copied."""
        md = dict(obj.get("metadata") or {})
        md["name"] = f"{cid}{SEP}{md.get('name', '')}"
        labels = dict(md.get("labels") or {})
        labels[CLUSTER_LABEL] = cid
        md["labels"] = labels
        out = dict(obj)
        out["metadata"] = md
        spec = obj.get("spec")
        if isinstance(spec, dict):
            spec2 = dict(spec)
            if spec.get("nodeName"):
                spec2["nodeName"] = f"{cid}{SEP}{spec['nodeName']}"
            if with_selector and isinstance(spec.get("selector"), dict):
                sel = dict(spec["selector"])
                sel[CLUSTER_LABEL] = cid
                spec2["selector"] = sel
            out["spec"] = spec2
        io = obj.get("involvedObject")
        if isinstance(io, dict) and io.get("name"):
            out["involvedObject"] = {
                **io, "name": f"{cid}{SEP}{io['name']}",
            }
        return out

    def _merge_lists(self, getter: str, ns: str) -> List[dict]:
        with_sel = getter in _SELECTOR_GETTERS
        out: List[dict] = []
        for cid, m in self.set.members.items():
            for obj in getattr(m, getter)(ns) or []:
                out.append(self._prefixed_obj(obj, cid, with_sel))
        return out

    # -- routed single-object access ----------------------------------------
    def _route_name(self, name: str) -> Tuple[str, Any, str]:
        cid, sep, rest = name.partition(SEP)
        if not sep or cid not in self.set.members:
            raise KeyError(f"{name!r} carries no known cluster prefix")
        return cid, self.set.members[cid], rest

    def get_pod(self, namespace: str, name: str) -> Optional[dict]:
        try:
            cid, m, rest = self._route_name(name)
        except KeyError:
            return None
        obj = m.get_pod(namespace, rest)
        return None if obj is None else self._prefixed_obj(obj, cid)

    def get_pod_logs(self, namespace: str, pod_name: str,
                     container: Optional[str] = None,
                     previous: bool = False,
                     tail_lines: Optional[int] = None) -> str:
        try:
            _cid, m, rest = self._route_name(pod_name)
        except KeyError:
            return ""
        return m.get_pod_logs(
            namespace, rest, container=container, previous=previous,
            tail_lines=tail_lines,
        )

    # -- cluster-scoped ------------------------------------------------------
    def get_nodes(self) -> List[dict]:
        out: List[dict] = []
        for cid, m in self.set.members.items():
            for node in m.get_nodes() or []:
                out.append(self._prefixed_obj(node, cid))
        return out

    def get_node_metrics(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for cid, m in self.set.members.items():
            for name, rec in (m.get_node_metrics() or {}).items():
                out[f"{cid}{SEP}{name}"] = rec
        return out

    def get_pod_metrics(self, namespace: str) -> Dict[str, Any]:
        pods: Dict[str, Any] = {}
        for cid, m in self.set.members.items():
            recs = (m.get_pod_metrics(namespace) or {}).get("pods", {}) or {}
            for name, rec in recs.items():
                pods[f"{cid}{SEP}{name}"] = rec
        return {"pods": pods}

    # -- traces (edges stay cluster-local by prefixing within a member) ------
    def get_service_latency_stats(self, namespace: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for cid, m in self.set.members.items():
            for svc, v in (
                m.get_service_latency_stats(namespace) or {}
            ).items():
                out[f"{cid}{SEP}{svc}"] = v
        return out

    def get_error_rate_by_service(self, namespace: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for cid, m in self.set.members.items():
            for svc, v in (
                m.get_error_rate_by_service(namespace) or {}
            ).items():
                out[f"{cid}{SEP}{svc}"] = v
        return out

    def get_service_dependencies(self, namespace: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for cid, m in self.set.members.items():
            deps = m.get_service_dependencies(namespace) or {}
            for src, dsts in deps.items():
                out[f"{cid}{SEP}{src}"] = [
                    f"{cid}{SEP}{d}" for d in (dsts or [])
                ]
        return out

    def find_slow_operations(self, namespace: str,
                             threshold_ms: float = 500.0) -> List[dict]:
        out: List[dict] = []
        for cid, m in self.set.members.items():
            for op in m.find_slow_operations(namespace, threshold_ms) or []:
                op2 = dict(op)
                if op2.get("service"):
                    op2["service"] = f"{cid}{SEP}{op2['service']}"
                out.append(op2)
        return out

    def get_trace_ids(self, namespace: str, limit: int = 20) -> List[str]:
        out: List[str] = []
        for cid, m in self.set.members.items():
            out.extend(
                f"{cid}{SEP}{t}"
                for t in m.get_trace_ids(namespace, limit) or []
            )
        return out[:limit]

    # -- watch (fan-out; one merged token covers every member) ---------------
    def watch_changes(self, namespace: str,
                      cursor: Optional[str]) -> Dict[str, Any]:
        if cursor is None:
            per: Dict[str, str] = {}
            for cid, m in self.set.members.items():
                r = m.watch_changes(namespace, None)
                if not r.get("supported"):
                    for done_cid, tok in per.items():
                        self._member_close(done_cid, namespace, tok)
                    return {"supported": False, "cursor": None,
                            "expired": False, "changes": []}
                per[cid] = r.get("cursor")
            token = f"mc{next(self._token_seq)}"
            self._tokens[token] = per
            return {"supported": True, "cursor": token,
                    "expired": False, "changes": []}
        per = self._tokens.get(cursor)
        if per is None:
            return {"supported": True, "cursor": cursor,
                    "expired": True, "changes": []}
        changes: List[Dict[str, str]] = []
        for cid, m in self.set.members.items():
            r = m.watch_changes(namespace, per.get(cid))
            if not r.get("supported") or r.get("expired"):
                # ONE member expiring expires the merged feed: partial
                # resync would leave that cluster's slice silently stale
                self.watch_close(namespace, cursor)
                return {"supported": True, "cursor": cursor,
                        "expired": True, "changes": []}
            # member cursors advance per drain (journal-seq feeds mint a
            # new one each time); holding the original would replay every
            # change since registration on every sweep
            per[cid] = r.get("cursor", per.get(cid))
            for c in r.get("changes") or []:
                c2 = dict(c)
                if c2.get("name"):
                    c2["name"] = f"{cid}{SEP}{c2['name']}"
                changes.append(c2)
        return {"supported": True, "cursor": cursor,
                "expired": False, "changes": changes}

    def _member_close(self, cid: str, namespace: str, tok: Any) -> None:
        # journal-seq feeds (mock worlds) are stateless and have no close
        close = getattr(self.set.members[cid], "watch_close", None)
        if callable(close):
            close(namespace, tok)

    def watch_close(self, namespace: str, cursor: Optional[str]) -> None:
        per = self._tokens.pop(cursor, None) if cursor else None
        if per:
            for cid, tok in per.items():
                self._member_close(cid, namespace, tok)

    # -- columnar (the live adapter over the merged view) --------------------
    def get_columnar(self, namespace: str,
                     cursor: Optional[str] = None) -> Dict[str, Any]:
        from rca_tpu.cluster.live_columnar import LiveColumnarFeed

        feed = self._feeds.get(namespace)
        if feed is None:
            feed = self._feeds[namespace] = LiveColumnarFeed(
                self, namespace
            )
        return feed.payload(cursor)

    def close(self) -> None:
        for feed in self._feeds.values():
            feed.close()
        self._feeds.clear()


# forwarded plain list getters: merged union with prefixing
def _make_merged_getter(getter: str):
    def merged(self: MergedClusterClient, namespace: str, *args, **kwargs):
        return self._merge_lists(getter, namespace)

    merged.__name__ = getter
    merged.__doc__ = (
        f"Merged union of every member's ``{getter}`` for this "
        "namespace, cluster-prefixed."
    )
    return merged


for _g in _NS_LIST_GETTERS:
    setattr(MergedClusterClient, _g, _make_merged_getter(_g))
del _g
