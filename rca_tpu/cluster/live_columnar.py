"""Live columnar ingestion: ``get_columnar`` for watch-backed clients.

Until ISSUE 17 the columnar capture plane (ISSUE 10) stopped at the lab
door — only the mock client served ``get_columnar``, because only the
mock had a mutation journal for the master to consume.  This module
closes that gap without forking the encode path: a
:class:`LiveColumnarFeed` maintains a **shadow**
:class:`~rca_tpu.cluster.world.World` for one namespace of any
watch-capable client (the real :class:`~rca_tpu.cluster.k8s_client.
K8sApiClient`, or the multi-cluster merged client in
``cluster/clusterset.py``), journals every observed change into it, and
runs the SAME :class:`~rca_tpu.cluster.columnar.ColumnarWorld` master on
top.  Every pod row is encoded by the shared
:func:`~rca_tpu.cluster.columnar._extract_columnar` — live-vs-dict
bit-parity is therefore structural, not a reimplementation promise, and
the property gates in tests/test_planetcap.py drive it through
``extract_features`` exactly like the mock's.

Sync model (one ``payload()`` call = one sweep):

- the watch feed (``client.watch_changes``, the PR 6 pump surface whose
  entries carry per-event resourceVersions) names what changed; changed
  pods are re-fetched individually (object + tail-200 logs), changed
  topology kinds re-list their store and diff by ``resourceVersion``;
- a watch **expiry** (410 Gone, pump death, journal overrun) re-opens
  the feed FIRST and then reconciles every store against a fresh list —
  re-list-after-reopen means nothing that changes during the recovery
  can fall between feed positions (no silent gap);
- pod metrics re-fetch and diff every sweep (metrics have no watch),
  topology re-lists every ``RCA_INGEST_TOPO_EVERY``-th sweep even
  without watch entries (real pumps only stream pods + events).

Shadow-journal note: :meth:`World.touch` deliberately rewrites the
touched object's ``resourceVersion`` (mock worlds need write stamps);
the shadow must NOT — its objects carry the API server's versions
verbatim, and snapshot parity compares them — so the feed appends
journal entries itself (:meth:`LiveColumnarFeed._journal`).

Cursor note: mirrors parse cursors with ``int()``, and a feed torn down
by a reconnect restarts its shadow journal at zero — so every feed
instance offsets its cursors by a process-monotonic generation base.  A
cursor minted by a dead feed lands below the new feed's base, reads as
out-of-range, and is answered with a full dump instead of silently
aliasing onto unrelated diff ops.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Set

from rca_tpu.cluster.columnar import (
    KIND_STORES,
    LOG_TAIL_LINES,
    ColumnarWorld,
    _extract_columnar,  # noqa: F401  (re-exported: THE shared encoder)
)
from rca_tpu.cluster.world import World

#: store name -> the ClusterClient list getter that serves it
STORE_GETTERS: Dict[str, str] = {
    "pods": "get_pods", "services": "get_services",
    "deployments": "get_deployments", "statefulsets": "get_statefulsets",
    "daemonsets": "get_daemonsets", "cronjobs": "get_cronjobs",
    "endpoints": "get_endpoints", "ingresses": "get_ingresses",
    "network_policies": "get_network_policies",
    "configmaps": "get_configmaps", "secrets": "get_secrets",
    "pvcs": "get_pvcs", "resource_quotas": "get_resource_quotas",
    "hpas": "get_hpas",
}

#: generation bases are (counter << _GEN_SHIFT): shadow journal seqs stay
#: far below 2**40, so bases from distinct feed instances can never
#: overlap each other's cursor ranges
_GEN_SHIFT = 40
_GEN = itertools.count(1)


def _name_of(obj: dict) -> str:
    return (obj.get("metadata") or {}).get("name", "")


def _rv_of(obj: dict) -> Optional[str]:
    return (obj.get("metadata") or {}).get("resourceVersion")


class LiveColumnarFeed:
    """One namespace's columnar master over a live (watch-capable)
    client — see module docstring.  ``payload(cursor)`` is the whole
    surface; it returns exactly what the mock's ``get_columnar`` does."""

    def __init__(self, client: Any, namespace: str,
                 topo_every: Optional[int] = None,
                 fetch_logs: Optional[bool] = None):
        from rca_tpu.config import ingest_log_fetch, ingest_topo_every

        self.client = client
        self.namespace = namespace
        self.topo_every = int(
            ingest_topo_every() if topo_every is None else topo_every
        )
        self.fetch_logs = bool(
            ingest_log_fetch() if fetch_logs is None else fetch_logs
        )
        self.world = World(cluster_name=f"live-shadow:{namespace}")
        self.master = ColumnarWorld.master(self.world, namespace)
        self._gen_base = next(_GEN) << _GEN_SHIFT
        self._token: Optional[str] = None
        self._syncs = 0
        self._order_dirty = False
        #: observability: full re-list reconciles (1 bootstrap + expiries)
        self.resyncs = 0
        #: observability: forced master rebuilds after object inserts
        self.order_rebuilds = 0

    # -- the get_columnar surface -------------------------------------------
    def payload(self, cursor: Optional[str] = None) -> Dict[str, Any]:
        """[no-dict-scan] One sweep: drain the watch feed (per-MUTATION
        work lives in ``_sync``), then assemble the coldiff payload as
        the master's column ops — no per-pod Python may run here."""
        if not self._sync():
            return {"supported": False, "reason": "no live watch feed"}
        p = self.master.payload(self._internal_cursor(cursor))
        if p.get("supported") and p.get("cursor") is not None:
            p["cursor"] = str(int(p["cursor"]) + self._gen_base)
        return p

    def close(self) -> None:
        if self._token is not None and hasattr(self.client, "watch_close"):
            self.client.watch_close(self.namespace, self._token)
            self._token = None

    def _internal_cursor(self, cursor: Optional[str]) -> Optional[str]:
        if cursor is None:
            return None
        try:
            c = int(cursor) - self._gen_base
        except (TypeError, ValueError):
            return None
        # a cursor from another generation (an older feed instance) is
        # out of range by construction -> master serves a full dump
        return str(c) if c >= 0 else None

    # -- sync: watch feed -> shadow world -----------------------------------
    def _sync(self) -> bool:
        self._syncs += 1
        if self._token is None:
            res = self.client.watch_changes(self.namespace, None)
            if not res.get("supported"):
                return False
            self._token = res.get("cursor")
            self._reconcile_all()
            return True
        res = self.client.watch_changes(self.namespace, self._token)
        if not res.get("supported"):
            self._token = None
            return False
        # advance: journal-seq feeds (mock, merged) mint a NEW cursor per
        # drain; pump feeds echo the token back — either way the result's
        # cursor is the position of everything this drain delivered
        self._token = res.get("cursor", self._token)
        if res.get("expired"):
            # 410-expiry recovery: reopen the feed FIRST, then re-list —
            # anything that changes mid-recovery lands in the new feed
            res = self.client.watch_changes(self.namespace, None)
            if not res.get("supported"):
                self._token = None
                return False
            self._token = res.get("cursor")
            self._reconcile_all()
            return True
        self._apply_changes(res.get("changes") or [])
        if self.topo_every > 0 and self._syncs % self.topo_every == 0:
            for store in KIND_STORES:
                if store != "pods":
                    self._reconcile_store(store)
            self._reconcile_nodes()
        self._reconcile_metrics()
        if self._order_dirty:
            # an INSERT landed this sweep: incremental master rows
            # append at the tail, but the client's list getter places
            # new objects at their canonical position (segment order on
            # the merged client, name order on a real API server).  The
            # stores were re-listed into client order above; force the
            # master to rebuild from the shadow so row order matches the
            # dict path bit-for-bit.  Updates/deletes stay incremental.
            self._force_rebuild()
        return True

    def _apply_changes(self, changes: List[Dict[str, str]]) -> None:
        pods_changed: Set[str] = set()
        logs_changed: Set[str] = set()
        topo: Set[str] = set()
        events_dirty = nodes_dirty = False
        for c in changes:
            kind = c.get("kind", "")
            if kind == "pod":
                pods_changed.add(c.get("name", ""))
            elif kind == "logs":
                logs_changed.add(c.get("name", ""))
            elif kind == "event":
                events_dirty = True
            elif kind == "node":
                nodes_dirty = True
            elif kind in ("pod_metrics", "traces"):
                continue  # metrics diff every sweep; traces ride snapshots
            else:
                store = World._KIND_PLURAL.get(kind, "")
                if store in STORE_GETTERS and store != "pods":
                    topo.add(store)
        shadow_pods = {
            _name_of(o) for o in self.world.pods.get(self.namespace, [])
        }
        if pods_changed - shadow_pods:
            # at least one changed pod is NEW to the shadow: re-list the
            # whole store so it lands at its canonical list position
            # (and flags the order-dirty rebuild below).  The re-list
            # rv-diffs EVERY pod, so the per-name syncs are covered.
            self._reconcile_store("pods")
            pods_changed.clear()
        for name in sorted(pods_changed):
            self._sync_pod(name)
        for name in sorted(logs_changed - pods_changed):
            self._sync_logs(name)
        for store in sorted(topo):
            self._reconcile_store(store)
        if events_dirty:
            self._reconcile_events()
        if nodes_dirty:
            self._reconcile_nodes()

    # -- per-object sync -----------------------------------------------------
    def _journal(self, kind: str, name: str) -> None:
        """World.touch minus the resourceVersion rewrite: shadow objects
        keep the API server's versions verbatim (parity compares them)."""
        w = self.world
        w.journal_seq += 1
        w.journal.append({
            "seq": w.journal_seq, "kind": kind,
            "namespace": self.namespace, "name": name,
        })
        if len(w.journal) > w.journal_cap:
            drop = len(w.journal) - w.journal_cap
            del w.journal[:drop]
            w.journal_floor = w.journal[0]["seq"]

    def _fetch_logs(self, obj: dict, name: str) -> Dict[str, str]:
        if not self.fetch_logs:
            return {}
        out: Dict[str, str] = {}
        for c in (obj.get("spec", {}) or {}).get("containers", []) or []:
            cname = c.get("name", "")
            try:
                out[cname] = self.client.get_pod_logs(
                    self.namespace, name, container=cname,
                    tail_lines=LOG_TAIL_LINES,
                ) or ""
            except Exception:
                out[cname] = ""
        return out

    def _sync_pod(self, name: str) -> None:
        w, ns = self.world, self.namespace
        obj = self.client.get_pod(ns, name)
        lst = w.pods.setdefault(ns, [])
        if not isinstance(obj, dict) or not obj:
            for i, o in enumerate(lst):
                if _name_of(o) == name:
                    del lst[i]
                    w.logs.get(ns, {}).pop(name, None)
                    self._journal("pod", name)
                    return
            return
        for i, o in enumerate(lst):
            if _name_of(o) == name:
                lst[i] = obj
                break
        else:
            lst.append(obj)
        w.logs.setdefault(ns, {})[name] = self._fetch_logs(obj, name)
        self._journal("pod", name)

    def _sync_logs(self, name: str) -> None:
        w, ns = self.world, self.namespace
        pod = None
        for o in w.pods.get(ns, []):
            if _name_of(o) == name:
                pod = o
                break
        if pod is None:
            return
        w.logs.setdefault(ns, {})[name] = self._fetch_logs(pod, name)
        self._journal("logs", name)

    # -- store-level reconcile ----------------------------------------------
    def _reconcile_store(self, store: str,
                         fetched: Optional[List[dict]] = None) -> None:
        """List one store and diff against the shadow by resourceVersion
        (deep equality for rv-less objects): upserts and deletes journal,
        unchanged rows cost nothing downstream (the master's rv-skip)."""
        w, ns = self.world, self.namespace
        if fetched is None:
            fetched = getattr(self.client, STORE_GETTERS[store])(ns) or []
        kind = World._KIND_SINGULAR.get(store, store)
        cur = getattr(w, store).setdefault(ns, [])
        want = {_name_of(o): o for o in fetched}
        for o in [o for o in cur if _name_of(o) not in want]:
            name = _name_of(o)
            cur.remove(o)
            if store == "pods":
                w.logs.get(ns, {}).pop(name, None)
            self._journal(kind, name)
        pos = {_name_of(o): i for i, o in enumerate(cur)}
        inserted = False
        for name, obj in want.items():
            i = pos.get(name)
            if i is not None:
                rv_new, rv_old = _rv_of(obj), _rv_of(cur[i])
                if (rv_new is not None and rv_new == rv_old) \
                        or cur[i] == obj:
                    continue
                cur[i] = obj
            else:
                cur.append(obj)
                inserted = True
            if store == "pods":
                w.logs.setdefault(ns, {})[name] = \
                    self._fetch_logs(obj, name)
            self._journal(kind, name)
        if inserted:
            # restore the client's canonical list order (new objects
            # were appended at the tail above); master row order is
            # fixed up by the caller's forced rebuild
            by_name = {_name_of(o): o for o in cur}
            cur[:] = [by_name[n] for n in want if n in by_name]
            # in-place reorder keeps the list's id() and len() — the
            # world's position index would go stale-on-MISS (find()
            # only self-heals on hit mismatch), and a stale miss reads
            # as a deletion to the columnar master
            w._pos_index.pop((store, ns), None)
            self._order_dirty = True

    def _reconcile_events(self) -> None:
        w, ns = self.world, self.namespace
        evs = self.client.get_events(ns) or []
        if evs != w.events.get(ns, []):
            w.events[ns] = list(evs)
            self._journal("event", "")

    def _reconcile_nodes(self) -> None:
        nodes = self.client.get_nodes() or []
        if nodes != self.world.nodes:
            self.world.nodes = list(nodes)
            self._journal("node", "")

    def _reconcile_metrics(self) -> None:
        w, ns = self.world, self.namespace
        mets = self.client.get_pod_metrics(ns) or {}
        new_pods = dict(mets.get("pods", {}) or {})
        old_pods = (w.pod_metrics.get(ns) or {}).get("pods", {}) or {}
        changed = [
            n for n, rec in new_pods.items() if old_pods.get(n) != rec
        ] + [n for n in old_pods if n not in new_pods]
        w.pod_metrics[ns] = {**mets, "pods": new_pods}
        for name in sorted(changed):
            self._journal("pod_metrics", name)

    def _force_rebuild(self) -> None:
        """Expire every master/mirror cursor at or below the current
        journal seq: the next ``payload()`` rebuilds columns from the
        shadow world (in client list order) and serves mirrors a full
        dump.  Used when list ORDER changed (inserts), which incremental
        ops cannot express."""
        w = self.world
        w.journal.clear()
        w.journal_floor = w.journal_seq + 2
        w.journal_seq += 1
        self._order_dirty = False
        self.order_rebuilds += 1

    def _reconcile_all(self) -> None:
        """Full re-list of every store — bootstrap and expiry recovery.
        Rebuilds ride the forced-expiry path: the master re-derives the
        columns from the shadow world instead of chewing an op flood,
        and every outstanding mirror cursor gets a full dump."""
        self.resyncs += 1
        self._reconcile_store("pods")
        for store in KIND_STORES:
            if store != "pods":
                self._reconcile_store(store)
        self._reconcile_events()
        self._reconcile_nodes()
        self._reconcile_metrics()
        self._force_rebuild()
