"""The 200-pod OOMKill-chain test configuration (BASELINE.md row 3).

SURVEY.md §4 prescribes that the kind test environment grows a "200-pod
OOMKill-chain config": one root service whose memory fault — the
reference's fill-a-memory-backed-emptyDir trick
(reference: setup_test_cluster.py:303-310), pushed past the 128Mi limit so
the kernel actually OOM-kills it — cascades through ~200 pods arranged in
a dependency tree.  This module is the single source of truth for that
configuration:

- :func:`oom_chain_topology` — the service tree + replica plan, shared by
  the kind manifest generator (``tools/setup_test_cluster.py --profile
  oom-chain-200``) and the hermetic mock twin, so the live cluster and the
  mock world cannot drift apart;
- :func:`oom_chain_world` — the hermetic :class:`World`: root pods
  OOMKilled + CrashLoopBackOff, victim pods Running but logging
  connection-refused probes at their parent, ground truth naming the root;
- :func:`measure_analyze` — the row-3 measurement hook: end-to-end
  analyze latency + hit@1 against any ``ClusterClient`` (live kind or
  mock), the JSON the driver records as ``KIND_r*.json``.
"""

from __future__ import annotations

import time
from typing import Dict, List, Tuple

OOM_NS = "oom-chain"
OOM_ROOT = "cache"
ROOT_REPLICAS = 2


def oom_chain_topology(
    n_pods: int = 200, replicas_per_service: int = 3
) -> Tuple[List[str], Dict[str, str], Dict[str, int]]:
    """(services, parent-of, replicas-of) totalling ~``n_pods`` pods.

    The victims form a binary tree rooted at :data:`OOM_ROOT` (depth
    ~log2(n_victims) ≈ 6 at 200 pods — within the engine's 8 propagation
    steps), each depending on its parent via an env-var service URL the
    topology builder turns into a dependency edge."""
    n_victims = max(1, (n_pods - ROOT_REPLICAS) // replicas_per_service)
    services = [OOM_ROOT] + [f"svc-{i:03d}" for i in range(n_victims)]
    parent: Dict[str, str] = {}
    for i in range(n_victims):
        parent[f"svc-{i:03d}"] = (
            OOM_ROOT if i == 0 else f"svc-{(i - 1) // 2:03d}"
        )
    replicas = {OOM_ROOT: ROOT_REPLICAS}
    for i in range(n_victims):
        replicas[f"svc-{i:03d}"] = replicas_per_service
    return services, parent, replicas


def oom_chain_world(n_pods: int = 200):
    """Hermetic twin of the ``oom-chain-200`` kind profile.

    Root pods: container OOMKilled (exit 137) and waiting in
    CrashLoopBackOff, memory metric pinned at its limit, kubelet OOMKilling
    events.  Victim pods: Running and ready, but their logs carry
    connection-refused probe failures against the parent service — soft
    symptoms the engine must explain away up the tree to the one true
    root."""
    from rca_tpu.cluster.world import (
        World,
        container_spec,
        make_deployment,
        make_endpoints,
        make_event,
        make_node,
        make_pod,
        make_service,
        pod_metric,
        waiting_status,
    )

    services, parent, replicas = oom_chain_topology(n_pods)
    w = World(cluster_name="rca-oom-chain")
    w.nodes = [make_node(f"node-{i}") for i in range(4)]
    w.node_metrics = {
        n["metadata"]["name"]: {
            "cpu": {"usage_percentage": 55},
            "memory": {"usage_percentage": 60},
        }
        for n in w.nodes
    }
    w.pod_metrics[OOM_NS] = {"pods": {}}
    w.logs[OOM_NS] = {}
    w.events[OOM_NS] = []

    def pod_name(svc: str, i: int) -> str:
        return f"{svc}-{i}"

    for svc in services:
        for i in range(replicas[svc]):
            name = pod_name(svc, i)
            if svc == OOM_ROOT:
                pod = make_pod(
                    name, OOM_NS, svc,
                    containers=[
                        container_spec(
                            svc,
                            requests={"cpu": "50m", "memory": "64Mi"},
                            limits={"cpu": "100m", "memory": "128Mi"},
                            volume_mounts=[{"name": "scratch",
                                            "mountPath": "/scratch"}],
                        )
                    ],
                    container_statuses=[
                        waiting_status(
                            svc, "CrashLoopBackOff",
                            "Back-off restarting failed container",
                            restarts=7, last_exit_code=137,
                            last_reason="OOMKilled",
                        )
                    ],
                    volumes=[{"name": "scratch",
                              "emptyDir": {"medium": "Memory"}}],
                )
                w.pod_metrics[OOM_NS]["pods"][name] = pod_metric(
                    20, 127, 100, 128, svc
                )
                w.logs[OOM_NS][name] = {svc: (
                    "INFO: cache warming...\n"
                    "INFO: loading 150MiB working set\n"
                )}
                w.events[OOM_NS].append(make_event(
                    OOM_NS, "Pod", name, "OOMKilling",
                    f"Memory cgroup out of memory: Killed process "
                    f"({svc})", count=7,
                ))
                w.events[OOM_NS].append(make_event(
                    OOM_NS, "Pod", name, "BackOff",
                    "Back-off restarting failed container", count=7,
                ))
            else:
                up = parent[svc]
                pod = make_pod(
                    name, OOM_NS, svc,
                    containers=[
                        container_spec(
                            svc,
                            requests={"cpu": "25m", "memory": "32Mi"},
                            limits={"cpu": "100m", "memory": "64Mi"},
                            env=[{
                                "name": "PARENT_URL",
                                "value": f"http://{up}.{OOM_NS}"
                                         ".svc.cluster.local:80",
                            }],
                        )
                    ],
                )
                w.pod_metrics[OOM_NS]["pods"][name] = pod_metric(
                    10, 20, 100, 64, svc
                )
                w.logs[OOM_NS][name] = {svc: (
                    f"INFO: probing {up}\n"
                    f"ERROR: connection refused to {up}:80 "
                    "(ECONNREFUSED)\n"
                    "ERROR: upstream request failed\n"
                ) * 2}
            w.add("pods", OOM_NS, pod)

    for svc in services:
        broken = svc == OOM_ROOT
        w.add("deployments", OOM_NS, make_deployment(
            svc, OOM_NS, svc, replicas[svc],
            0 if broken else replicas[svc],
        ))
        w.add("services", OOM_NS, make_service(svc, OOM_NS))
        healthy = (
            [] if broken
            else [pod_name(svc, i) for i in range(replicas[svc])]
        )
        w.add("endpoints", OOM_NS, make_endpoints(svc, OOM_NS, healthy))

    w.traces = {
        "dependencies": {OOM_NS: {
            svc: [parent[svc]] for svc in services if svc in parent
        }},
    }
    w.ground_truth = {
        "namespace": OOM_NS,
        "fault_roots": [OOM_ROOT],
        "faults": {OOM_ROOT: "OOMKilled restart loop (exit 137; "
                             "memory-backed volume exceeds 128Mi limit)"},
        "n_pods": sum(replicas.values()),
    }
    return w


def measure_analyze(
    client, namespace: str, expected_root: str, backend: str = "jax",
) -> Dict[str, object]:
    """BASELINE.md row-3 measurement: TWO end-to-end comprehensive
    analyses (snapshot capture → agents → engine correlation) through the
    public coordinator API, wall-clock timed — the first run as this
    process finds things (jit compiles included if the cache is cold),
    the second with warm executables — plus hit@1/hit@3 against the
    expected root.  Both numbers are recorded so the artifact says what
    was measured instead of claiming a single ambiguous latency.  Works
    against the live kind cluster and the hermetic mock twin alike; the
    caller records the dict (``KIND_r*.json``)."""
    from rca_tpu.coordinator import RCACoordinator

    coord = RCACoordinator(client, backend=backend)
    t0 = time.perf_counter()
    coord.run_analysis("comprehensive", namespace)
    first_ms = (time.perf_counter() - t0) * 1e3
    t1 = time.perf_counter()
    record = coord.run_analysis("comprehensive", namespace)
    warm_ms = (time.perf_counter() - t1) * 1e3
    corr = record.get("results", {}).get("correlated", {})
    ranked = [r["component"] for r in corr.get("root_causes", [])]
    from rca_tpu.cluster.mock_client import MockClusterClient

    return {
        "metric": "oom_chain_200_analyze",
        # honest provenance: a mock-twin measurement (any subclass or the
        # class itself) must never read as a live-cluster number
        "environment": (
            "hermetic-mock" if isinstance(client, MockClusterClient)
            else "live-kind"
        ),
        "namespace": namespace,
        "status": record.get("status"),
        "backend": corr.get("backend"),
        "engine": corr.get("engine", "single"),
        "fallback_reason": corr.get("fallback_reason"),
        "latency_first_run_ms": round(first_ms, 1),
        "latency_warm_ms": round(warm_ms, 1),
        "engine_latency_ms": corr.get("engine_latency_ms"),
        "expected_root": expected_root,
        "top5": ranked[:5],
        "hit1": bool(ranked and ranked[0] == expected_root),
        "hit3": expected_root in ranked[:3],
    }
