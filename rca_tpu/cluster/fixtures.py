"""Canonical faulted test worlds.

``five_service_world`` reproduces the *behavioral* content of the reference's
hermetic fixture (reference: utils/mock_k8s_client.py — database pod in
CrashLoopBackOff :154-163, api-gateway Failed on a missing env var :188,
backend CPU-hot with throttling events, resource-service near its memory
limit, a network policy whose ``from`` selector matches a nonexistent app
:617, services with empty endpoints for the broken pods :677-689, two HPAs
one of which has desired>current replicas :779-792, and canned trace
latency/error data :1146-1303) — built programmatically from the
:mod:`rca_tpu.cluster.world` builders rather than literal dicts.
"""

from __future__ import annotations

from rca_tpu.cluster.world import (
    World,
    container_spec,
    make_configmap,
    make_deployment,
    make_endpoints,
    make_event,
    make_hpa,
    make_ingress,
    make_network_policy,
    make_node,
    make_pod,
    make_secret,
    make_service,
    pod_metric,
    running_status,
    terminated_status,
    waiting_status,
)

NS = "test-microservices"

SERVICES = ["frontend", "backend", "database", "api-gateway", "resource-service"]

# service -> list of services it depends on (frontend -> api-gateway -> backend
# -> database; resource-service standalone consumer of backend)
DEPENDENCIES = {
    "frontend": ["api-gateway"],
    "api-gateway": ["backend"],
    "backend": ["database"],
    "resource-service": ["backend"],
}


def five_service_world() -> World:
    w = World(cluster_name="rca-test-cluster")
    w.nodes = [make_node("node-0"), make_node("node-1")]
    w.node_metrics = {
        "node-0": {"cpu": {"usage_percentage": 62}, "memory": {"usage_percentage": 70}},
        "node-1": {"cpu": {"usage_percentage": 45}, "memory": {"usage_percentage": 58}},
    }

    pods = {}

    def add_pod(pod):
        pods[pod["metadata"]["name"]] = pod
        w.add("pods", NS, pod)
        return pod

    # frontend: two healthy replicas
    for i, suffix in enumerate(["jk2x5", "p9x2q"]):
        add_pod(make_pod(f"frontend-7d8f675c7b-{suffix}", NS, "frontend"))

    # backend: healthy but CPU-hot (spin loop)
    be_env = [{"name": "DATABASE_URL", "value": f"http://database.{NS}.svc.cluster.local:5432"}]
    add_pod(
        make_pod(
            "backend-5b6d8f9c7d-2zf8g",
            NS,
            "backend",
            containers=[
                container_spec(
                    "backend",
                    requests={"cpu": "100m", "memory": "64Mi"},
                    limits={"cpu": "200m", "memory": "128Mi"},
                    env=be_env,
                )
            ],
        )
    )

    # database: CrashLoopBackOff with restart loop
    add_pod(
        make_pod(
            "database-7c9f8b6d5e-3x5qp",
            NS,
            "database",
            phase="Running",
            container_statuses=[
                waiting_status(
                    "database",
                    "CrashLoopBackOff",
                    "Back-off restarting failed container",
                    restarts=5,
                    last_exit_code=1,
                )
            ],
        )
    )

    # api-gateway: Failed, missing required env var
    gw_env = [{"name": "BACKEND_URL", "value": f"http://backend.{NS}.svc.cluster.local:8080"}]
    add_pod(
        make_pod(
            "api-gateway-6b7c8d9e5f-4q3zx",
            NS,
            "api-gateway",
            phase="Failed",
            containers=[
                container_spec(
                    "api-gateway",
                    requests={"cpu": "50m", "memory": "64Mi"},
                    limits={"cpu": "100m", "memory": "128Mi"},
                    env=gw_env,
                    env_from=[{"secretRef": {"name": "api-gateway-secrets"}}],
                )
            ],
            container_statuses=[
                terminated_status(
                    "api-gateway",
                    exit_code=1,
                    message="Missing required environment variable",
                    restarts=3,
                )
            ],
        )
    )

    # resource-service: running but memory near limit
    add_pod(
        make_pod(
            "resource-service-9d8e7f6c5b-1r5wq",
            NS,
            "resource-service",
            containers=[
                container_spec(
                    "resource-service",
                    requests={"cpu": "50m", "memory": "64Mi"},
                    limits={"cpu": "100m", "memory": "128Mi"},
                    volume_mounts=[{"name": "scratch", "mountPath": "/scratch"}],
                )
            ],
            volumes=[{"name": "scratch", "emptyDir": {"medium": "Memory"}}],
        )
    )

    # Deployments (api-gateway and database show ready shortfalls)
    for svc in SERVICES:
        replicas = 2 if svc == "frontend" else 1
        ready = replicas
        if svc in ("database", "api-gateway"):
            ready = 0
        w.add("deployments", NS, make_deployment(svc, NS, svc, replicas, ready))

    # Services + endpoints (broken services have no ready endpoints)
    for svc in SERVICES:
        w.add("services", NS, make_service(svc, NS))
        healthy_pods = [
            name
            for name, pod in pods.items()
            if pod["metadata"]["labels"]["app"] == svc
            and pod["status"]["phase"] == "Running"
            and all(
                cs.get("ready")
                for cs in pod["status"].get("containerStatuses", [])
            )
        ]
        w.add("endpoints", NS, make_endpoints(svc, NS, healthy_pods))

    # Config objects referenced (and one missing reference for the topology
    # agent to flag): api-gateway envFrom a secret that does not exist.
    w.add("configmaps", NS, make_configmap("frontend-config", NS, {"nginx.conf": "server {}"}))
    w.add("secrets", NS, make_secret("database-credentials", NS, ["password"]))
    w.add("ingresses", NS, make_ingress("frontend-ingress", NS, "app.example.com", "frontend"))

    # Network policy with a 'from' selector matching a nonexistent app
    w.add(
        "network_policies",
        NS,
        make_network_policy(
            "backend-network-policy", NS, {"app": "backend"},
            ingress_from_app="non-existent-service",
        ),
    )

    # HPAs: backend healthy-ish; api-gateway desired > current under low CPU
    w.add("hpas", NS, make_hpa("backend-hpa", NS, "backend", 1, 5, 1, 1, current_cpu_pct=85))
    w.add("hpas", NS, make_hpa("api-gateway-hpa", NS, "api-gateway", 1, 3, 1, 2, current_cpu_pct=40))

    # Events
    w.events[NS] = [
        make_event(NS, "Pod", "database-7c9f8b6d5e-3x5qp", "BackOff",
                   "Back-off restarting failed container database in pod "
                   "database-7c9f8b6d5e-3x5qp", count=5),
        make_event(NS, "Pod", "api-gateway-6b7c8d9e5f-4q3zx", "Failed",
                   "Error: Missing required environment variable", count=3),
        make_event(NS, "Pod", "backend-5b6d8f9c7d-2zf8g", "CPUThrottling",
                   "Container backend CPU throttled", count=10),
        make_event(NS, "Pod", "resource-service-9d8e7f6c5b-1r5wq", "MemoryHigh",
                   "Container resource-service memory usage high (89.8%)", count=2),
    ]

    # Logs (patterns chosen to trip the log agent's regex classes)
    w.logs[NS] = {
        "frontend-7d8f675c7b-jk2x5": {"frontend": _info_log("nginx serving requests")},
        "frontend-7d8f675c7b-p9x2q": {"frontend": _info_log("nginx serving requests")},
        "backend-5b6d8f9c7d-2zf8g": {"backend": _info_log("computing batch")},
        "database-7c9f8b6d5e-3x5qp": {
            "database": (
                "INFO: Starting database...\n"
                "ERROR: Database initialization failed\n"
                "FATAL: could not open relation mapping file\n"
                "INFO: Starting database...\n"
                "ERROR: Database initialization failed\n"
            )
        },
        "api-gateway-6b7c8d9e5f-4q3zx": {
            "api-gateway": (
                "INFO: API Gateway starting...\n"
                "ERROR: Missing required environment variable\n"
            )
        },
        "resource-service-9d8e7f6c5b-1r5wq": {
            "resource-service": (
                "INFO: Allocating memory resources\n"
                "WARN: Memory usage high\n"
                "WARN: Memory usage approaching limit\n"
            )
        },
    }
    w.previous_logs[NS] = {
        "database-7c9f8b6d5e-3x5qp": {
            "database": "ERROR: Database initialization failed\nexit status 1\n"
        }
    }

    # Metrics: backend at 95% CPU, resource-service at 90% memory
    w.pod_metrics[NS] = {
        "pods": {
            "frontend-7d8f675c7b-jk2x5": pod_metric(40, 48, 200, 128, "frontend"),
            "frontend-7d8f675c7b-p9x2q": pod_metric(38, 50, 200, 128, "frontend"),
            "backend-5b6d8f9c7d-2zf8g": pod_metric(190, 70, 200, 128, "backend"),
            "database-7c9f8b6d5e-3x5qp": pod_metric(5, 20, 100, 128, "database"),
            "resource-service-9d8e7f6c5b-1r5wq": pod_metric(45, 115, 100, 128, "resource-service"),
        }
    }

    # Traces: canned latency/error-rate/dependency data
    w.traces = {
        "trace_ids": {NS: [f"trace-{i:04d}" for i in range(10)]},
        "traces": {
            "trace-0000": {
                "trace_id": "trace-0000",
                "spans": [
                    {"service": "frontend", "operation": "GET /", "duration_ms": 120},
                    {"service": "api-gateway", "operation": "route", "duration_ms": 95},
                    {"service": "backend", "operation": "compute", "duration_ms": 1450},
                    {"service": "database", "operation": "query", "duration_ms": 0,
                     "error": "connection refused"},
                ],
            }
        },
        "latency": {
            NS: {
                "frontend": {"p50": 120, "p95": 300, "p99": 500},
                "api-gateway": {"p50": 95, "p95": 400, "p99": 900},
                "backend": {"p50": 500, "p95": 1450, "p99": 2000},
                "database": {"p50": 100, "p95": 200, "p99": 400},
                "resource-service": {"p50": 150, "p95": 350, "p99": 600},
            }
        },
        "error_rates": {
            NS: {
                "frontend": 0.01,
                "api-gateway": 0.25,
                "backend": 0.05,
                "database": 0.15,
                "resource-service": 0.02,
            }
        },
        "dependencies": {NS: {k: list(v) for k, v in DEPENDENCIES.items()}},
        "slow_ops": {
            NS: [
                {"service": "backend", "operation": "compute", "duration_ms": 1450},
                {"service": "api-gateway", "operation": "route", "duration_ms": 900},
            ]
        },
    }

    w.ground_truth = {
        "namespace": NS,
        "fault_roots": ["database", "api-gateway"],
        "faults": {
            "database": "CrashLoopBackOff restart loop (exit 1)",
            "api-gateway": "Failed: missing required environment variable",
            "backend": "CPU saturation (spin loop)",
            "resource-service": "memory near limit",
        },
    }
    return w


def _info_log(line: str) -> str:
    return "\n".join(f"INFO: {line} #{i}" for i in range(5)) + "\n"
