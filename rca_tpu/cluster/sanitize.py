"""Defensive normalization of raw Kubernetes objects at the snapshot boundary.

Live clusters produce objects this codebase's consumers must not have to
defend against one key at a time: a `metadata: null` from a partial
serialization, containers without a `name`, a `status` stripped by RBAC
field selectors.  The reference crashed on exactly this class of input —
its archived evidence files record AttributeErrors from malformed objects
(reference: logs/archive/20250419_190111_* per SURVEY.md §2.6) and every
agent re-implemented (or forgot) its own guards.

One pass here means every consumer downstream — feature extractor, graph
builder, all six agents, log prioritization — can rely on the invariants:

- keys that hold OBJECTS are dicts (never None): metadata, spec, status, …
- keys that hold COLLECTIONS are lists (never None): containers,
  containerStatuses, conditions, env, subsets, …
- metadata.name exists (possibly ""), metadata.labels is a dict
- containers/containerStatuses entries have a "name"

Unknown keys pass through untouched; nothing is dropped.
"""

from __future__ import annotations

from typing import Any, List

# keys whose value must be a dict when present
_DICT_KEYS = frozenset({
    "metadata", "spec", "status", "labels", "annotations", "selector",
    "matchLabels", "template", "involvedObject", "source", "resources",
    "requests", "limits", "state", "lastState", "waiting", "running",
    "terminated", "securityContext", "configMapRef", "secretRef",
    "configMapKeyRef", "secretKeyRef", "valueFrom", "configMap", "secret",
    "emptyDir", "backend", "service", "http", "scaleTargetRef", "podSelector",
    "namespaceSelector", "capacity", "allocatable", "nodeInfo", "hard",
    "used",
})

# keys whose value must be a list when present
_LIST_KEYS = frozenset({
    "containers", "initContainers", "containerStatuses",
    "initContainerStatuses", "conditions", "env", "envFrom", "volumes",
    "volumeMounts", "subsets", "addresses", "notReadyAddresses", "ports",
    "rules", "paths", "ingress", "egress", "from", "to", "items",
    "ownerReferences", "accessModes",
})

# list entries under these keys must each carry a "name"
_NAMED_LIST_KEYS = frozenset({
    "containers", "initContainers", "containerStatuses",
    "initContainerStatuses", "env",
})

# label-style maps: every value must be a string (selector matching and
# text scans concatenate/startswith them)
_STR_MAP_KEYS = frozenset({
    "labels", "annotations", "matchLabels", "nodeSelector",
})

# scalar keys: a present-but-null value is coerced to the type consumers
# compare/concatenate with (None > 0 and "".join([None]) were the two
# biggest crash classes in the structure-fuzz probe)
_INT_KEYS = frozenset({
    "restartCount", "replicas", "readyReplicas", "availableReplicas",
    "updatedReplicas", "currentReplicas", "desiredReplicas", "minReplicas",
    "maxReplicas", "exitCode", "count", "observedGeneration",
    "numberReady", "desiredNumberScheduled", "currentNumberScheduled",
})
_STR_KEYS = frozenset({
    "phase", "reason", "message", "type", "kind", "namespace", "fieldPath",
    "host", "image", "apiVersion", "component", "firstTimestamp",
    "lastTimestamp", "creationTimestamp", "startedAt", "finishedAt",
})


def _empty_metadata() -> dict:
    """A fresh metadata satisfying the module invariant (name + labels) —
    the single Python-side spelling; sanitizec.c's empty_metadata() is its
    twin."""
    return {"name": "", "labels": {}}


def sanitize_object(obj: Any, parent_key: str = "") -> Any:
    """Recursively normalize one K8s object (see module docstring).

    Copy-on-write: well-formed subtrees (the overwhelmingly common case)
    are returned AS-IS with zero allocations — this runs over every object
    of every snapshot, including the 1 Hz live-streaming captures, where a
    rebuild-everything version measured ~1.6 s at 10k pods."""
    if obj is None:
        if parent_key == "metadata":
            return _empty_metadata()
        if parent_key in _DICT_KEYS:
            return {}
        if parent_key in _LIST_KEYS:
            return []
        return None
    cls = obj.__class__
    if cls is dict:
        if parent_key in _STR_MAP_KEYS:
            if all(
                type(k) is str and type(v) is str for k, v in obj.items()
            ):
                return obj
            return {
                str(k): ("" if v is None else str(v))
                for k, v in obj.items()
            }
        out = None  # allocated only when something changes
        for k, v in obj.items():
            # "status" is a DICT at object top level (pod.status) but a
            # STRING inside condition entries ({type, status: "True"});
            # strip the key context there so neither the None branch nor
            # the dict coercion below wipes a legitimate string
            child_key = (
                "" if (parent_key == "conditions" and k == "status") else k
            )
            nv = sanitize_object(v, child_key)
            if nv is None:
                if child_key in _INT_KEYS:
                    nv = 0
                elif child_key in _STR_KEYS:
                    nv = ""
            elif child_key in _DICT_KEYS and nv.__class__ is not dict:
                # same repair as the None branch: a replaced metadata must
                # still satisfy the name/labels invariant
                nv = _empty_metadata() if child_key == "metadata" else {}
            elif child_key in _LIST_KEYS and nv.__class__ is not list:
                nv = []
            if nv is not v:
                if out is None:
                    out = dict(obj)
                out[k] = nv
        result = out if out is not None else obj
        if parent_key == "metadata":
            name = result.get("name")
            labels = result.get("labels")
            # a missing name reads as None -> the same repair branch
            if type(name) is not str or type(labels) is not dict:
                if result is obj:
                    result = dict(obj)
                result["name"] = (
                    name if type(name) is str else str(name or "")
                )
                if type(labels) is not dict:
                    result["labels"] = {}
        return result
    if cls is list:
        named = parent_key in _NAMED_LIST_KEYS
        is_env = parent_key == "env"
        obj_entries = parent_key in _LIST_KEYS and parent_key != "accessModes"
        out = None
        for i, v in enumerate(obj):
            if v is None and obj_entries:
                # a null ELEMENT of an object list becomes an empty object,
                # not a nested [] (the parent_key-recursion trap) — the
                # named-list pass below then gives it a "" name
                nv = {}
            else:
                nv = sanitize_object(v, parent_key)
            if nv.__class__ is dict:
                if named and type(nv.get("name")) is not str:
                    nv = {**nv, "name": str(nv.get("name") or "")}
                if is_env and not nv.get("valueFrom") \
                        and nv.get("value") is None:
                    nv = {**nv, "value": ""}
            if nv is not v:
                if out is None:
                    out = list(obj)
                out[i] = nv
        return out if out is not None else obj
    return obj


def _native_sanitize():
    """The C extension twin (rca_tpu/native/sanitizec.c), or None.  Same
    walk in C: ~20x faster on the 1.2M-node sanitize of a 10k-pod
    snapshot.  Exact parity is enforced by tests/test_native.py; the
    Python implementation above is the spec."""
    from rca_tpu.native import load_sanitize

    return load_sanitize()


def sanitize_objects(items: List[dict]) -> List[dict]:
    """Normalize a collection; drops entries that are not dicts at all."""
    native = _native_sanitize()
    san = native.sanitize_object if native is not None else sanitize_object
    out = []
    for item in items or []:
        if not isinstance(item, dict):
            continue
        clean = san(item)
        # every top-level object gets a metadata dict with a name
        md = clean.get("metadata")
        if not isinstance(md, dict):
            clean = dict(clean) if clean is item else clean
            clean["metadata"] = _empty_metadata()
        elif "name" not in md or not isinstance(md.get("labels"), dict):
            clean = dict(clean) if clean is item else clean
            md = dict(md)
            md.setdefault("name", "")
            if not isinstance(md.get("labels"), dict):
                md["labels"] = {}
            clean["metadata"] = md
        out.append(clean)
    return out
